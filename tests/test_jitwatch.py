"""Jit retrace/compile watchdog (cake_tpu/obs/jitwatch.py).

Pins the runtime complement of the static jit lints: tracked functions count
exactly one trace per signature, rebuilt wrappers recompiling an old
signature are flagged, the armed watchdog turns ANY steady-state trace into a
counter + flight event (+ a raise under CAKE_RETRACE_FATAL=1), and — the PR 4
promise, now a tier-1 invariant — steady-state paged lockstep decode performs
ZERO retraces after warmup, with page growth, release, and a same-shape
second request all hitting the compiled entry.
"""

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.obs import jitwatch
from cake_tpu.runtime.serving import BatchEngine, ServeConfig
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def wait_epochs_closed(n: int, timeout: float = 10.0) -> None:
    """Block until n epoch spans have CLOSED on the timeline — i.e. the
    engine fully drained them. Submitting the steady-state request before
    the warm epoch exits would continuous-batching-JOIN it (a different,
    legitimately cold code path) instead of starting a same-shape epoch."""
    import time

    from cake_tpu.obs.timeline import timeline

    deadline = time.time() + timeout
    while time.time() < deadline:
        done = sum(1 for e in timeline.snapshot() if e["name"] == "epoch")
        if done >= n:
            return
        time.sleep(0.01)
    raise AssertionError(f"epoch {n} never closed")


def retrace_events():
    return [
        e for e in metrics.flight.snapshot() if e["event"] == "jit-retrace"
    ]


# ------------------------------------------------------------- tracked_jit


def test_one_trace_per_signature():
    f = jitwatch.tracked_jit(lambda x: x * 2, name="t.double")
    f(jnp.ones(3))
    f(jnp.ones(3))
    f(jnp.ones(3))
    assert jitwatch.watch.trace_count("t.double") == 1
    f(jnp.ones(5))  # new shape: a legitimate new compile, not a retrace
    assert jitwatch.watch.trace_count("t.double") == 2
    assert jitwatch.retrace_total() == 0
    assert (
        metrics.registry.counter("cake_jit_traces_total").value(fn="t.double")
        == 2
    )
    snap = jitwatch.snapshot()["t.double"]
    assert snap["traces"] == 2 and snap["retraces"] == 0
    assert snap["compile_s"] > 0  # the tracing calls were wall-timed


def test_rebuilt_wrapper_same_signature_is_a_retrace():
    """An evicted-and-rebuilt wrapper recompiling the SAME program is the
    waste the watchdog exists to surface (lru churn, jit-in-loop bugs)."""
    for _ in range(2):
        # The in-loop rebuild IS the defect under test (the runtime watchdog
        # catching what the static rule catches at review time).
        f = jitwatch.tracked_jit(  # cake-lint: disable=jit-in-hot-loop
            lambda x: x + 1, name="t.rebuilt"
        )
        f(jnp.ones(4))
    assert jitwatch.watch.trace_count("t.rebuilt") == 2
    assert jitwatch.retrace_total() == 1
    events = retrace_events()
    assert events and events[0]["fn"] == "t.rebuilt"
    assert events[0]["reason"] == "duplicate-signature"


def test_armed_watchdog_flags_any_trace_and_fatal_raises(monkeypatch):
    f = jitwatch.tracked_jit(lambda x: x - 1, name="t.armed")
    f(jnp.ones(2))  # warmup
    with jitwatch.expect_no_retrace():
        f(jnp.ones(2))  # cache hit: no trace, no complaint
        assert jitwatch.retrace_total() == 0
        f(jnp.ones(7))  # traces while armed -> retrace (non-fatal: counted)
        assert jitwatch.retrace_total() == 1
        assert retrace_events()[0]["reason"] == "armed"
        monkeypatch.setenv("CAKE_RETRACE_FATAL", "1")
        with pytest.raises(jitwatch.RetraceError):
            f(jnp.ones(9))
    assert not jitwatch.watch.armed  # context manager disarms


# ----------------------------------------------- paged decode: no retraces


def setup_engine(serve=None, **kw):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(11), jnp.float32)
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    serve = serve or ServeConfig(
        max_batch=4, decode_chunk_size=4, admission_window=0.03,
        kv_mode="paged", page_size=16,
    )
    eng = BatchEngine(cfg, params, ByteTokenizer(), serve=serve, **kw)
    eng.start()
    return eng


def test_paged_steady_state_zero_retraces_fatal(monkeypatch):
    """Tier-1 pin of the PR 4 claim: after one warmup request, a second
    same-shape request — prefill, decode chunks, page growth at boundaries,
    release on finish — performs ZERO jit traces, enforced in FATAL mode
    (any retrace raises inside the engine and fails the stream)."""
    eng = setup_engine()
    try:
        prompt = "steady state prompt!"
        # Warmup: compiles paged prefill + every decode-chunk variant this
        # shape sequence needs (24 tokens cross page boundaries of 16).
        h = eng.submit([Message.user(prompt)], 24, GREEDY)
        warm = [t.id for t in h.tokens()]
        assert len(warm) >= 1
        wait_epochs_closed(1)
        monkeypatch.setenv("CAKE_RETRACE_FATAL", "1")
        with jitwatch.expect_no_retrace():
            h2 = eng.submit([Message.user(prompt)], 24, GREEDY)
            again = [t.id for t in h2.tokens()]  # a raise lands here
        assert again == warm  # greedy, same seed: bit-identical
        assert jitwatch.retrace_total() == 0
        assert retrace_events() == []
    finally:
        monkeypatch.delenv("CAKE_RETRACE_FATAL", raising=False)
        eng.stop()


def test_paged_decode_block_table_growth_never_retraces(monkeypatch):
    """Direct backend-level pin: growing a lane's block table between decode
    chunks (the _extend_pages protocol) changes only the VALUES of a traced
    operand — same compiled entry, zero traces, fatal-armed."""
    from cake_tpu.runtime.batch_backend import PagedLocalBackend

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(12), jnp.float32)
    backend = PagedLocalBackend(
        cfg, params, max_seq_len=128, cache_dtype=jnp.float32, page_size=16,
    )
    kv = backend.init_kv(2)
    alloc = backend.allocator
    for lane in range(2):
        alloc.map_range(lane, 0, 16)
    b = 2
    tok = jnp.zeros((b,), jnp.int32)
    pads = jnp.zeros((b,), jnp.int32)
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(b)])
    ring = jnp.full((b, 0), -1, jnp.int32)
    ring_idx = jnp.zeros((b,), jnp.int32)
    s = GREEDY

    toks, kv, keys, ring, ring_idx = backend.decode(
        kv, tok, 12, pads, keys, ring, ring_idx, 4, s
    )  # warmup compile
    monkeypatch.setenv("CAKE_RETRACE_FATAL", "1")
    try:
        with jitwatch.expect_no_retrace():
            slot = 16
            for _ in range(3):
                for lane in range(2):
                    alloc.map_range(lane, slot, slot + 4)  # page growth
                toks, kv, keys, ring, ring_idx = backend.decode(
                    kv, toks[:, -1], slot, pads, keys, ring, ring_idx, 4, s
                )
                slot += 4
            alloc.release(1)  # release mid-epoch: table row -> UNMAPPED
            for lane in (0,):
                alloc.map_range(lane, slot, slot + 4)
            backend.decode(
                kv, toks[:, -1], slot, pads, keys, ring, ring_idx, 4, s
            )
        assert jitwatch.retrace_total() == 0
    finally:
        monkeypatch.delenv("CAKE_RETRACE_FATAL", raising=False)


def test_forced_shape_change_counts_retrace_with_event():
    """The watchdog's positive case: a genuinely new shape after warmup is
    counted and lands a flight-recorder event (non-fatal mode degrades to
    telemetry, never to a failed request)."""
    eng = setup_engine()
    try:
        h = eng.submit([Message.user("short")], 6, GREEDY)
        assert len([t for t in h.tokens()]) >= 1
        wait_epochs_closed(1)
        with jitwatch.expect_no_retrace():
            # 4x longer prompt: a different prefill bucket MUST trace.
            h2 = eng.submit(
                [Message.user("a much longer prompt " * 8)], 6, GREEDY
            )
            out = [t for t in h2.tokens()]
        assert len(out) >= 1  # stream completed despite the flagged trace
        assert jitwatch.retrace_total() >= 1
        events = retrace_events()
        assert events and all(e["reason"] == "armed" for e in events)
        assert (
            metrics.registry.counter("cake_jit_retraces_total").value(
                fn=events[0]["fn"]
            )
            >= 1
        )
    finally:
        eng.stop()


# ------------------------------------------------------------- compile tap


def test_compile_listener_accumulates():
    assert jitwatch.install_compile_listener()  # idempotent
    assert jitwatch.install_compile_listener()
    n0, s0 = jitwatch.compile_totals()
    jax.jit(lambda x: x * 3 + 1)(jnp.ones(8)).block_until_ready()
    n1, s1 = jitwatch.compile_totals()
    assert n1 > n0 and s1 > s0
