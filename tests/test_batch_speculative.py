"""Batched speculative decoding in the serving engine (runtime/serving.py).

Contracts under test: greedy engine streams with speculation are BYTE-
IDENTICAL to the engine without it (draft quality affects speed only);
sampled streams are deterministic per seed and the acceptance machinery is
the single-row rejection rule vmapped (distribution exactness inherits from
tests/test_speculative.py); the shared min-advance keeps every lockstep
invariant (verified against plain decode after a speculative round).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import layout_prompts, seed_rings, first_sample
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.batch_backend import LocalBatchBackend
from cake_tpu.runtime.serving import BatchEngine

MAX_SEQ = 128


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(41), jnp.float32)
    return cfg, params


def _engine(model, speculative_k, **kw):
    cfg, params = model
    kw.setdefault("decode_chunk_size", 4)
    return BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32, max_batch=4,
        admission_window=0.05, speculative_k=speculative_k, **kw,
    )


def _run(eng, prompts, max_tokens, s):
    eng.start()
    try:
        handles = [eng.submit([Message.user(p)], max_tokens, s) for p in prompts]
        return [[t.id for t in h.tokens()] for h in handles]
    finally:
        eng.stop()


# Repetitive prompts: prompt lookup drafts verify at high rates on these.
PROMPTS = [
    "abc abc abc abc abc abc",
    "xyzw xyzw xyzw xyzw xyzw",
    "q1 q1 q1 q1 q1 q1 q1",
]


def test_greedy_streams_byte_identical(model):
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    plain = _run(_engine(model, 0), PROMPTS, 16, s)
    spec_eng = _engine(model, 4)
    spec = _run(spec_eng, PROMPTS, 16, s)
    assert spec == plain
    # Rounds really ran (cross-row MIN acceptance on a random-weight model
    # is usually 1, so only count rounds here; the single-row test below
    # pins multi-token acceptance).
    assert spec_eng.stats["spec_rounds"] > 0
    assert spec_eng.stats["spec_tokens"] >= spec_eng.stats["spec_rounds"]


def test_single_row_accepts_drafts(model):
    """One live row (dead dummy lanes excluded from the min): its own
    prompt-lookup drafts must verify and the round advance must exceed one
    token per round.

    decode_chunk_size=1 so a speculative round is ATTEMPTED at every slot:
    a random-weight greedy stream is only quasi-periodic, so the slots where
    a lookup draft actually matches the true continuation are sparse, and a
    draft-less fallback chunk of 4 skips right over them (rounds then only
    ever land on mispredicting slots and spec_tokens == spec_rounds — the
    verify corrections were byte-exact all along, which `spec == plain`
    still pins). Chunk size affects only where rounds land, never the
    stream."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    eng = _engine(model, 4, decode_chunk_size=1)
    plain = _run(_engine(model, 0), PROMPTS[:1], 24, s)
    spec = _run(eng, PROMPTS[:1], 24, s)
    assert spec == plain
    assert eng.stats["spec_rounds"] > 0
    assert eng.stats["spec_tokens"] > eng.stats["spec_rounds"]


def test_sampled_streams_deterministic(model):
    """temperature > 0: distribution exactness is pinned at the acceptance-
    rule level (test_speculative.py, vmapped unchanged); here pin that the
    engine path is deterministic per seed and actually speculates."""
    s = SamplingConfig(
        temperature=0.9, top_k=12, repeat_penalty=1.0, seed=7
    )
    a = _run(_engine(model, 4), PROMPTS, 12, s)
    b = _run(_engine(model, 4), PROMPTS, 12, s)
    assert a == b
    # (High-temperature streams on random weights rarely repeat, so rounds
    # may not engage here; the backend-level test below pins the sampled
    # acceptance machinery itself.)


def test_backend_sampled_acceptance_near_greedy(model):
    """verify_sampled at near-zero temperature with the greedy continuation
    as drafts: the target is a near-point-mass on the greedy token, so every
    real draft must accept and the bonus must be the greedy bonus — the
    vmapped rejection rule agreeing with the greedy oracle row for row."""
    cfg, params = model
    be = LocalBatchBackend(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    s0 = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    ids_list = [[5, 9, 5, 9], [3, 3, 3]]
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    keys0 = jax.random.split(jax.random.PRNGKey(9), 2)

    kv = be.init_kv(2)
    logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
    ring, ridx = seed_rings(ids_list, 0)
    first, keys, ring, ridx = first_sample(logits, s0, ring, ridx, keys0)
    toks, kv, keys, *_ = be.decode(
        kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
        jnp.asarray(ring), jnp.asarray(ridx), 4, s0,
    )
    oracle = np.concatenate([np.asarray(first)[:, None], np.asarray(toks)], 1)

    kv2 = be.init_kv(2)
    logits, kv2 = be.prefill(jnp.asarray(tokens), kv2, jnp.asarray(pads))
    first2, keys2, *_ = first_sample(logits, s0, *seed_rings(ids_list, 0), keys0)
    K = 3
    drafts = oracle[:, 1 : 1 + K]
    chunk = np.concatenate([oracle[:, :1], drafts], axis=1)
    s_near = SamplingConfig(temperature=1e-3, repeat_penalty=1.0)
    n_accs, nxts, kv2, keys2 = be.verify_sampled(
        kv2, chunk, bucket, jnp.asarray(pads), drafts,
        np.full((2,), K, np.int32), jax.random.split(jax.random.PRNGKey(1), 2),
        s_near,
    )
    np.testing.assert_array_equal(np.asarray(n_accs), [K, K])
    np.testing.assert_array_equal(np.asarray(nxts), oracle[:, K + 1])


def test_repeat_penalty_disables_speculation(model):
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.2)
    eng = _engine(model, 4)
    plain = _run(_engine(model, 0), PROMPTS[:1], 8, s)
    spec = _run(eng, PROMPTS[:1], 8, s)
    assert spec == plain
    assert eng.stats["spec_rounds"] == 0


def test_spec_composes_with_join(model):
    """A request joining mid-epoch must still match its solo greedy stream
    while the epoch runs speculative rounds."""
    import threading
    import time

    cfg, params = model
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    solo = _run(_engine(model, 0), ["join me join me join me"], 6, s)[0]

    eng = _engine(model, 4)
    eng.start()
    try:
        h0 = eng.submit([Message.user(PROMPTS[0])], 20, s)
        it0 = h0.tokens()
        next(it0)  # epoch live
        h1 = eng.submit([Message.user("join me join me join me")], 6, s)
        ids1 = [t.id for t in h1.tokens()]
        _ = list(it0)
    finally:
        eng.stop()
    assert ids1 == solo
    assert eng.stats["joins"] == 1


def test_engine_over_tp_speculative_matches_local(model):
    """TPBatchBackend grows verify ops: the engine over a tp=2 mesh with
    speculation emits the same greedy streams as the plain local engine."""
    from cake_tpu.runtime.batch_backend import TPBatchBackend

    cfg, params = model
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    plain = _run(_engine(model, 0), PROMPTS[:2], 16, s)
    tp_backend = TPBatchBackend(
        cfg, params, tp=2, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    eng = BatchEngine(
        cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=4,
        admission_window=0.05, speculative_k=4, backend=tp_backend,
    )
    spec = _run(eng, PROMPTS[:2], 16, s)
    assert spec == plain
    assert eng.stats["spec_rounds"] > 0


def test_min_advance_against_backend_oracle(model):
    """Layout invariant after a speculative round: decode picks up exactly
    where the verify left off — compare a verify-round-then-decode against
    plain decode from the same state (greedy: streams must agree wherever
    the accepted prefix reached)."""
    cfg, params = model
    be = LocalBatchBackend(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    ids_list = [[5, 9, 5, 9, 5, 9], [3, 3, 3, 3]]
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    keys0 = jax.random.split(jax.random.PRNGKey(3), 2)

    # Oracle: plain chunked decode, 6 tokens.
    kv = be.init_kv(2)
    logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
    ring, ridx = seed_rings(ids_list, 0)
    first, keys, ring, ridx = first_sample(logits, s, ring, ridx, keys0)
    toks, kv, keys, *_ = be.decode(
        kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
        jnp.asarray(ring), jnp.asarray(ridx), 6, s,
    )
    oracle = np.concatenate([np.asarray(first)[:, None], np.asarray(toks)], 1)

    # Speculative: one verify round with the ORACLE's continuation as drafts
    # (perfect drafts -> full acceptance), then decode the rest.
    kv2 = be.init_kv(2)
    logits, kv2 = be.prefill(jnp.asarray(tokens), kv2, jnp.asarray(pads))
    first2, keys2, ring, ridx = first_sample(
        logits, s, seed_rings(ids_list, 0)[0], seed_rings(ids_list, 0)[1], keys0
    )
    np.testing.assert_array_equal(np.asarray(first2), oracle[:, 0])
    K = 3
    drafts = oracle[:, 1 : 1 + K]
    chunk = np.concatenate([oracle[:, :1], drafts], axis=1)
    ids, kv2 = be.verify_greedy(kv2, chunk, bucket, jnp.asarray(pads))
    ids = np.asarray(ids)
    # Perfect drafts: every draft position's argmax equals the draft.
    np.testing.assert_array_equal(ids[:, :K], drafts)
    # Advance by K+1 (all accepted + bonus) and decode 2 more plain tokens.
    bonus = ids[:, K]
    np.testing.assert_array_equal(bonus, oracle[:, K + 1])
    toks2, kv2, keys2, *_ = be.decode(
        kv2, jnp.asarray(bonus), bucket + K + 1, jnp.asarray(pads), keys2,
        jnp.asarray(seed_rings(ids_list, 0)[0]),
        jnp.asarray(seed_rings(ids_list, 0)[1]), 2, s,
    )
    np.testing.assert_array_equal(
        np.asarray(toks2), oracle[:, K + 2 : K + 4]
    )


def test_engine_over_pipeline_speculative_matches_local(model):
    """PipelineBatchBackend verify ops: the engine over a 3-stage mesh with
    speculation emits the same greedy streams as the plain local engine
    (composes with the 1F1B decode walk)."""
    from cake_tpu.runtime.batch_backend import PipelineBatchBackend

    cfg, params = model
    if jax.device_count() < 3:
        pytest.skip("needs 3 devices")
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    plain = _run(_engine(model, 0), PROMPTS[:2], 16, s)
    backend = PipelineBatchBackend(
        cfg, params, [(0, 1), (1, 2), (2, 3)], max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    eng = BatchEngine(
        cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=4,
        admission_window=0.05, speculative_k=4, backend=backend,
    )
    spec = _run(eng, PROMPTS[:2], 16, s)
    assert spec == plain
    assert eng.stats["spec_rounds"] > 0
