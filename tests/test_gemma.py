"""Gemma and Gemma-2 families, pinned against HF transformers.

Gemma stresses every family knob at once: GeGLU activation, zero-centered
(1 + w) RMSNorm, sqrt(hidden) embedding scaling, explicit head_dim, tied
embeddings. Gemma-2 adds post-attention/post-MLP norms, attention and final
logit soft-capping, a score scale decoupled from head_dim
(query_pre_attn_scalar), and the ALTERNATING local/global sliding-window
pattern — carried as a per-layer "win_flag" in the layer tree so stages and
workers keep absolute layer parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.io.safetensors_io import load_params, save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message, encode_dialog_gemma
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import LocalForwardStep

MAX_SEQ = 96


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


def ours_greedy(model_dir, prompt_ids, n_steps):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    logits, kv = fwd(
        params, jnp.asarray([prompt_ids], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(prompt_ids)), cfg,
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


def make_gemma_checkpoint(tmp_path, seed=0):
    cfg = transformers.GemmaConfig(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        bos_token_id=256,
        eos_token_id=260,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.GemmaForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def make_gemma2_checkpoint(tmp_path, seed=0, sliding_window=8):
    cfg = transformers.Gemma2Config(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        query_pre_attn_scalar=32,  # != head_dim: the scale override matters
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        sliding_window=sliding_window,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        bos_token_id=256,
        eos_token_id=260,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.Gemma2ForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_gemma_config_parses(tmp_path):
    make_gemma_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "gemma"
    assert cfg.hidden_activation == "gelu_tanh"
    assert cfg.rmsnorm_offset
    assert cfg.embedding_scale == pytest.approx(8.0)  # sqrt(64)
    assert cfg.head_dim == 16
    assert cfg.tie_word_embeddings


def test_gemma_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_gemma_checkpoint(tmp_path, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_gemma_prefill_logits_match_transformers(tmp_path):
    hf_model = make_gemma_checkpoint(tmp_path, seed=2)
    prompt = [256, 11, 205, 499, 3, 3, 64]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )


def test_gemma2_config_parses(tmp_path):
    make_gemma2_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "gemma2"
    assert cfg.attn_logit_softcap == 50.0
    assert cfg.final_logit_softcap == 30.0
    assert cfg.query_pre_attn_scalar == 32
    assert cfg.post_block_norms and cfg.alt_sliding_window
    assert cfg.sliding_window == 8


def test_gemma2_greedy_and_alternating_window(tmp_path):
    """Greedy parity on a prompt much longer than the window: even layers are
    windowed, odd global — any parity slip or missing softcap shows here."""
    hf_model = make_gemma2_checkpoint(tmp_path, seed=3)
    rng = np.random.default_rng(0)
    prompt = [256] + [int(t) for t in rng.integers(0, 512, 39)]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_gemma2_prefill_logits_match_transformers(tmp_path):
    hf_model = make_gemma2_checkpoint(tmp_path, seed=4)
    rng = np.random.default_rng(1)
    prompt = [256] + [int(t) for t in rng.integers(0, 512, 30)]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    assert "win_flag" in params["layers"]
    assert params["layers"]["win_flag"].tolist() == [True, False, True, False]
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )


def test_gemma2_pipeline_preserves_layer_parity(tmp_path):
    """Ragged pipeline stages must keep the ABSOLUTE alternating-window
    parity (win_flag rides the layer tree through stage slicing)."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    make_gemma2_checkpoint(tmp_path, seed=5)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    rng = np.random.default_rng(2)
    tokens = np.asarray(
        [[256] + [int(t) for t in rng.integers(0, 512, 20)]], np.int32
    )

    def drive(step):
        n = tokens.shape[1]
        outs = [step(tokens, 0, n)]
        pos = n
        for _ in range(3):
            nxt = np.argmax(outs[-1], -1).astype(np.int32)[:, None]
            outs.append(step(nxt, pos, 1))
            pos += 1
        return np.stack(outs)

    local = LocalForwardStep(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    pipe = PipelineRunner(
        cfg, params, [(0, 1), (1, 4)], max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        drive(pipe), drive(local), atol=2e-4, rtol=2e-4
    )


def test_gemma2_worker_range_keeps_parity(tmp_path):
    """A worker loading layers [1, 3) gets win_flag [False, True] — absolute
    parity, not range-relative."""
    make_gemma2_checkpoint(tmp_path, seed=6)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    shard = load_params(tmp_path, cfg, jnp.float32, layer_range=(1, 3))
    assert shard["layers"]["win_flag"].tolist() == [False, True]


def test_gemma2_roundtrip_four_norms(tmp_path):
    cfg = LlamaConfig.tiny(
        model_type="gemma2", num_hidden_layers=2, sliding_window=8,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        query_pre_attn_scalar=32, post_block_norms=True,
        alt_sliding_window=True, hidden_activation="gelu_tanh",
        rmsnorm_offset=True, embedding_scale=8.0, tie_word_embeddings=True,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    save_tiny_checkpoint(tmp_path, params, cfg)
    loaded = load_params(tmp_path, cfg, jnp.float32)
    for k in ("ln_attn", "ln_mlp", "ln_post_attn", "ln_post_mlp"):
        np.testing.assert_array_equal(
            np.asarray(loaded["layers"][k]), np.asarray(params["layers"][k]), k
        )
    assert loaded["layers"]["win_flag"].tolist() == [True, False]


def test_gemma_template_text():
    msgs = [
        Message.system("Be kind."),
        Message.user("hi"),
        Message.assistant("hello"),
        Message.user("again"),
    ]
    assert encode_dialog_gemma(msgs) == (
        "<bos><start_of_turn>user\nBe kind.\n\nhi<end_of_turn>\n"
        "<start_of_turn>model\nhello<end_of_turn>\n"
        "<start_of_turn>user\nagain<end_of_turn>\n"
        "<start_of_turn>model\n"
    )
    with pytest.raises(ValueError):
        encode_dialog_gemma(
            [Message.user("a"), Message.system("late system")]
        )


def test_gemma2_tcp_workers_match_local(tmp_path):
    """TCP workers serving Gemma-2 ranges == local oracle: the win_flag
    parity, four norms, and softcaps all survive the wire path."""
    from cake_tpu.models.llama.generator import (
        LlamaGenerator,
        SamplingConfig,
    )
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    make_gemma2_checkpoint(tmp_path, seed=7)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    topo = Topology.from_dict(
        {"w1": {"host": "x", "layers": ["model.layers.1-2"]}}
    )
    w = Worker(
        "w1", tmp_path, topo, ("127.0.0.1", 0), dtype=jnp.float32,
        max_seq_len=MAX_SEQ,
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

        def run(step):
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
            gen.add_message(Message.user("g2 over tcp"))
            gen.generate(6)
            return gen.generated_token_ids

        ref = run(LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ,
                                   cache_dtype=jnp.float32))
        got = run(DistributedForwardStep(
            cfg, tmp_path, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ,
        ))
        assert got == ref
    finally:
        w.stop()
