"""Weight-only int8 quantization (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.ops.quant import (
    QuantWeight,
    dequantize_weight,
    qmat,
    quantize_params,
    quantize_weight,
    quantized_bytes,
)

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 128)) * 0.3, jnp.float32)
    qw = quantize_weight(w)
    assert qw.w.dtype == jnp.int8
    back = dequantize_weight(qw)
    # Symmetric per-channel absmax: error bounded by scale/2 per element.
    max_err = np.abs(np.asarray(back - w)).max()
    per_chan_bound = np.asarray(qw.scale).max() / 2 + 1e-7
    assert max_err <= per_chan_bound


def test_qmat_matches_dequantized_matmul():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    qw = quantize_weight(w)
    got = np.asarray(qmat(x, qw))
    want = np.asarray(x @ dequantize_weight(qw))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # Plain-array path unchanged.
    np.testing.assert_allclose(np.asarray(qmat(x, w)), np.asarray(x @ w))


def test_qmat_stacked_layer_axis():
    """Quantized stacked weights [n, in, out] must work under lax.scan slices."""
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.standard_normal((3, 16, 8)), jnp.float32)
    qw = quantize_weight(w)
    assert qw.scale.shape == (3, 1, 8)
    x = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    lp = QuantWeight(w=qw.w[1], scale=qw.scale[1])  # one scanned layer slice
    want = np.asarray(x @ dequantize_weight(lp))
    np.testing.assert_allclose(np.asarray(qmat(x, lp)), want, rtol=1e-5, atol=1e-5)


def test_quantized_generation_deterministic_and_finite():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(51), jnp.float32)
    qparams = quantize_params(params)
    assert quantized_bytes(qparams) < quantized_bytes(params)

    def run():
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        gen.add_message(Message.user("quantized run"))
        gen.generate(10)
        return list(gen.generated_token_ids)

    a, b = run(), run()
    assert a == b
    assert all(0 <= t < cfg.vocab_size for t in a)


def test_quantized_fused_decode_matches_per_step():
    """The fused scan and per-step paths must agree under quantized weights."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = quantize_params(M.init_params(cfg, jax.random.PRNGKey(52), jnp.float32))
    outs = []
    for chunk in (1, 4):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
            decode_chunk_size=chunk,
        )
        gen.add_message(Message.user("fused quant"))
        gen.generate(9)
        outs.append(list(gen.generated_token_ids))
    assert outs[0] == outs[1]


def test_generator_load_quantize(tmp_path):
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(53), jnp.float32)
    model_dir = tmp_path / "m"
    save_tiny_checkpoint(model_dir, params, cfg)
    gen = LlamaGenerator.load(
        model_dir, dtype=jnp.float32, max_seq_len=64, sampling=GREEDY,
        quantize="int8",
    )
    gen.add_message(Message.user("hi"))
    assert len(gen.generate(5)) >= 0  # runs end to end
    # LocalForwardStep fuses QKV/gate-up at prep time (ops/fuse.py); the
    # quantized representation rides the fusion.
    assert isinstance(gen.step.params["layers"]["wqkv"], QuantWeight)
    assert isinstance(gen.step.params["layers"]["w_gu"], QuantWeight)


def test_end_to_end_quality_vs_f32():
    """Quality, not just determinism: int8 weight-only must track the f32
    model closely — top-1 agreement and per-position KL over a long prefill.
    (Thresholds sit ~10x above measured values: agreement 0.98, KL med 3e-4.)"""
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(54), jnp.float32)
    qparams = quantize_params(params)
    prompt = np.random.default_rng(0).integers(0, 256, (1, 64)).astype(np.int32)

    def all_logits(p):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        lg, _ = M.forward_all_logits(
            p, jnp.asarray(prompt), kv, jnp.int32(0), cfg, cached_prefill=False
        )
        return np.asarray(lg[0])

    lf, lq = all_logits(params), all_logits(qparams)
    agreement = float((lf.argmax(-1) == lq.argmax(-1)).mean())
    pf = np.asarray(jax.nn.softmax(lf, -1))
    pq = np.asarray(jax.nn.softmax(lq, -1))
    kl = np.sum(pf * (np.log(pf + 1e-9) - np.log(pq + 1e-9)), -1)
    assert agreement >= 0.9, agreement
    assert float(np.median(kl)) <= 0.01, np.median(kl)
    assert float(kl.max()) <= 0.1, kl.max()


def test_qmat_bf16_matches_f32_dequant_reference():
    """The accumulation-dtype choice: int8 weights in a bf16 matmul must match
    dequantize-to-f32 + f32 matmul up to bf16 input rounding alone — the
    int8->bf16 convert is lossless and products accumulate in f32."""
    from cake_tpu.ops.quant import dequantize_weight, qmat, quantize_weight

    key = jax.random.PRNGKey(55)
    w = jax.random.normal(key, (96, 64), jnp.float32)
    x32 = jax.random.normal(jax.random.PRNGKey(56), (8, 96), jnp.float32)
    qw = quantize_weight(w)

    x16 = x32.astype(jnp.bfloat16)
    got = np.asarray(qmat(x16, qw), np.float32)
    # Reference: the SAME bf16-rounded activations against the exact
    # dequantized weight in f32 — isolates accumulation error from input
    # rounding (which the unquantized bf16 path pays identically).
    want = np.asarray(
        x16.astype(jnp.float32) @ dequantize_weight(qw, jnp.float32)
        * 1.0
    )
    np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)


def test_quantized_tp_matches_quantized_local():
    """int8 x tensor parallelism: the sharded runner must reproduce the local
    quantized stream exactly (replicated scales on row-parallel weights
    commute with the tp psum)."""
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(57), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized tensor parallel"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    got = run(
        TensorParallelRunner(cfg, qparams, tp=2, max_seq_len=128, cache_dtype=jnp.float32)
    )
    assert got == want


def test_quantized_sp_matches_quantized_local():
    """int8 x sequence parallelism (and the sp x tp 2-D mesh)."""
    from cake_tpu.parallel.sequence import SequenceParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(58), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized sequence parallel oracle"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=256, cache_dtype=jnp.float32))
    got_sp = run(
        SequenceParallelRunner(cfg, qparams, sp=4, max_seq_len=256, cache_dtype=jnp.float32)
    )
    got_sp_tp = run(
        SequenceParallelRunner(
            cfg, qparams, sp=2, tp=2, max_seq_len=256, cache_dtype=jnp.float32
        )
    )
    assert got_sp == want
    assert got_sp_tp == want


def test_quantized_mesh_pipeline_matches_quantized_local():
    """int8 x the shard_map stage pipeline (--backend mesh --quantize):
    stage-stacked QuantWeight leaves (pad_stages regroups w/scale) must
    reproduce the quantized local stream exactly."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(59), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized mesh pipeline"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    # Ragged boundaries exercise the padded-stage path with quantized leaves.
    got = run(
        PipelineRunner(
            cfg, qparams, [(0, 1), (1, 4)], max_seq_len=128, cache_dtype=jnp.float32
        )
    )
    assert got == want


def test_quantized_worker_matches_quantized_layers_local(tmp_path):
    """Worker-side --quantize: a worker serving int8 block ranges reproduces a
    local run whose layers (and only its layers) are int8."""
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.models.llama.generator import LlamaGenerator
    from cake_tpu.ops.quant import quantize_layer_tree
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(60), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w1": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    w = Worker(
        "w1", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32,
        max_seq_len=128, quantize="int8",
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=128
        )
        try:
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
            gen.add_message(Message.user("quantized worker"))
            gen.generate(8)
            got = list(gen.generated_token_ids)
        finally:
            step.close()

        oracle_params = dict(params)
        oracle_params["layers"] = quantize_layer_tree(params["layers"])
        ref = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, oracle_params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        ref.add_message(Message.user("quantized worker"))
        ref.generate(8)
        assert got == list(ref.generated_token_ids)
    finally:
        w.stop()


# ---------------------------------------------------------------- int4


def test_quantize4_roundtrip_error_bounded():
    from cake_tpu.ops.quant import quantize4_weight

    rng = np.random.default_rng(10)
    w = jnp.asarray(rng.standard_normal((512, 96)) * 0.3, jnp.float32)
    q4 = quantize4_weight(w)
    assert q4.w.dtype == jnp.int8
    assert q4.w.shape == (256, 96)  # two nibbles per byte along in
    assert q4.scale.shape == (4, 96)  # group-128 along in
    back = dequantize_weight(q4)
    # Symmetric group absmax/7: error bounded by the group's scale/2.
    err = np.abs(np.asarray(back - w)).reshape(4, 128, 96)
    bound = np.asarray(q4.scale).reshape(4, 1, 96) / 2 + 1e-7
    assert (err <= bound).all()


def test_quantize4_nibble_packing_layout():
    """Byte i holds logical rows 2i (low nibble) and 2i+1 (high): a contiguous
    packed slice IS a contiguous logical slice — the row-parallel tp
    contract."""
    from cake_tpu.ops.quant import quantize4_weight, unpack4

    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.standard_normal((64, 8)), jnp.float32)
    q4 = quantize4_weight(w, group_size=8)
    lo, hi = unpack4(q4.w)
    assert int(lo.min()) >= -7 and int(hi.max()) <= 7
    # Re-quantize the bottom half alone (same group size): its packed bytes
    # must equal the bottom half of the full packed array — contiguous packed
    # slices are contiguous logical slices.
    q_half = quantize4_weight(w[:32], group_size=8)
    np.testing.assert_array_equal(
        np.asarray(q4.w[:16]), np.asarray(q_half.w)
    )


def test_qmat4_matches_dequantized_matmul():
    from cake_tpu.ops.quant import quantize4_weight

    rng = np.random.default_rng(12)
    x = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 32)), jnp.float32)
    q4 = quantize4_weight(w)
    got = np.asarray(qmat(x, q4))
    want = np.asarray(x @ dequantize_weight(q4))
    # Both sides share the default-matmul-precision noise; the grouped sum
    # only changes reduction order.
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_qmat4_stacked_layer_axis():
    from cake_tpu.ops.quant import Quant4Weight, quantize4_weight

    rng = np.random.default_rng(13)
    w = jnp.asarray(rng.standard_normal((3, 32, 8)), jnp.float32)
    q4 = quantize4_weight(w)
    assert q4.w.shape == (3, 16, 8)
    x = jnp.asarray(rng.standard_normal((2, 32)), jnp.float32)
    lp = Quant4Weight(w=q4.w[1], scale=q4.scale[1])  # one scanned layer slice
    want = np.asarray(x @ dequantize_weight(quantize4_weight(w[1])))
    np.testing.assert_allclose(np.asarray(qmat(x, lp)), want, rtol=1e-4, atol=1e-4)


def test_int4_generation_deterministic_and_smaller_than_int8():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(61), jnp.float32)
    q8 = quantize_params(params)
    q4 = quantize_params(params, "int4")
    assert quantized_bytes(q4) < quantized_bytes(q8)

    def run():
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, q4, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        gen.add_message(Message.user("int4 run"))
        gen.generate(10)
        return list(gen.generated_token_ids)

    a, b = run(), run()
    assert a == b
    assert all(0 <= t < cfg.vocab_size for t in a)


def test_int4_fused_decode_matches_per_step():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = quantize_params(
        M.init_params(cfg, jax.random.PRNGKey(62), jnp.float32), "int4"
    )
    outs = []
    for chunk in (1, 4):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
            decode_chunk_size=chunk,
        )
        gen.add_message(Message.user("fused int4"))
        gen.generate(9)
        outs.append(list(gen.generated_token_ids))
    assert outs[0] == outs[1]


def test_int4_end_to_end_vs_dequantized_oracle():
    """The int4 forward must match the SAME model run with materialized
    dequantized weights — isolating the packed-matmul path (nibble planes,
    grouped scales) from the rounding itself. Rounding noise vs f32 is NOT a
    useful oracle here: RTN-int4 perturbs logits by ~0.4 of their std on this
    64-dim random-weight tiny model (relative weight noise shrinks ~1/sqrt(in)
    on real 4096-dim models, and trained logits have real margins; the
    measured quality trade is documented in ops/quant.py)."""
    from cake_tpu.ops.quant import Quant4Weight

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(63), jnp.float32)
    qparams = quantize_params(params, "int4")

    def deq_tree(t):
        if isinstance(t, (Quant4Weight, QuantWeight)):
            return dequantize_weight(t)
        if isinstance(t, dict):
            return {k: deq_tree(v) for k, v in t.items()}
        return t

    prompt = np.random.default_rng(1).integers(0, 256, (1, 64)).astype(np.int32)

    def all_logits(p):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        lg, _ = M.forward_all_logits(
            p, jnp.asarray(prompt), kv, jnp.int32(0), cfg, cached_prefill=False
        )
        return np.asarray(lg[0])

    lq = all_logits(qparams)
    ld = all_logits(deq_tree(qparams))
    agreement = float((lq.argmax(-1) == ld.argmax(-1)).mean())
    assert agreement >= 0.85, agreement
    assert float(np.abs(lq - ld).max()) <= 0.2  # matmul-precision noise only


def test_int4_fuse_commutes_with_quantize():
    """fuse(quantize4(w)) == quantize4(fuse(w)): per-(group, out-channel)
    scales ride their columns through the output-dim concat."""
    from cake_tpu.ops.fuse import fuse_layer_tree
    from cake_tpu.ops.quant import quantize_layer_tree

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    layers = M.init_params(cfg, jax.random.PRNGKey(64), jnp.float32)["layers"]
    a = fuse_layer_tree(quantize_layer_tree(layers, "int4"))
    b = quantize_layer_tree(fuse_layer_tree(layers), "int4")
    for k in a:
        la, lb = jax.tree.leaves(a[k]), jax.tree.leaves(b[k])
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=k)


def test_int4_tp_matches_int4_local():
    """int4 x tensor parallelism: group scales shard with the packed rows on
    row-parallel weights (adjacent nibble pairing keeps shard slices
    logical-contiguous); the sharded runner reproduces the local stream."""
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    qparams = quantize_params(
        M.init_params(cfg, jax.random.PRNGKey(65), jnp.float32), "int4"
    )

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("int4 tensor parallel"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    got = run(
        TensorParallelRunner(cfg, qparams, tp=2, max_seq_len=128, cache_dtype=jnp.float32)
    )
    assert got == want


def test_int4_mesh_pipeline_matches_int4_local():
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    qparams = quantize_params(
        M.init_params(cfg, jax.random.PRNGKey(66), jnp.float32), "int4"
    )

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("int4 mesh pipeline"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    got = run(
        PipelineRunner(
            cfg, qparams, [(0, 1), (1, 4)], max_seq_len=128, cache_dtype=jnp.float32
        )
    )
    assert got == want


def test_int4_worker_matches_int4_layers_local(tmp_path):
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.ops.quant import quantize_layer_tree
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(67), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w1": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    w = Worker(
        "w1", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32,
        max_seq_len=128, quantize="int4",
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=128
        )
        try:
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
            gen.add_message(Message.user("int4 worker"))
            gen.generate(8)
            got = list(gen.generated_token_ids)
        finally:
            step.close()

        oracle_params = dict(params)
        oracle_params["layers"] = quantize_layer_tree(params["layers"], "int4")
        ref = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, oracle_params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        ref.add_message(Message.user("int4 worker"))
        ref.generate(8)
        assert got == list(ref.generated_token_ids)
    finally:
        w.stop()


def test_int4_moe_experts_stay_int8():
    """Mixed mode: under mode="int4" the MoE expert stacks keep the int8
    per-expert scale layout (ops/moe.py dispatch reads it); the shared expert
    and attention projections go int4."""
    from cake_tpu.ops.quant import Quant4Weight, quantize_layer_tree

    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="qwen2_moe",
        num_local_experts=4, num_experts_per_tok=2,
        shared_expert_intermediate_size=32,
    )
    layers = M.init_params(cfg, jax.random.PRNGKey(68), jnp.float32)["layers"]
    q = quantize_layer_tree(layers, "int4")
    assert isinstance(q["w_gate"], QuantWeight)  # expert stack: int8
    assert isinstance(q["w_down"], QuantWeight)
    assert isinstance(q["wq"], Quant4Weight)
    assert isinstance(q["sh_gate"], Quant4Weight)  # dense shared expert: int4


def test_int4_moe_generation_runs():
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="mixtral",
        num_local_experts=4, num_experts_per_tok=2,
    )
    params = quantize_params(
        M.init_params(cfg, jax.random.PRNGKey(69), jnp.float32), "int4"
    )
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
    )
    gen.add_message(Message.user("int4 moe"))
    ids = gen.generate(8)
    assert len(gen.generated_token_ids) > 0


def test_int4_unaligned_groups_fail_with_clear_error():
    """Row-parallel int4 whose group count does not divide tp must fail at
    placement with the actionable message, not a deep device_put error
    (e.g. Llama-2-7B w_down: 11008/128 = 86 groups, tp=4)."""
    import pytest

    from cake_tpu.parallel.tensor import TensorParallelRunner

    from cake_tpu.ops.quant import Quant4Weight

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(70), jnp.float32)
    q = quantize_params(params, "int4")
    # Hand-build an ODD (3) group count on the row-parallel w_down: tp=2
    # cannot divide it, so placement must refuse with the actionable message.
    w = q["layers"]["w_down"]
    q["layers"]["w_down"] = Quant4Weight(
        w=w.w, scale=jnp.ones((w.w.shape[0], 3, w.w.shape[-1]), jnp.float32)
    )
    with pytest.raises(ValueError, match="scale groups do not divide"):
        TensorParallelRunner(cfg, q, tp=2, max_seq_len=64, cache_dtype=jnp.float32)


def test_int4_pallas_kernel_matches_xla_path():
    """The Pallas int4 matmul (interpret mode here; Mosaic on real TPU) must
    match the XLA grouped formulation on the same packed weights — including
    ragged batch rows, multi-block K, and group sizes below the k-block."""
    from cake_tpu.ops.pallas.int4_matmul import int4_matmul
    from cake_tpu.ops.quant import quantize4_weight

    rng = np.random.default_rng(20)
    for b, in_dim, out, gs in ((1, 512, 256, 128), (3, 256, 128, 32), (9, 1024, 384, 128)):
        x = jnp.asarray(rng.standard_normal((b, in_dim)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((in_dim, out)), jnp.float32)
        q4 = quantize4_weight(w, group_size=gs)
        got = np.asarray(int4_matmul(x, q4.w, q4.scale, interpret=True))
        want = np.asarray(qmat(x, q4))
        np.testing.assert_allclose(
            got, want, rtol=2e-3, atol=2e-3, err_msg=f"{(b, in_dim, out, gs)}"
        )


def test_int4_pallas_kernel_bf16_accumulation():
    """bf16 activations: the kernel's scaled-weight cast + f32 accumulation
    must track the f32 dequant oracle within bf16 input rounding."""
    from cake_tpu.ops.pallas.int4_matmul import int4_matmul
    from cake_tpu.ops.quant import quantize4_weight

    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.standard_normal((4, 512)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
    q4 = quantize4_weight(w)
    got = np.asarray(int4_matmul(x, q4.w, q4.scale, interpret=True), np.float32)
    want = np.asarray(
        x.astype(jnp.float32) @ dequantize_weight(q4, jnp.float32)
    )
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.5)


def test_int4_pallas_kernel_rows_tile_and_match_across_batch():
    """The row-gridded kernel must (a) handle prefill-scale row counts and
    (b) give each row a batch-composition-independent result — the property
    that lets qmat use ONE path for decode, verify, and prefill on TPU."""
    from cake_tpu.ops.pallas.int4_matmul import int4_matmul
    from cake_tpu.ops.quant import quantize4_weight

    rng = np.random.default_rng(22)
    w = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
    q4 = quantize4_weight(w)
    xs = jnp.asarray(rng.standard_normal((300, 256)), jnp.float32)  # > row tile
    full = np.asarray(int4_matmul(xs, q4.w, q4.scale, interpret=True))
    one = np.asarray(int4_matmul(xs[17:18], q4.w, q4.scale, interpret=True))
    np.testing.assert_array_equal(full[17:18], one)
    want = np.asarray(qmat(xs, q4))
    np.testing.assert_allclose(full, want, rtol=2e-3, atol=2e-3)


# ------------------------------------------------- native-s4 representation


def test_s4_dequantizes_identically_to_packed():
    from cake_tpu.ops.quant import (
        QuantS4Weight,
        dequantize_weight,
        quantize4_weight,
        to_native_int4,
    )

    w = jax.random.normal(jax.random.PRNGKey(7), (256, 192), jnp.float32)
    q4 = quantize4_weight(w)
    s4 = to_native_int4(q4)
    assert isinstance(s4, QuantS4Weight)
    assert s4.w.dtype == jnp.int4 and s4.w.shape == (256, 192)
    np.testing.assert_array_equal(
        np.asarray(dequantize_weight(s4)), np.asarray(dequantize_weight(q4))
    )


def test_qmat_s4_matches_grouped_path():
    """The native-s4 dot is the same exact-int + f32-group-combine
    arithmetic as _qmat4 — only the accumulation grouping differs, so the
    results agree to float-sum-reorder tolerance."""
    from cake_tpu.ops.quant import _qmat4, qmat, quantize4_weight, to_native_int4

    w = jax.random.normal(jax.random.PRNGKey(8), (256, 192), jnp.float32)
    q4 = quantize4_weight(w)
    s4 = to_native_int4(q4)
    x = jax.random.normal(jax.random.PRNGKey(9), (3, 256), jnp.float32)
    got = np.asarray(qmat(x, s4))
    want = np.asarray(_qmat4(x, q4))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_s4_repr_generation_matches_packed_quality(monkeypatch):
    """CAKE_INT4_REPR=s4 converts at the LocalForwardStep prep site (the
    single-chip runtime): prefill logits match the packed-int4 model to
    float-reorder tolerance, greedy generation is deterministic, and the
    offline quantizer/quantize_params stay PACKED regardless of the env."""
    from cake_tpu.ops.quant import (
        QuantS4Weight,
        apply_runtime_int4_repr,
        quantize_params,
        tree_quantization,
    )

    monkeypatch.delenv("CAKE_INT4_REPR", raising=False)
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(90), jnp.float32)
    q4 = quantize_params(params, "int4")
    monkeypatch.setenv("CAKE_INT4_REPR", "s4")
    # The quantization primitive itself must NOT honor the env (checkpoint
    # format stays packed); only the runtime prep converts.
    assert not any(
        isinstance(l, QuantS4Weight)
        for l in jax.tree.leaves(
            quantize_params(params, "int4"),
            is_leaf=lambda x: isinstance(x, QuantS4Weight),
        )
    )
    s4 = apply_runtime_int4_repr(q4)
    assert tree_quantization(s4) == "int4"
    assert isinstance(s4["layers"]["wq"], QuantS4Weight)

    prompt = np.random.default_rng(3).integers(0, 256, (1, 24)).astype(np.int32)

    def prefill_logits(p):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        logits, _ = M.forward(
            p, jnp.asarray(prompt), kv, jnp.int32(0), jnp.int32(24), cfg
        )
        return np.asarray(logits, np.float32)

    np.testing.assert_allclose(
        prefill_logits(s4), prefill_logits(q4), rtol=2e-4, atol=2e-4
    )

    def stream():
        # LocalForwardStep is the env's one conversion site: feed it the
        # PACKED tree and let prep convert (the real runtime flow).
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, q4, max_seq_len=64, cache_dtype=jnp.float32),
            ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        )
        assert isinstance(gen.step.params["layers"]["wqkv"], QuantS4Weight)
        gen.add_message(Message.user("s4 repr"))
        gen.generate(8)
        return list(gen.generated_token_ids)

    a = stream()
    assert a == stream()  # deterministic
    assert all(0 <= t < cfg.vocab_size for t in a)

    # tp placement rejects the s4 representation with an actionable error.
    import pytest as _pytest

    from cake_tpu.parallel.tensor import layer_partition_specs

    with _pytest.raises(NotImplementedError, match="single-chip"):
        layer_partition_specs(params=s4["layers"])

    # quantized_bytes reads s4 at its true 0.5 B/weight stream.
    from cake_tpu.ops.quant import quantized_bytes

    assert quantized_bytes(s4) == quantized_bytes(q4)
