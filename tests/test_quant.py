"""Weight-only int8 quantization (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.ops.quant import (
    QuantWeight,
    dequantize_weight,
    qmat,
    quantize_params,
    quantize_weight,
    quantized_bytes,
)

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 128)) * 0.3, jnp.float32)
    qw = quantize_weight(w)
    assert qw.w.dtype == jnp.int8
    back = dequantize_weight(qw)
    # Symmetric per-channel absmax: error bounded by scale/2 per element.
    max_err = np.abs(np.asarray(back - w)).max()
    per_chan_bound = np.asarray(qw.scale).max() / 2 + 1e-7
    assert max_err <= per_chan_bound


def test_qmat_matches_dequantized_matmul():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    qw = quantize_weight(w)
    got = np.asarray(qmat(x, qw))
    want = np.asarray(x @ dequantize_weight(qw))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # Plain-array path unchanged.
    np.testing.assert_allclose(np.asarray(qmat(x, w)), np.asarray(x @ w))


def test_qmat_stacked_layer_axis():
    """Quantized stacked weights [n, in, out] must work under lax.scan slices."""
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.standard_normal((3, 16, 8)), jnp.float32)
    qw = quantize_weight(w)
    assert qw.scale.shape == (3, 1, 8)
    x = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
    lp = QuantWeight(w=qw.w[1], scale=qw.scale[1])  # one scanned layer slice
    want = np.asarray(x @ dequantize_weight(lp))
    np.testing.assert_allclose(np.asarray(qmat(x, lp)), want, rtol=1e-5, atol=1e-5)


def test_quantized_generation_deterministic_and_finite():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(51), jnp.float32)
    qparams = quantize_params(params)
    assert quantized_bytes(qparams) < quantized_bytes(params)

    def run():
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        gen.add_message(Message.user("quantized run"))
        gen.generate(10)
        return list(gen.generated_token_ids)

    a, b = run(), run()
    assert a == b
    assert all(0 <= t < cfg.vocab_size for t in a)


def test_quantized_fused_decode_matches_per_step():
    """The fused scan and per-step paths must agree under quantized weights."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = quantize_params(M.init_params(cfg, jax.random.PRNGKey(52), jnp.float32))
    outs = []
    for chunk in (1, 4):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
            decode_chunk_size=chunk,
        )
        gen.add_message(Message.user("fused quant"))
        gen.generate(9)
        outs.append(list(gen.generated_token_ids))
    assert outs[0] == outs[1]


def test_generator_load_quantize(tmp_path):
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(53), jnp.float32)
    model_dir = tmp_path / "m"
    save_tiny_checkpoint(model_dir, params, cfg)
    gen = LlamaGenerator.load(
        model_dir, dtype=jnp.float32, max_seq_len=64, sampling=GREEDY,
        quantize="int8",
    )
    gen.add_message(Message.user("hi"))
    assert len(gen.generate(5)) >= 0  # runs end to end
    # LocalForwardStep fuses QKV/gate-up at prep time (ops/fuse.py); the
    # quantized representation rides the fusion.
    assert isinstance(gen.step.params["layers"]["wqkv"], QuantWeight)
    assert isinstance(gen.step.params["layers"]["w_gu"], QuantWeight)


def test_end_to_end_quality_vs_f32():
    """Quality, not just determinism: int8 weight-only must track the f32
    model closely — top-1 agreement and per-position KL over a long prefill.
    (Thresholds sit ~10x above measured values: agreement 0.98, KL med 3e-4.)"""
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(54), jnp.float32)
    qparams = quantize_params(params)
    prompt = np.random.default_rng(0).integers(0, 256, (1, 64)).astype(np.int32)

    def all_logits(p):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        lg, _ = M.forward_all_logits(
            p, jnp.asarray(prompt), kv, jnp.int32(0), cfg, cached_prefill=False
        )
        return np.asarray(lg[0])

    lf, lq = all_logits(params), all_logits(qparams)
    agreement = float((lf.argmax(-1) == lq.argmax(-1)).mean())
    pf = np.asarray(jax.nn.softmax(lf, -1))
    pq = np.asarray(jax.nn.softmax(lq, -1))
    kl = np.sum(pf * (np.log(pf + 1e-9) - np.log(pq + 1e-9)), -1)
    assert agreement >= 0.9, agreement
    assert float(np.median(kl)) <= 0.01, np.median(kl)
    assert float(kl.max()) <= 0.1, kl.max()


def test_qmat_bf16_matches_f32_dequant_reference():
    """The accumulation-dtype choice: int8 weights in a bf16 matmul must match
    dequantize-to-f32 + f32 matmul up to bf16 input rounding alone — the
    int8->bf16 convert is lossless and products accumulate in f32."""
    from cake_tpu.ops.quant import dequantize_weight, qmat, quantize_weight

    key = jax.random.PRNGKey(55)
    w = jax.random.normal(key, (96, 64), jnp.float32)
    x32 = jax.random.normal(jax.random.PRNGKey(56), (8, 96), jnp.float32)
    qw = quantize_weight(w)

    x16 = x32.astype(jnp.bfloat16)
    got = np.asarray(qmat(x16, qw), np.float32)
    # Reference: the SAME bf16-rounded activations against the exact
    # dequantized weight in f32 — isolates accumulation error from input
    # rounding (which the unquantized bf16 path pays identically).
    want = np.asarray(
        x16.astype(jnp.float32) @ dequantize_weight(qw, jnp.float32)
        * 1.0
    )
    np.testing.assert_allclose(got, want, atol=5e-2, rtol=5e-2)


def test_quantized_tp_matches_quantized_local():
    """int8 x tensor parallelism: the sharded runner must reproduce the local
    quantized stream exactly (replicated scales on row-parallel weights
    commute with the tp psum)."""
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(57), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized tensor parallel"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    got = run(
        TensorParallelRunner(cfg, qparams, tp=2, max_seq_len=128, cache_dtype=jnp.float32)
    )
    assert got == want


def test_quantized_sp_matches_quantized_local():
    """int8 x sequence parallelism (and the sp x tp 2-D mesh)."""
    from cake_tpu.parallel.sequence import SequenceParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(58), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized sequence parallel oracle"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=256, cache_dtype=jnp.float32))
    got_sp = run(
        SequenceParallelRunner(cfg, qparams, sp=4, max_seq_len=256, cache_dtype=jnp.float32)
    )
    got_sp_tp = run(
        SequenceParallelRunner(
            cfg, qparams, sp=2, tp=2, max_seq_len=256, cache_dtype=jnp.float32
        )
    )
    assert got_sp == want
    assert got_sp_tp == want


def test_quantized_mesh_pipeline_matches_quantized_local():
    """int8 x the shard_map stage pipeline (--backend mesh --quantize):
    stage-stacked QuantWeight leaves (pad_stages regroups w/scale) must
    reproduce the quantized local stream exactly."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    qparams = quantize_params(M.init_params(cfg, jax.random.PRNGKey(59), jnp.float32))

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("quantized mesh pipeline"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, qparams, max_seq_len=128, cache_dtype=jnp.float32))
    # Ragged boundaries exercise the padded-stage path with quantized leaves.
    got = run(
        PipelineRunner(
            cfg, qparams, [(0, 1), (1, 4)], max_seq_len=128, cache_dtype=jnp.float32
        )
    )
    assert got == want


def test_quantized_worker_matches_quantized_layers_local(tmp_path):
    """Worker-side --quantize: a worker serving int8 block ranges reproduces a
    local run whose layers (and only its layers) are int8."""
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.models.llama.generator import LlamaGenerator
    from cake_tpu.ops.quant import quantize_layer_tree
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(60), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w1": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    w = Worker(
        "w1", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32,
        max_seq_len=128, quantize="int8",
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=128
        )
        try:
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
            gen.add_message(Message.user("quantized worker"))
            gen.generate(8)
            got = list(gen.generated_token_ids)
        finally:
            step.close()

        oracle_params = dict(params)
        oracle_params["layers"] = quantize_layer_tree(params["layers"])
        ref = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, oracle_params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        ref.add_message(Message.user("quantized worker"))
        ref.generate(8)
        assert got == list(ref.generated_token_ids)
    finally:
        w.stop()
