"""Native C++ codec (cake_tpu/native): wire parity with the Python proto path.

Builds the shared library on the fly (skips when no C++ toolchain); every test
asserts the native and pure-Python implementations are interchangeable on the
same socket — one peer native, one forced Python.
"""

import ctypes
import os
import socket
import threading

import numpy as np
import pytest

from cake_tpu import native
from cake_tpu.runtime import proto


@pytest.fixture(scope="module")
def native_lib():
    if not native.available():
        try:
            from cake_tpu.native.build import build
        except Exception:  # pragma: no cover
            pytest.skip("native build tooling unavailable")
        if os.environ.get("CAKE_TPU_NO_NATIVE"):
            pytest.skip("native disabled via CAKE_TPU_NO_NATIVE")
        if build(verbose=False) is None:
            pytest.skip("no C++ compiler")
        assert native.reload()
    return native.lib


def roundtrip(frame: proto.Frame) -> proto.Frame:
    """Send through a real socketpair: native writer -> native reader."""
    a, b = socket.socketpair()
    try:
        err: list[BaseException] = []
        got: list[proto.Frame] = []

        def rx():
            try:
                got.append(proto.read_frame(b))
            except BaseException as e:  # pragma: no cover
                err.append(e)

        t = threading.Thread(target=rx)
        t.start()
        proto.write_frame(a, frame)
        t.join(timeout=10)
        assert not err, err
        return got[0]
    finally:
        a.close()
        b.close()


def test_native_roundtrip_tensor_frame(native_lib):
    x = np.arange(6 * 1024, dtype=np.float32).reshape(2, -1)
    frame = proto.tensor_frame(proto.WireTensor.from_numpy(x))
    out = roundtrip(frame)
    assert out.type == proto.MsgType.TENSOR
    np.testing.assert_array_equal(out.tensor().to_numpy(), x)


def test_native_writer_python_reader_and_back(native_lib):
    """Cross-implementation: bytes on the wire must be identical."""
    x = np.random.default_rng(0).standard_normal((3, 128)).astype(np.float32)
    frame = proto.forward_frame(
        proto.WireTensor.from_numpy(x), [(0, 4), (8, 12)], pos=7
    )
    wire_native = bytearray()

    a, b = socket.socketpair()
    try:
        t = threading.Thread(
            target=lambda: wire_native.extend(
                proto._recv_exact(b, len(proto.encode_frame(frame)))
            )
        )
        t.start()
        proto.write_frame(a, frame)  # native path (lib is loaded)
        t.join(timeout=10)
    finally:
        a.close()
        b.close()
    assert bytes(wire_native) == proto.encode_frame(frame)


def test_native_recv_honors_timeout(native_lib):
    a, b = socket.socketpair()
    try:
        b.settimeout(0.2)
        with pytest.raises((TimeoutError, socket.timeout)):
            proto.read_frame(b)
    finally:
        a.close()
        b.close()


def test_native_recv_raises_on_peer_close(native_lib):
    a, b = socket.socketpair()
    a.close()
    try:
        with pytest.raises(ConnectionError):
            proto.read_frame(b)
    finally:
        b.close()


def test_native_large_payload_roundtrip(native_lib):
    """Multi-MB payload: exercises partial sends/recvs and the writev split."""
    x = np.random.default_rng(1).integers(0, 255, 8 * 1024 * 1024, np.uint8)
    t = proto.WireTensor(data=x.tobytes(), dtype="i8", shape=x.shape)
    out = roundtrip(proto.tensor_frame(t))
    np.testing.assert_array_equal(
        out.tensor().to_numpy().view(np.uint8), x
    )


def test_bf16_conversion_matches_ml_dtypes(native_lib):
    import ml_dtypes

    rng = np.random.default_rng(2)
    src = np.concatenate(
        [
            rng.standard_normal(4096).astype(np.float32) * 1e3,
            np.array([0.0, -0.0, np.inf, -np.inf, np.nan, 1e-40], np.float32),
        ]
    )
    dst = np.empty(src.size, np.uint16)
    native.lib.ct_f32_to_bf16(
        src.ctypes.data_as(ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p),
        src.size,
    )
    want = src.astype(ml_dtypes.bfloat16).view(np.uint16)
    # NaNs: any quiet NaN encoding is acceptable; compare payloads elsewhere.
    finite = np.isfinite(src)
    np.testing.assert_array_equal(dst[finite], want[finite])
    assert np.all(np.isnan(dst[~finite].view(ml_dtypes.bfloat16).astype(np.float32))
                  == np.isnan(src[~finite]))

    back = np.empty(src.size, np.float32)
    native.lib.ct_bf16_to_f32(
        dst.ctypes.data_as(ctypes.c_void_p),
        back.ctypes.data_as(ctypes.c_void_p),
        src.size,
    )
    widened = dst.view(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(
        back[finite], widened[finite]
    )


def test_wire_to_jax_f32_narrowing_matches_device_cast(native_lib):
    import jax.numpy as jnp

    from cake_tpu.runtime.worker import wire_to_jax

    x = np.random.default_rng(3).standard_normal((4, 257)).astype(np.float32)
    t = proto.WireTensor.from_numpy(x)
    got = wire_to_jax(t, jnp.bfloat16)
    want = jnp.asarray(x).astype(jnp.bfloat16)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(got.view(jnp.uint16)), np.asarray(want.view(jnp.uint16))
    )


def test_f32_bf16_wrappers_fallback_parity(native_lib):
    """native.f32_to_bf16 must agree with its own ml_dtypes fallback."""
    from cake_tpu import native as nat

    x = np.random.default_rng(4).standard_normal(1000).astype(np.float32) * 50
    fast = nat.f32_to_bf16(x)
    saved, nat.lib = nat.lib, None
    try:
        slow = nat.f32_to_bf16(x)
        back_slow = nat.bf16_to_f32(fast)
    finally:
        nat.lib = saved
    np.testing.assert_array_equal(fast, slow)
    np.testing.assert_array_equal(nat.bf16_to_f32(fast), back_slow)
