"""Splitter tests: bundle contents, ownership filtering, end-to-end worker boot."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import (
    SafetensorsReader,
    save_tiny_checkpoint,
)
from cake_tpu.io.splitter import split_model
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.parallel.topology import Topology

TOPO = {
    "alpha": {"host": "10.0.0.1:10128", "layers": ["model.layers.0-2"]},
    "beta": {"host": "10.0.0.2:10128", "layers": ["model.layers.3-5"]},
}


@pytest.fixture(scope="module")
def split(tmp_path_factory):
    root = tmp_path_factory.mktemp("split")
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    save_tiny_checkpoint(root / "model", params, cfg)
    topo_path = root / "topology.yml"
    Topology.from_dict(TOPO).save(topo_path)
    bundles = split_model(root / "model", topo_path, root / "out")
    return cfg, params, root, bundles


def test_bundle_layout(split):
    cfg, params, root, bundles = split
    assert [b.name for b in bundles] == ["alpha-node", "beta-node"]
    for b in bundles:
        assert (b / "model" / "reduced.safetensors").exists()
        assert (b / "model" / "model.safetensors.index.json").exists()
        assert (b / "model" / "config.json").exists()
        assert (b / "topology.yml").exists()


def test_bundle_contains_only_owned_layers(split):
    cfg, params, root, bundles = split
    r = SafetensorsReader([bundles[0] / "model" / "reduced.safetensors"])
    names = list(r.names())
    assert all(n.startswith("model.layers.") for n in names)
    owned_layers = {n.split(".")[2] for n in names}
    assert owned_layers == {"0", "1", "2"}
    # 9 weights per layer (q/k/v/o, gate/up/down, 2 norms).
    assert len(names) == 3 * 9
    # No embedding/head in worker bundles (they stay on the master).
    assert "model.embed_tokens.weight" not in names


def test_bundle_tensor_bytes_identical(split):
    cfg, params, root, bundles = split
    src = SafetensorsReader([root / "model" / "model.safetensors"])
    red = SafetensorsReader([bundles[1] / "model" / "reduced.safetensors"])
    for name in red.names():
        np.testing.assert_array_equal(src.numpy(name), red.numpy(name))


def test_bundle_topology_is_single_entry(split):
    cfg, params, root, bundles = split
    t = Topology.from_path(bundles[0] / "topology.yml")
    assert list(t.nodes) == ["alpha"]
    assert t.nodes["alpha"].layer_indices() == [0, 1, 2]


def test_worker_boots_from_bundle(split):
    """A worker must start from its reduced bundle alone (the deployment story:
    split on a big host, rsync the bundle, run the worker)."""
    from cake_tpu.runtime.worker import Worker

    cfg, params, root, bundles = split
    t = Topology.from_path(bundles[0] / "topology.yml")
    w = Worker(
        "alpha",
        bundles[0] / "model",
        t,
        ("127.0.0.1", 0),
        dtype=jnp.float32,
        max_seq_len=64,
    )
    try:
        assert w.ranges == [(0, 3)]
        # The worker fuses QKV at load (ops/fuse.py); q occupies the leading
        # columns of the fused projection.
        qw = params["layers"]["wq"].shape[-1]
        np.testing.assert_array_equal(
            np.asarray(w.range_params[(0, 3)]["wqkv"][..., :qw]),
            np.asarray(params["layers"]["wq"][0:3]),
        )
    finally:
        w.stop()


def test_index_weight_map_complete(split):
    cfg, params, root, bundles = split
    with open(bundles[0] / "model" / "model.safetensors.index.json") as f:
        idx = json.load(f)
    r = SafetensorsReader([bundles[0] / "model" / "reduced.safetensors"])
    assert set(idx["weight_map"]) == set(r.names())
    assert all(v == "reduced.safetensors" for v in idx["weight_map"].values())
