"""Engine-level tests: suppression syntax, baseline workflow, output formats,
CLI exit codes, and the repo self-check (`cake-tpu lint cake_tpu/` exits 0).

The analysis package is stdlib-only; only the self-check spawns a real
`cake-tpu lint` process to pin the console entry point's contract.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from cake_tpu.analysis import engine, lint_source
from cake_tpu.analysis.cli import lint_main

REPO = Path(__file__).resolve().parent.parent

BAD = """
def f(x, acc=[]):
    return acc
"""


# ---------------------------------------------------------------- suppression


def test_same_line_suppression():
    src = "def f(x, acc=[]):  # cake-lint: disable=mutable-default-arg\n    return acc\n"
    assert lint_source(src) == []


def test_next_line_suppression():
    src = (
        "# cake-lint: disable-next-line=mutable-default-arg\n"
        "def f(x, acc=[]):\n    return acc\n"
    )
    assert lint_source(src) == []


def test_file_level_suppression():
    src = "# cake-lint: disable-file=mutable-default-arg\n" + BAD
    assert lint_source(src) == []


def test_bare_disable_silences_every_rule():
    src = "def f(x, acc=[]):  # cake-lint: disable\n    return acc\n"
    assert lint_source(src) == []


def test_suppression_is_rule_scoped():
    # Suppressing a DIFFERENT rule must not silence this one.
    src = "def f(x, acc=[]):  # cake-lint: disable=jit-in-hot-loop\n    return acc\n"
    assert [f.rule for f in lint_source(src)] == ["mutable-default-arg"]


# -------------------------------------------------------------- select/ignore


def test_select_and_ignore():
    assert lint_source(BAD, select=["jit-in-hot-loop"]) == []
    assert lint_source(BAD, ignore=["mutable-default-arg"]) == []
    assert len(lint_source(BAD, select=["mutable-default-arg"])) == 1


def test_unknown_rule_raises():
    with pytest.raises(ValueError, match="unknown rule"):
        lint_source(BAD, select=["no-such-rule"])


# ------------------------------------------------------------------- baseline


def test_baseline_roundtrip(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(BAD)
    first = engine.run_lint([f])
    assert len(first.findings) == 1

    bl = tmp_path / "baseline.json"
    engine.write_baseline(first, bl)
    doc = engine.load_baseline(bl)
    again = engine.run_lint([f], baseline=doc)
    assert again.findings == []
    assert len(again.baselined) == 1

    # A NEW finding still gates through the old baseline.
    f.write_text(BAD + "\ndef g(y, opts={}):\n    return opts\n")
    third = engine.run_lint([f], baseline=doc)
    assert len(third.findings) == 1
    assert "opts" in third.findings[0].message


def test_fingerprint_survives_line_moves(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(BAD)
    fp1 = engine.run_lint([f]).findings[0].fingerprint
    f.write_text("\n\n# moved down\n" + BAD)
    fp2 = engine.run_lint([f]).findings[0].fingerprint
    assert fp1 == fp2


def test_rejects_foreign_baseline(tmp_path):
    bl = tmp_path / "nope.json"
    bl.write_text(json.dumps({"version": 99}))
    with pytest.raises(ValueError, match="version 1"):
        engine.load_baseline(bl)


# --------------------------------------------------------------------- output


def test_json_output_is_stable_and_machine_readable(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(BAD)
    res = engine.run_lint([f])
    doc = json.loads(res.to_json())
    assert doc["version"] == 1
    assert doc["summary"]["errors"] == 1
    (finding,) = doc["findings"]
    assert set(finding) == {
        "rule", "path", "line", "col", "severity", "message", "fingerprint",
    }
    assert finding["rule"] == "mutable-default-arg"
    assert finding["line"] == 2
    # Byte-stable across runs: CI can diff it.
    assert res.to_json() == engine.run_lint([f]).to_json()


def test_sarif_output_is_schema_shaped(tmp_path):
    """Structural validation against the SARIF 2.1.0 shape GitHub
    code-scanning ingests: version pinned, rule metadata present for
    every referenced rule, results carrying a physical location and the
    baseline-stable fingerprint as a partial fingerprint."""
    f = tmp_path / "bad.py"
    f.write_text(BAD)
    res = engine.run_lint([f])
    doc = json.loads(res.to_sarif())
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-2.1.0.json")
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "cake-lint"
    assert "informationUri" in driver
    rules = driver["rules"]
    (result,) = run["results"]
    assert result["ruleId"] == "mutable-default-arg"
    # ruleIndex must address the driver's rule array, per the spec.
    rule = rules[result["ruleIndex"]]
    assert rule["id"] == result["ruleId"]
    assert rule["shortDescription"]["text"]
    assert rule["defaultConfiguration"]["level"] in ("error", "warning")
    assert result["level"] == "error"
    assert result["message"]["text"]
    (loc,) = result["locations"]
    phys = loc["physicalLocation"]
    assert phys["artifactLocation"]["uriBaseId"] == "%SRCROOT%"
    assert phys["artifactLocation"]["uri"].endswith("bad.py")
    assert phys["region"]["startLine"] == 2
    assert phys["region"]["startColumn"] >= 1
    fp = result["partialFingerprints"]["cakeLintFingerprint/v1"]
    assert fp == res.findings[0].fingerprint
    # Byte-stable across runs: the CI artifact can be diffed.
    assert res.to_sarif() == engine.run_lint([f]).to_sarif()


def test_sarif_clean_run_has_empty_results(tmp_path):
    f = tmp_path / "ok.py"
    f.write_text("def f(x):\n    return x\n")
    doc = json.loads(engine.run_lint([f]).to_sarif())
    assert doc["runs"][0]["results"] == []
    assert doc["runs"][0]["tool"]["driver"]["rules"] == []


def test_sarif_cli_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    assert lint_main([str(bad), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"][0]["ruleId"] == "mutable-default-arg"


def test_findings_sorted_by_location(tmp_path):
    f = tmp_path / "multi.py"
    f.write_text(
        "def b(x, a={}):\n    return a\n\ndef a(x, b=[]):\n    return b\n"
    )
    res = engine.run_lint([f])
    assert [x.line for x in res.findings] == [1, 4]


def test_github_format_annotations(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    assert lint_main([str(bad), "--format", "github"]) == 1
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines() if l.startswith("::"))
    assert line.startswith("::error file=")
    assert "line=2" in line
    assert "title=cake-lint: mutable-default-arg" in line
    assert "::" in line.rsplit("title=", 1)[1]  # message after the :: sep
    # Warn severities map to ::warning.
    warn = tmp_path / "warn.py"
    warn.write_text("try:\n    f()\nexcept Exception:\n    pass\n")
    lint_main([str(warn), "--format", "github"])
    out = capsys.readouterr().out
    assert any(l.startswith("::warning ") for l in out.splitlines())


def test_github_format_escapes_newlines(tmp_path):
    from cake_tpu.analysis.engine import Finding

    f = Finding(
        rule="r", path="p.py", line=1, col=1, severity="error",
        message="two\nlines % done",
    )
    rendered = f.render_github()
    assert "\n" not in rendered
    assert "%0A" in rendered and "%25" in rendered


def test_prune_baseline_drops_stale_fingerprints(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD + "\ndef g(y, opts={}):\n    return opts\n")
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--write-baseline", str(bl)]) == 0
    assert len(engine.load_baseline(bl)["fingerprints"]) == 2

    # One finding gets fixed; its fingerprint is now stale.
    bad.write_text(BAD)
    assert lint_main(
        [str(bad), "--baseline", str(bl), "--prune-baseline"]
    ) == 0
    out = capsys.readouterr().out
    assert "pruned 1 stale fingerprint(s)" in out
    doc = engine.load_baseline(bl)
    assert len(doc["fingerprints"]) == 1
    # The remaining entry still baselines the live finding.
    assert lint_main([str(bad), "--baseline", str(bl)]) == 0
    capsys.readouterr()


def test_prune_baseline_requires_baseline(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    assert lint_main([str(bad), "--prune-baseline"]) == 2
    capsys.readouterr()


def test_prune_baseline_rejects_narrowed_runs(tmp_path, capsys):
    # --select/--ignore narrow what the run checks; pruning against that
    # would delete still-live debt the narrowed run simply did not produce.
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--write-baseline", str(bl)]) == 0
    for extra in (
        ["--select", "jit-in-hot-loop"],
        ["--ignore", "mutable-default-arg"],
    ):
        rc = lint_main(
            [str(bad), "--baseline", str(bl), "--prune-baseline", *extra]
        )
        assert rc == 2
    # The baseline file is untouched.
    assert len(engine.load_baseline(bl)["fingerprints"]) == 1
    capsys.readouterr()


def test_parse_error_is_a_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def f(:\n")
    res = engine.run_lint([f])
    assert [x.rule for x in res.findings] == ["parse-error"]
    assert res.findings[0].severity == "error"


# ------------------------------------------------------------------------ CLI


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    assert lint_main([str(bad)]) == 1
    assert lint_main([str(bad), "--ignore", "mutable-default-arg"]) == 0
    # Warn-severity findings do not gate unless --strict.
    warn = tmp_path / "warn.py"
    warn.write_text("try:\n    f()\nexcept Exception:\n    pass\n")
    assert lint_main([str(warn)]) == 0
    assert lint_main([str(warn), "--strict"]) == 1
    assert lint_main([str(bad), "--select", "bogus"]) == 2
    capsys.readouterr()


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(BAD)
    bl = tmp_path / "bl.json"
    assert lint_main([str(bad), "--write-baseline", str(bl)]) == 0
    assert lint_main([str(bad), "--baseline", str(bl)]) == 0
    capsys.readouterr()


# ------------------------------------------------------------ repo self-check


def test_repo_is_lint_clean():
    """`cake-tpu lint cake_tpu/` exits 0 on this repo — the acceptance
    criterion. Runs the real CLI (subprocess) so argv handling, exit code,
    and the no-jax import path are all covered."""
    proc = subprocess.run(
        [sys.executable, "-m", "cake_tpu.cli", "lint", "cake_tpu", "--strict"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 error(s)" in proc.stdout


def test_repo_tests_are_lint_clean_too():
    res = engine.run_lint([REPO / "tests"])
    assert res.errors == [], [f.render() for f in res.errors]
