"""Fused multi-token decode (models/llama/fused.py): parity with per-step
path — and the decode hot-path OP fusions (ISSUE 13): fused_norm_matmul /
fused_qkv_ingest / fused_sample_tail streams bit-identical to unfused, with
kernel-vs-XLA-twin oracles."""

import dataclasses

import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.utils import metrics

import jax
import jax.numpy as jnp


def make_gen(sampling: SamplingConfig, chunk: int) -> LlamaGenerator:
    cfg = LlamaConfig.tiny()
    params = M.init_params(cfg, jax.random.PRNGKey(7), np.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=np.float32)
    return LlamaGenerator(
        cfg, step, ByteTokenizer(), sampling, decode_chunk_size=chunk
    )


@pytest.mark.parametrize(
    "sampling",
    [
        SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0),
        SamplingConfig(temperature=0.9, top_k=20, repeat_penalty=1.1, seed=123),
    ],
    ids=["greedy+penalty", "greedy-no-penalty", "sampled"],
)
def test_fused_matches_per_step(sampling):
    """Same params + seed: chunked decode must emit the identical token stream.

    Covers the penalty-ring reseeding, PRNG split ordering, and position
    bookkeeping all at once; 11 tokens with chunk 4 exercises first-token
    per-step entry, two full fused chunks, and a per-step tail.
    """
    outs = []
    for chunk in (1, 4):
        gen = make_gen(sampling, chunk)
        gen.add_message(Message.user("tell me a story"))
        text = gen.generate(11)
        outs.append((text, list(gen.generated_token_ids)))
    (t1, ids1), (t4, ids4) = outs
    assert ids1 == ids4
    assert t1 == t4
    assert len(ids1) == 11 or 259 in ids1 or 260 in ids1


def test_fused_chunk_composes_with_continued_decode():
    """State after a fused chunk must let per-step decode continue seamlessly."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=6)
    ref = make_gen(s, 1)
    ref.add_message(Message.user("abc"))
    want = ref.generate(9)

    gen = make_gen(s, 4)
    gen.add_message(Message.user("abc"))
    first = gen.generate(5)  # 1 per-step + 1 fused chunk of 4
    rest = gen.generate(4)  # continues the same sequence per-step/fused
    assert (first + rest) == want


class ScriptedFusedStep:
    """Fake step with decode_chunk: scripted ids, records call granularity."""

    max_seq_len = 64

    def __init__(self, script, vocab=512):
        self.script = list(script)
        self.vocab = vocab
        self.i = 0
        self.chunk_calls = []
        self.step_calls = 0

    def reset(self):
        self.i = 0

    def __call__(self, tokens, pos, seq_len):
        self.step_calls += 1
        logits = np.full((1, self.vocab), -100.0, np.float32)
        logits[0, self.script[self.i]] = 100.0
        self.i += 1
        return logits

    def decode_chunk(self, last_token, pos, n_steps, sampling, key, ring, ring_idx):
        self.chunk_calls.append(n_steps)
        ids = self.script[self.i : self.i + n_steps]
        self.i += n_steps
        return np.asarray([ids], np.int32), key


def make_scripted(script, chunk):
    cfg = LlamaConfig.tiny()
    step = ScriptedFusedStep(script)
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        decode_chunk_size=chunk,
    )
    return gen, step


def test_fused_eos_mid_chunk_truncates():
    eos = 259
    script = [ord("A"), ord("B"), eos, ord("X"), ord("Y"), ord("Z"), ord("W")]
    gen, step = make_scripted(script, 4)
    gen.add_message(Message.user("x"))
    text = gen.generate(10)
    assert text == "AB"
    assert gen.last_finish_reason == "stop"
    # Token history ends AT the EOS — the chunk tail was discarded.
    assert gen.generated_token_ids[-1] == eos
    assert len(gen.generated_token_ids) == 3
    assert step.chunk_calls == [4]
    assert step.step_calls == 1  # prefill only


def test_fused_tail_falls_back_to_per_step():
    script = [ord(c) for c in "ABCDEFGHIJ"]
    gen, step = make_scripted(script, 4)
    gen.add_message(Message.user("x"))
    text = gen.generate(10)
    assert text == "ABCDEFGHIJ"
    assert gen.last_finish_reason == "length"
    # 1 prefill step + 2 full chunks (4+4) + 1 leftover... budget math:
    # after first token, 9 remain -> chunks [4, 4], then 1 per-step tail.
    assert step.chunk_calls == [4, 4]
    assert step.step_calls == 2  # prefill + 1 tail token


def _gen_with_step(step, cfg, sampling, chunk):
    return LlamaGenerator(cfg, step, ByteTokenizer(), sampling, decode_chunk_size=chunk)


def test_fused_pipeline_matches_per_step():
    """Mesh backend: fused scan over the shard_mapped pipeline == per-step."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(3), np.float32)
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8)
    outs = []
    for chunk in (1, 4):
        step = PipelineRunner(
            cfg, params, [(0, 2), (2, 4)], max_seq_len=64, cache_dtype=np.float32
        )
        gen = _gen_with_step(step, cfg, s, chunk)
        gen.add_message(Message.user("pipeline story"))
        outs.append((gen.generate(9), list(gen.generated_token_ids)))
    assert outs[0] == outs[1]


def test_fused_tensor_parallel_matches_per_step():
    """tp backend: fused scan with in-scan psums == per-step decode."""
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny()
    params = M.init_params(cfg, jax.random.PRNGKey(5), np.float32)
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    outs = []
    for chunk in (1, 4):
        step = TensorParallelRunner(
            cfg, params, tp=2, max_seq_len=64, cache_dtype=np.float32
        )
        gen = _gen_with_step(step, cfg, s, chunk)
        gen.add_message(Message.user("tp story"))
        outs.append((gen.generate(9), list(gen.generated_token_ids)))
    assert outs[0] == outs[1]


# ===================================================================== op
# fusion (ISSUE 13): the decode hot-path kernels and their dispatch. Every
# fusion is BIT-IDENTICAL to the unfused arithmetic on fp32 CPU — the
# engine-level tests pin whole streams, the kernel-level tests pin each
# kernel (interpret mode) against its XLA twin, which IS the unfused path.

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
SAMPLED = SamplingConfig(
    temperature=0.9, top_k=20, repeat_penalty=1.1, repeat_last_n=8, seed=11
)


@pytest.fixture(scope="module")
def fmodel():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(7), np.float32)
    return cfg, params


def _engine_streams(
    cfg, params, fusion, *, kv_mode="paged", prefix=False, spec_k=0,
    sampling=GREEDY, rounds=1,
):
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    eng = BatchEngine(
        dataclasses.replace(cfg, fusion_impl=fusion), params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=np.float32, speculative_k=spec_k,
        serve=ServeConfig(
            max_batch=4, decode_chunk_size=4, kv_mode=kv_mode, page_size=16,
            prefix_cache=prefix,
        ),
    )
    eng.start()
    outs = []
    try:
        for _ in range(rounds):
            hs = [
                eng.submit([Message.user(p)], 10, sampling)
                for p in ("shared system prompt: a", "shared system prompt: bb")
            ]
            outs.append([[t.id for t in h.tokens()] for h in hs])
            assert eng.quiesce(30.0)
    finally:
        eng.stop()
    return outs


@pytest.mark.parametrize("kv_mode", ["dense", "paged"])
@pytest.mark.parametrize(
    "sampling", [GREEDY, SAMPLED], ids=["greedy", "sampled"]
)
def test_fused_streams_bit_identical(fmodel, kv_mode, sampling):
    """fusion_impl=all (twin AND pallas kernels) == unfused, dense + paged,
    greedy + sampled: whole engine streams, token for token."""
    cfg, params = fmodel
    base = _engine_streams(cfg, params, "none", kv_mode=kv_mode, sampling=sampling)
    for spec in ("all", "all@pallas"):
        got = _engine_streams(
            cfg, params, spec, kv_mode=kv_mode, sampling=sampling
        )
        assert got == base, f"{spec} diverged under {kv_mode}"


def test_fused_per_fusion_opt_in_bit_identical(fmodel):
    """Each fusion opts in independently and alone preserves the stream."""
    cfg, params = fmodel
    base = _engine_streams(cfg, params, "none", sampling=SAMPLED)
    for spec in ("norm", "ingest", "tail", "norm,tail"):
        assert _engine_streams(cfg, params, spec, sampling=SAMPLED) == base


def test_fused_warm_prefix_cache_identical_to_cold(fmodel):
    """Warm (prefix-cache fork) rounds under fusion == cold rounds == the
    unfused engine's rounds — the fusions compose with the PR 8 suffix
    arithmetic without perturbing a byte."""
    cfg, params = fmodel
    base = _engine_streams(cfg, params, "none", prefix=True, rounds=2)
    assert base[0] == base[1]  # warm == cold, the PR 8 contract
    for spec in ("all", "all@pallas"):
        got = _engine_streams(cfg, params, spec, prefix=True, rounds=2)
        assert got == base


def test_fused_spec_verify_round_unaffected(fmodel):
    """Speculative rounds (paged verify) under fusion_impl=all emit the
    same accepted stream: the verify chunk keeps the unfused cached-chunk
    path (multi-token), and the fusions around it are exact."""
    cfg, params = fmodel
    base = _engine_streams(cfg, params, "none", spec_k=3)
    for spec in ("all", "all@pallas"):
        assert _engine_streams(cfg, params, spec, spec_k=3) == base


# ----------------------------------------------------- kernel-vs-twin oracles


def test_norm_matmul_kernel_matches_unfused_bits():
    """fused_norm_matmul (interpret) == rms_norm + qmat, bitwise, across
    out-tile counts and the Gemma (1 + w) offset."""
    from cake_tpu.ops.norm import rms_norm
    from cake_tpu.ops.pallas.fused_norm_matmul import fused_norm_matmul
    from cake_tpu.ops.quant import qmat

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (3, 1, 96), jnp.float32) * 3.0
    nw = jax.random.normal(jax.random.PRNGKey(1), (96,), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (96, 384), jnp.float32)
    for offset in (False, True):
        for block_n in (128, 384):
            got = fused_norm_matmul(
                x, nw, w, eps=1e-5, offset=offset, impl="pallas",
                block_n=block_n, interpret=True,
            )
            want = qmat(rms_norm(x, nw, 1e-5, offset), w)
            assert got.dtype == want.dtype
            assert jnp.array_equal(got, want), (offset, block_n)


def test_norm_matmul_untiled_out_dim_takes_twin():
    """An output dim that does not tile into 128 lanes silently (and
    bit-identically) runs the twin — never a wrong kernel launch."""
    from cake_tpu.ops.norm import rms_norm
    from cake_tpu.ops.pallas.fused_norm_matmul import (
        fused_norm_matmul,
        norm_matmul_supported,
    )
    from cake_tpu.ops.quant import qmat

    x = jax.random.normal(jax.random.PRNGKey(0), (2, 1, 64), jnp.float32)
    nw = jnp.ones((64,), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 96), jnp.float32)
    assert not norm_matmul_supported(w)
    got = fused_norm_matmul(x, nw, w, eps=1e-5, impl="pallas")
    assert jnp.array_equal(got, qmat(rms_norm(x, nw, 1e-5, False), w))


def _rand_qkv(key, b, n_q, n_kv, hd):
    qkv_dim = (n_q + 2 * n_kv) * hd
    ks = jax.random.split(key, 3)
    qkv = jax.random.normal(ks[0], (b, 1, qkv_dim), jnp.float32)
    cos = jax.random.normal(ks[1], (b, 1, hd // 2), jnp.float32)
    sin = jax.random.normal(ks[2], (b, 1, hd // 2), jnp.float32)
    return qkv, cos, sin


def _jit_ingest(n_q, n_kv, impl, paged):
    """Both oracle sides run UNDER jit, as they do in the decode scan: the
    bit-identity contract is between compiled paths (an eager evaluation
    re-associates the rope multiply-adds differently than XLA's fused
    graph — not a divergence any serving path can observe)."""
    import functools

    from cake_tpu.ops.pallas.fused_ingest import fused_qkv_ingest

    if paged:
        def run(qkv, cos, sin, pos, k, v, tables):
            return fused_qkv_ingest(
                qkv, cos, sin, pos, k, v, n_q=n_q, n_kv=n_kv,
                block_tables=tables, impl=impl, interpret=True,
            )
    else:
        def run(qkv, cos, sin, pos, k, v):
            return fused_qkv_ingest(
                qkv, cos, sin, pos, k, v, n_q=n_q, n_kv=n_kv,
                impl=impl, interpret=True,
            )
    return jax.jit(run)


def test_ingest_kernel_dense_matches_twin_bits():
    """Dense fused_qkv_ingest (interpret): roped q and the slot write are
    bitwise the twin's (apply_rope + write_layer); every other cache byte
    is untouched."""
    b, n_q, n_kv, hd, max_seq = 3, 4, 2, 16, 64
    qkv, cos, sin = _rand_qkv(jax.random.PRNGKey(3), b, n_q, n_kv, hd)
    base = jax.random.normal(
        jax.random.PRNGKey(4), (b, n_kv, max_seq, hd), jnp.float32
    )
    pos = jnp.int32(17)
    q_t, k_t, v_t = _jit_ingest(n_q, n_kv, "xla", False)(
        qkv, cos, sin, pos, base, base + 1.0
    )
    q_p, k_p, v_p = _jit_ingest(n_q, n_kv, "pallas", False)(
        qkv, cos, sin, pos, base, base + 1.0
    )
    assert jnp.array_equal(q_p, q_t)
    assert jnp.array_equal(k_p, k_t)
    assert jnp.array_equal(v_p, v_t)
    # The slot changed; everything else is byte-stable.
    assert not jnp.array_equal(k_p[:, :, 17], base[:, :, 17])
    mask = jnp.arange(max_seq) != 17
    assert jnp.array_equal(k_p[:, :, mask], base[:, :, mask])


def test_ingest_kernel_paged_scattered_pages_and_unmapped_drop():
    """Paged fused_qkv_ingest with SCATTERED physical pages: the write
    resolves through the block table (ignored indirection fails loudly on
    non-uniform pages), an UNMAPPED lane's write DROPS (paged_write_layer
    semantics), and untouched pool pages stay byte-stable."""
    b, n_q, n_kv, hd, ps, n_pages = 3, 4, 2, 16, 8, 7
    qkv, cos, sin = _rand_qkv(jax.random.PRNGKey(5), b, n_q, n_kv, hd)
    pool = jax.random.normal(
        jax.random.PRNGKey(6), (n_pages, n_kv, ps, hd), jnp.float32
    )
    # Row 0 -> physical 5, row 1 -> physical 2 (scattered), row 2 UNMAPPED.
    tables = jnp.asarray(
        [[3, 5, -1], [6, 2, -1], [-1, -1, -1]], jnp.int32
    )
    pos = jnp.int32(11)  # logical page 1, offset 3
    q_t, k_t, v_t = _jit_ingest(n_q, n_kv, "xla", True)(
        qkv, cos, sin, pos, pool, pool + 1.0, tables
    )
    q_p, k_p, v_p = _jit_ingest(n_q, n_kv, "pallas", True)(
        qkv, cos, sin, pos, pool, pool + 1.0, tables
    )
    assert jnp.array_equal(q_p, q_t)
    assert jnp.array_equal(k_p, k_t)
    assert jnp.array_equal(v_p, v_t)
    # The two mapped rows landed at their scattered physical pages...
    assert not jnp.array_equal(k_p[5, :, 3], pool[5, :, 3])
    assert not jnp.array_equal(k_p[2, :, 3], pool[2, :, 3])
    # ...the unmapped row dropped, and untouched pages are byte-stable.
    for page in (0, 1, 3, 4, 6):
        assert jnp.array_equal(k_p[page], pool[page])


def test_ingest_kernel_paged_out_of_table_slot_drops():
    """A slot past the table's logical pages drops (the logical-before-
    physical clamp): no write, no crash — both impls."""
    from cake_tpu.ops.pallas.fused_ingest import fused_qkv_ingest

    b, n_q, n_kv, hd, ps, n_pages = 1, 2, 1, 16, 8, 3
    qkv, cos, sin = _rand_qkv(jax.random.PRNGKey(8), b, n_q, n_kv, hd)
    pool = jnp.zeros((n_pages, n_kv, ps, hd), jnp.float32)
    tables = jnp.asarray([[1]], jnp.int32)  # one logical page: slots [0, 8)
    pos = jnp.int32(9)  # logical page 1: past the table
    for impl in ("xla", "pallas"):
        _, k_o, v_o = fused_qkv_ingest(
            qkv, cos, sin, pos, pool, pool, n_q=n_q, n_kv=n_kv,
            block_tables=tables, impl=impl, interpret=True,
        )
        assert jnp.array_equal(k_o, pool), impl
        assert jnp.array_equal(v_o, pool), impl


def _tail_ref(logits, ring, key, s):
    """The UNFUSED sampling tail — fused.sample_step with tail_impl=None."""
    from cake_tpu.models.llama.fused import sample_step

    nxt, _, _, _ = sample_step(
        logits, key, ring, jnp.zeros((logits.shape[0],), jnp.int32),
        temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
        repeat_penalty=s.repeat_penalty,
    )
    return nxt


def _tail_fused(logits, ring, key, s, impl):
    from cake_tpu.models.llama.fused import sample_step

    nxt, _, _, _ = sample_step(
        logits, key, ring, jnp.zeros((logits.shape[0],), jnp.int32),
        temperature=s.temperature, top_k=s.top_k, top_p=s.top_p,
        repeat_penalty=s.repeat_penalty, tail_impl=impl,
    )
    return nxt


@pytest.mark.parametrize(
    "s",
    [
        SamplingConfig(temperature=0.0, repeat_penalty=1.2, repeat_last_n=4),
        SamplingConfig(temperature=0.7, top_k=5, repeat_penalty=1.1),
        SamplingConfig(temperature=0.7, top_k=None, repeat_penalty=1.0),
    ],
    ids=["greedy+penalty", "topk+penalty", "plain"],
)
@pytest.mark.parametrize("per_row", [True, False], ids=["row-keys", "shared"])
def test_sample_tail_kernel_matches_unfused_bits(s, per_row):
    """fused_sample_tail (interpret AND twin) == the unfused sample_step
    chain, per-row and shared-stream keys, duplicate-heavy logits included
    (the top-k descent must count duplicates exactly like lax.top_k)."""
    b, vocab = 4, 256
    logits = jax.random.normal(jax.random.PRNGKey(9), (b, vocab), jnp.float32)
    # Quantize to force duplicate logit values — the top-k tie shape.
    logits = jnp.round(logits * 4) / 4
    ring = jnp.asarray(
        [[1, 2, -1, -1], [7, 7, 3, -1], [-1] * 4, [250, 0, 1, 2]], jnp.int32
    )[:, : max(1, s.repeat_last_n or 4)]
    key = jax.random.PRNGKey(42)
    if per_row:
        key = jax.random.split(key, b)
    want = _tail_ref(logits, ring, key, s)
    for impl in ("xla", "pallas"):
        got = _tail_fused(logits, ring, key, s, impl)
        assert jnp.array_equal(got, want), impl


def test_sample_tail_top_p_falls_back_bit_identically():
    """top_p set: the kernel path is refused in favor of the XLA sort twin
    — and the stream still byte-matches the unfused path."""
    s = SamplingConfig(temperature=0.8, top_p=0.9, repeat_penalty=1.1)
    b, vocab = 3, 256
    logits = jax.random.normal(jax.random.PRNGKey(10), (b, vocab), jnp.float32)
    ring = jnp.full((b, 4), -1, jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(1), b)
    want = _tail_ref(logits, ring, keys, s)
    for impl in ("xla", "pallas"):
        assert jnp.array_equal(_tail_fused(logits, ring, keys, s, impl), want)


def test_sample_tail_all_masked_and_nan_guards():
    """All -inf rows and NaN-carrying rows produce exactly what the unfused
    path produces (index 0 for a fully dead row) — no crash, no divergence."""
    vocab = 256
    dead = jnp.full((2, vocab), -jnp.inf, jnp.float32)
    ring = jnp.full((2, 4), -1, jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(2), 2)
    for s in (
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        SamplingConfig(temperature=0.9, top_k=4, repeat_penalty=1.0),
    ):
        want = _tail_ref(dead, ring, keys, s)
        for impl in ("xla", "pallas"):
            got = _tail_fused(dead, ring, keys, s, impl)
            assert jnp.array_equal(got, want)
            assert jnp.array_equal(got, jnp.zeros((2,), jnp.int32))
    nan_row = dead.at[:, 7].set(jnp.nan)
    sg = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    want = _tail_ref(nan_row, ring, keys, sg)
    for impl in ("xla", "pallas"):
        assert jnp.array_equal(_tail_fused(nan_row, ring, keys, sg, impl), want)


def test_sample_tail_untiled_vocab_refuses():
    """A vocab that does not tile into 128 lanes is a LOUD ValueError on
    the kernel path — never a silently wrong launch."""
    from cake_tpu.ops.pallas.fused_sample_tail import fused_sample_tail

    logits = jnp.zeros((2, 250), jnp.float32)
    ring = jnp.full((2, 2), -1, jnp.int32)
    with pytest.raises(ValueError, match="128-lane"):
        fused_sample_tail(
            logits, ring, None, temperature=0.0, top_k=None, top_p=None,
            repeat_penalty=1.0, impl="pallas",
        )


def test_fused_fallback_event_fires_exactly_once(fmodel):
    """fusion all@pallas + top_p: the tail runs the documented XLA sort
    fallback and surfaces ONE kernel-fallback flight event across many
    decode dispatches; an xla-by-choice fusion run emits none."""
    cfg, params = fmodel
    metrics.flight.clear()
    s = SamplingConfig(temperature=0.8, top_p=0.9, repeat_penalty=1.0, seed=2)
    _engine_streams(cfg, params, "all@pallas", sampling=s, rounds=2)
    events = [
        e for e in metrics.flight.snapshot()
        if e["event"] == "kernel-fallback"
    ]
    assert len(events) == 1
    assert events[0]["op"] == "fused_sample_tail"
    metrics.flight.clear()
    _engine_streams(cfg, params, "all@xla", sampling=s)
    assert not [
        e for e in metrics.flight.snapshot()
        if e["event"] == "kernel-fallback"
    ]


def test_sample_tail_untiled_vocab_downgrades_in_sample_step():
    """The SERVING dispatch (sample_step) downgrades an untileable vocab to
    the twin instead of raising — the same sample_tail_supported rule the
    backends' kernel-fallback note reads, so note and dispatch agree; only
    direct kernel calls refuse loudly (the test above)."""
    from cake_tpu.models.llama.fused import sample_step

    b, vocab = 2, 250  # not a 128-lane multiple
    logits = jax.random.normal(jax.random.PRNGKey(4), (b, vocab), jnp.float32)
    ring = jnp.full((b, 4), -1, jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(5), b)
    ridx = jnp.zeros((b,), jnp.int32)
    kw = dict(temperature=0.7, top_k=5, top_p=None, repeat_penalty=1.1)
    want, *_ = sample_step(logits, keys, ring, ridx, **kw)
    got, *_ = sample_step(logits, keys, ring, ridx, tail_impl="pallas", **kw)
    assert jnp.array_equal(got, want)
