"""Fused multi-token decode (models/llama/fused.py): parity with per-step path."""

import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

import jax


def make_gen(sampling: SamplingConfig, chunk: int) -> LlamaGenerator:
    cfg = LlamaConfig.tiny()
    params = M.init_params(cfg, jax.random.PRNGKey(7), np.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=np.float32)
    return LlamaGenerator(
        cfg, step, ByteTokenizer(), sampling, decode_chunk_size=chunk
    )


@pytest.mark.parametrize(
    "sampling",
    [
        SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0),
        SamplingConfig(temperature=0.9, top_k=20, repeat_penalty=1.1, seed=123),
    ],
    ids=["greedy+penalty", "greedy-no-penalty", "sampled"],
)
def test_fused_matches_per_step(sampling):
    """Same params + seed: chunked decode must emit the identical token stream.

    Covers the penalty-ring reseeding, PRNG split ordering, and position
    bookkeeping all at once; 11 tokens with chunk 4 exercises first-token
    per-step entry, two full fused chunks, and a per-step tail.
    """
    outs = []
    for chunk in (1, 4):
        gen = make_gen(sampling, chunk)
        gen.add_message(Message.user("tell me a story"))
        text = gen.generate(11)
        outs.append((text, list(gen.generated_token_ids)))
    (t1, ids1), (t4, ids4) = outs
    assert ids1 == ids4
    assert t1 == t4
    assert len(ids1) == 11 or 259 in ids1 or 260 in ids1


def test_fused_chunk_composes_with_continued_decode():
    """State after a fused chunk must let per-step decode continue seamlessly."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=6)
    ref = make_gen(s, 1)
    ref.add_message(Message.user("abc"))
    want = ref.generate(9)

    gen = make_gen(s, 4)
    gen.add_message(Message.user("abc"))
    first = gen.generate(5)  # 1 per-step + 1 fused chunk of 4
    rest = gen.generate(4)  # continues the same sequence per-step/fused
    assert (first + rest) == want


class ScriptedFusedStep:
    """Fake step with decode_chunk: scripted ids, records call granularity."""

    max_seq_len = 64

    def __init__(self, script, vocab=512):
        self.script = list(script)
        self.vocab = vocab
        self.i = 0
        self.chunk_calls = []
        self.step_calls = 0

    def reset(self):
        self.i = 0

    def __call__(self, tokens, pos, seq_len):
        self.step_calls += 1
        logits = np.full((1, self.vocab), -100.0, np.float32)
        logits[0, self.script[self.i]] = 100.0
        self.i += 1
        return logits

    def decode_chunk(self, last_token, pos, n_steps, sampling, key, ring, ring_idx):
        self.chunk_calls.append(n_steps)
        ids = self.script[self.i : self.i + n_steps]
        self.i += n_steps
        return np.asarray([ids], np.int32), key


def make_scripted(script, chunk):
    cfg = LlamaConfig.tiny()
    step = ScriptedFusedStep(script)
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        decode_chunk_size=chunk,
    )
    return gen, step


def test_fused_eos_mid_chunk_truncates():
    eos = 259
    script = [ord("A"), ord("B"), eos, ord("X"), ord("Y"), ord("Z"), ord("W")]
    gen, step = make_scripted(script, 4)
    gen.add_message(Message.user("x"))
    text = gen.generate(10)
    assert text == "AB"
    assert gen.last_finish_reason == "stop"
    # Token history ends AT the EOS — the chunk tail was discarded.
    assert gen.generated_token_ids[-1] == eos
    assert len(gen.generated_token_ids) == 3
    assert step.chunk_calls == [4]
    assert step.step_calls == 1  # prefill only


def test_fused_tail_falls_back_to_per_step():
    script = [ord(c) for c in "ABCDEFGHIJ"]
    gen, step = make_scripted(script, 4)
    gen.add_message(Message.user("x"))
    text = gen.generate(10)
    assert text == "ABCDEFGHIJ"
    assert gen.last_finish_reason == "length"
    # 1 prefill step + 2 full chunks (4+4) + 1 leftover... budget math:
    # after first token, 9 remain -> chunks [4, 4], then 1 per-step tail.
    assert step.chunk_calls == [4, 4]
    assert step.step_calls == 2  # prefill + 1 tail token


def _gen_with_step(step, cfg, sampling, chunk):
    return LlamaGenerator(cfg, step, ByteTokenizer(), sampling, decode_chunk_size=chunk)


def test_fused_pipeline_matches_per_step():
    """Mesh backend: fused scan over the shard_mapped pipeline == per-step."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(3), np.float32)
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8)
    outs = []
    for chunk in (1, 4):
        step = PipelineRunner(
            cfg, params, [(0, 2), (2, 4)], max_seq_len=64, cache_dtype=np.float32
        )
        gen = _gen_with_step(step, cfg, s, chunk)
        gen.add_message(Message.user("pipeline story"))
        outs.append((gen.generate(9), list(gen.generated_token_ids)))
    assert outs[0] == outs[1]


def test_fused_tensor_parallel_matches_per_step():
    """tp backend: fused scan with in-scan psums == per-step decode."""
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny()
    params = M.init_params(cfg, jax.random.PRNGKey(5), np.float32)
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    outs = []
    for chunk in (1, 4):
        step = TensorParallelRunner(
            cfg, params, tp=2, max_seq_len=64, cache_dtype=np.float32
        )
        gen = _gen_with_step(step, cfg, s, chunk)
        gen.add_message(Message.user("tp story"))
        outs.append((gen.generate(9), list(gen.generated_token_ids)))
    assert outs[0] == outs[1]
