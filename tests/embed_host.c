/* Minimal C host for libcakeembed.so (tests/test_embed_cabi.py).
 *
 * Proves the "embed a worker in any app" capability end-to-end from a
 * NON-Python host: starts a worker in the background, reports its bound
 * port, serves until stdin closes, then stops cleanly.
 *
 * Usage: embed_host <name> <model_dir> <topology.yml>
 * Prints "READY <port>" once serving.
 */
#include <stdio.h>

extern long cake_start_worker_background(const char *name,
                                         const char *model_path,
                                         const char *topology_path,
                                         const char *bind_address);
extern int cake_worker_port(long handle);
extern int cake_stop_worker(long handle);
extern const char *cake_last_error(void);

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <name> <model_dir> <topology.yml>\n", argv[0]);
    return 2;
  }
  long h =
      cake_start_worker_background(argv[1], argv[2], argv[3], "127.0.0.1:0");
  if (h < 0) {
    fprintf(stderr, "start failed: %s\n", cake_last_error());
    return 1;
  }
  int port = cake_worker_port(h);
  if (port <= 0) {
    fprintf(stderr, "port lookup failed: %s\n", cake_last_error());
    return 1;
  }
  printf("READY %d\n", port);
  fflush(stdout);
  char buf[64];
  while (fgets(buf, sizeof buf, stdin) != NULL) {
    /* serve until the orchestrator closes stdin */
  }
  return cake_stop_worker(h) == 0 ? 0 : 1;
}
