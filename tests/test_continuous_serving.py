"""Continuous scheduler (ISSUE 15): kill the lockstep epoch.

The contract under test (README "Continuous scheduling"):

  * Streams are BIT-IDENTICAL to epoch mode given the same admission order
    — greedy and sampled, dense and paged — because both schedulers walk
    the same per-row arithmetic (batch.first_sample / join / decode), each
    of which is already pinned bit-identical to a solo run.
  * Page pressure PREEMPTS instead of force-finishing: the victim lane's
    page chain spills host-side (history + sampling state at the chunk
    boundary — the _migrate_kv invariant) and a later restore re-attaches
    it through the join/suffix-join arithmetic, bit-identically.
  * The spill table honors the whole request lifecycle: cancel and
    deadline reach spilled lanes, stop() closes them, quiesce sees no
    leaked pages (a spilled lane holds none).
  * Convoy attribution drops to ~0 by construction: finished lanes retire
    immediately and empty lanes are admission headroom, not lockstep tax.
  * Zero steady-state retraces under the armed jit watchdog: lane-count
    churn, joins, spills and restores ride traced operands and the same
    64-bucketed window families epoch mode compiles.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.admission import StepBudget
from cake_tpu.runtime.serving import (
    BatchEngine,
    ServeConfig,
    _RowState,
    _SpilledLane,
)
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
SAMPLED = SamplingConfig(temperature=0.8, top_k=20, repeat_penalty=1.0, seed=7)

# Mixed prompt lengths: the workload shape the continuous scheduler exists
# for (short requests must not pay for long co-batched ones).
MIXED = [
    "short",
    "a medium prompt with some more words in it",
    "the long prompt of this batch, padded out with further words so its "
    "bucket is clearly taller than the short one's",
]


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def make_engine(cfg, params, **serve_kw):
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("decode_chunk_size", 4)
    serve_kw.setdefault("admission_window", 0.05)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32,
        serve=ServeConfig(**serve_kw),
    )
    eng.start()
    return eng


def collect(handle):
    return [tok.id for tok in handle.tokens()]


def serve_all(eng, prompts, n, sampling):
    handles = [eng.submit([Message.user(p)], n, sampling) for p in prompts]
    return [collect(h) for h in handles], handles


# ------------------------------------------------- epoch-vs-continuous parity


@pytest.mark.parametrize("sampling", [GREEDY, SAMPLED], ids=["greedy", "sampled"])
def test_continuous_dense_streams_match_epoch(sampling):
    cfg, params = setup()
    got = {}
    for sched in ("epoch", "continuous"):
        eng = make_engine(cfg, params, scheduler=sched)
        got[sched], handles = serve_all(eng, MIXED, 10, sampling)
        assert all(
            h.finish_reason in ("stop", "length") for h in handles
        )
        eng.stop()
    assert got["continuous"] == got["epoch"]


@pytest.mark.parametrize("prefix", [False, True], ids=["plain", "prefix"])
def test_continuous_paged_streams_match_epoch(prefix):
    cfg, params = setup(seed=32)
    got = {}
    for sched in ("epoch", "continuous"):
        eng = make_engine(
            cfg, params, scheduler=sched, kv_mode="paged", page_size=16,
            prefix_cache=prefix,
        )
        got[sched], _ = serve_all(eng, MIXED, 10, GREEDY)
        assert eng.quiesce()
        eng.stop()
    assert got["continuous"] == got["epoch"]


def test_continuous_late_submission_joins_bit_exact():
    """A request submitted while the segment is decoding joins it and is
    still bit-identical to its epoch-mode stream."""
    cfg, params = setup(seed=33)
    got = {}
    for sched in ("epoch", "continuous"):
        eng = make_engine(cfg, params, scheduler=sched)
        h0 = eng.submit([Message.user("the first, long-running stream")],
                        24, GREEDY)
        deadline = time.time() + 30
        while h0.completion_tokens < 2 and time.time() < deadline:
            time.sleep(0.005)
        h1 = eng.submit([Message.user("late joiner")], 8, GREEDY)
        got[sched] = (collect(h0), collect(h1))
        eng.stop()
    assert got["continuous"] == got["epoch"]
    # (both joined mid-flight; the join machinery is pinned bit-exact
    # against solo runs by test_serving.py)


# ------------------------------------------------------- preemption/restore


@pytest.mark.parametrize("sampling", [GREEDY, SAMPLED], ids=["greedy", "sampled"])
@pytest.mark.parametrize("prefix", [False, True], ids=["plain", "prefix"])
def test_preemption_spill_restore_bit_identical(prefix, sampling):
    """Page pressure preempts (spills) instead of force-finishing, and the
    restored stream is bit-identical to an unpressured run — greedy AND
    sampled (the PRNG key and penalty ring ride the spill), with and
    without the prefix cache (the restore walks the suffix arithmetic)."""
    cfg, params = setup()
    prompts = [
        "alpha prompt padded out to be long " * 2,
        "row two also made quite long here " * 2,
    ]

    def run(max_pages):
        eng = make_engine(
            cfg, params, scheduler="continuous", kv_mode="paged",
            page_size=16, max_pages=max_pages, prefix_cache=prefix,
        )
        out, handles = serve_all(eng, prompts, 48, sampling)
        stats = dict(eng.stats)
        assert eng.quiesce()
        with eng._cv:
            assert not eng._spilled  # no leaked spilled chains
        alloc = eng.backend.allocator
        held = eng._prefix.stats()["pages"] if eng._prefix else 0
        assert alloc.pages_free == alloc.pages_total - held
        eng.stop()
        return out, stats, [h.finish_reason for h in handles]

    want, st_big, fin_big = run(64)
    got, st_small, fin_small = run(14)
    assert st_big["preemptions"] == 0
    assert st_small["preemptions"] >= 1 and st_small["restores"] >= 1
    assert got == want  # spill/restore round trip is bit-identical
    # Nobody was force-finished by the pressure: same finish reasons.
    assert fin_small == fin_big


def test_preemption_victim_is_lowest_priority():
    cfg, params = setup()
    eng = make_engine(
        cfg, params, scheduler="continuous", kv_mode="paged",
        page_size=16, max_pages=14,
    )
    lo = eng.submit(
        [Message.user("alpha prompt padded out to be long " * 2)], 48,
        GREEDY, priority=0,
    )
    hi = eng.submit(
        [Message.user("row two also made quite long here " * 2)], 48,
        GREEDY, priority=2,
    )
    collect(lo), collect(hi)
    assert eng.stats["preemptions"] >= 1
    preempted = {
        e["request_id"]
        for e in metrics.flight.snapshot()
        if e["event"] == "preempted"
    }
    assert lo.request_id in preempted
    assert hi.request_id not in preempted
    eng.stop()


def test_spilled_lane_restores_via_spill_seeded_segment():
    """A spill that cannot re-attach inside its segment (the remaining
    budget no longer fits the segment's bounded capacity) waits out the
    drain and restores as the SEED of a fresh spill-seeded segment —
    bit-identical to the unpressured run, across the segment boundary."""
    cfg, params = setup()

    def run(max_pages):
        eng = BatchEngine(
            cfg, params, ByteTokenizer(),
            max_seq_len=512, cache_dtype=jnp.float32,
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=4, admission_window=0.1,
                scheduler="continuous", kv_mode="paged", page_size=16,
                max_pages=max_pages,
            ),
        )
        eng.start()
        h1 = eng.submit(
            [Message.user("alpha prompt padded out to be long " * 2)],
            140, GREEDY, priority=2,
        )
        h2 = eng.submit(
            [Message.user("row two also made quite long here " * 2)],
            48, GREEDY, priority=0,
        )
        out = (collect(h1), collect(h2))
        stats = dict(eng.stats)
        assert eng.quiesce()
        with eng._cv:
            assert not eng._spilled
        eng.stop()
        return out, stats

    want, st_big = run(64)
    got, st = run(15)
    assert st["preemptions"] >= 1 and st["restores"] >= 1
    assert st["page_truncations"] == 0  # preemption REPLACED force-finish
    # The restore rode a second, spill-seeded segment (the in-segment
    # path is covered by test_preemption_spill_restore_bit_identical).
    assert st["batches"] > st_big["batches"]
    assert got == want


def test_cancel_reaches_spilled_lane():
    """cancel() on a spilled rid finishes the stream immediately — no
    pages to free, the spill table entry is gone, cancel is idempotent."""
    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32,
        serve=ServeConfig(max_batch=2, scheduler="continuous"),
    )
    # Engine NOT started: forge the spill state deterministically.
    h = eng.submit([Message.user("park me")], 8, GREEDY)
    with eng._cv:
        req = next(iter(eng._queue))
        eng._queue.remove(req)
    row = _RowState(req, set(), ByteTokenizer(), lane=0, engine=eng)
    row.history.append(5)  # the pending token
    with eng._cv:
        eng._spilled[req.rid] = _SpilledLane(
            row=row, key=np.zeros((2,), np.uint32), ring=None, ring_idx=0,
        )
    assert eng.cancel(req.rid) is True
    assert collect(h) == []
    assert h.finish_reason == "cancelled"
    with eng._cv:
        assert not eng._spilled
    assert eng.cancel(req.rid) is False


def test_deadline_reaches_spilled_lane():
    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32,
        serve=ServeConfig(max_batch=2, scheduler="continuous"),
    )
    h = eng.submit([Message.user("expire me")], 8, GREEDY, deadline_s=0.01)
    with eng._cv:
        req = next(iter(eng._queue))
        eng._queue.remove(req)
    row = _RowState(req, set(), ByteTokenizer(), lane=0, engine=eng)
    row.history.append(5)
    with eng._cv:
        eng._spilled[req.rid] = _SpilledLane(
            row=row, key=np.zeros((2,), np.uint32), ring=None, ring_idx=0,
        )
    time.sleep(0.02)
    eng._apply_deadlines([])  # the chunk-boundary sweep reaches spills
    assert collect(h) == []
    assert h.finish_reason == "deadline"
    with eng._cv:
        assert not eng._spilled


# ------------------------------------------------------- convoy + step obs


def test_continuous_convoy_frac_below_epoch():
    """The headline A/B: on a mixed-length workload the continuous
    scheduler's measured convoy fraction is strictly below epoch mode's
    (finished lanes retire; empty lanes are headroom, not tax)."""
    cfg, params = setup()
    frac = {}
    for sched in ("epoch", "continuous"):
        eng = make_engine(cfg, params, scheduler=sched)
        budgets = [24, 6, 6]
        handles = [
            eng.submit([Message.user(p)], n, GREEDY)
            for p, n in zip(MIXED, budgets)
        ]
        for h in handles:
            collect(h)
        # Streams close BEFORE the epoch's finally runs the convoy meter
        # (the documented quiesce race) — poll for the meter.
        deadline = time.time() + 30
        while time.time() < deadline:
            with eng._phase_lock:
                cv = dict(eng.convoy_stats)
            if cv["epochs"] >= 1:
                break
            time.sleep(0.01)
        assert cv["epochs"] >= 1
        frac[sched] = cv["frac_sum"] / cv["epochs"]
        eng.stop()
    assert frac["continuous"] < frac["epoch"]


def test_continuous_emits_segment_and_step_spans():
    from cake_tpu.obs.timeline import timeline

    cfg, params = setup()
    eng = make_engine(cfg, params, scheduler="continuous")
    h = eng.submit([Message.user("spans please")], 8, GREEDY)
    collect(h)
    eng.stop()
    names = {e["name"] for e in timeline.snapshot()}
    assert "segment" in names and "step" in names
    assert "epoch" not in names  # step spans REPLACE epoch spans


def test_restore_phase_reaches_explain():
    """A preempted request's /explain decomposition carries the restore
    phase (the price its spill cost it) and still sums to the wall."""
    from cake_tpu.obs import critpath
    from cake_tpu.obs.timeline import timeline

    cfg, params = setup()
    eng = make_engine(
        cfg, params, scheduler="continuous", kv_mode="paged",
        page_size=16, max_pages=14,
    )
    prompts = [
        "alpha prompt padded out to be long " * 2,
        "row two also made quite long here " * 2,
    ]
    _, handles = serve_all(eng, prompts, 48, GREEDY)
    assert eng.stats["restores"] >= 1
    events = timeline.snapshot()
    restored_rids = {
        e["rid"] for e in events if e["name"] == "restore" and e.get("rid")
    }
    assert restored_rids
    rid = next(iter(restored_rids))
    res = critpath.explain(events, rid)
    assert res is not None
    assert res["phases"]["restore"] > 0.0
    # The structural pin of the merged-span decomposition: preemption
    # split the lane into (at least) a pre-spill and a post-restore
    # request span, and the explained wall covers FIRST open to LAST
    # close — before spans merged, latest-wins dropped the pre-spill
    # compute and the parked gap from the wall entirely.
    opens = [
        e for e in events
        if e.get("ph") == "B" and e.get("name") == "request"
        and e.get("rid") == rid
    ]
    closes = {
        e["id"]: e for e in events if e.get("ph") == "E" and "id" in e
    }
    assert len(opens) >= 2
    t0 = min(float(e["mono"]) for e in opens)
    t1 = max(
        float(closes[e["id"]]["mono"])
        for e in opens
        if e.get("id") in closes
    )
    assert res["wall_s"] >= (t1 - t0) * 0.99
    # Sanity on the attribution quality (host slop on a loaded CPU keeps
    # this below the synthetic-span 0.95 gate).
    assert res["coverage"] >= 0.5
    eng.stop()


# ------------------------------------------------------------- step budget


def test_step_budget_slo_feedback():
    """The SLO-aware prefill grant (runtime/admission.StepBudget): doubled
    under burn, quartered under running-deadline pressure, floored."""
    b = StepBudget()
    base = b.grant()
    assert base == StepBudget.AUTO_TOKENS
    assert b.grant(burning=True) == 2 * base
    # No chunk clock yet: slack cannot be priced, grant unchanged.
    assert b.grant(tightest_slack_s=0.001) == base
    b.observe_chunk(0.1)
    assert b.grant(tightest_slack_s=0.1) == max(
        StepBudget.MIN_TOKENS, base // 4
    )
    assert b.grant(tightest_slack_s=100.0) == base
    explicit = StepBudget(base_tokens=128)
    assert explicit.grant() == 128
    assert explicit.grant(burning=True) == 256


def test_step_budget_defers_joins_to_later_steps():
    """A tiny explicit step budget still serves everyone — candidates over
    the grant wait a step, they are not starved."""
    cfg, params = setup(seed=34)
    eng = make_engine(
        cfg, params, scheduler="continuous", step_prefill_tokens=64,
    )
    out, handles = serve_all(eng, MIXED, 8, GREEDY)
    assert all(h.finish_reason in ("stop", "length") for h in handles)
    # Oracle: same streams as an unbudgeted continuous engine.
    eng2 = make_engine(cfg, params, scheduler="continuous")
    want, _ = serve_all(eng2, MIXED, 8, GREEDY)
    assert out == want
    eng.stop()
    eng2.stop()


# --------------------------------------------------------- zero retraces


def test_continuous_steady_state_never_retraces():
    """Armed jitwatch: once the shape set is warm, a further continuous
    round (admission + joins + decode + retirement) traces NOTHING — lane
    churn stays a traced operand."""
    from cake_tpu.obs import jitwatch as _jw

    cfg, params = setup(seed=35)
    eng = make_engine(
        cfg, params, scheduler="continuous", kv_mode="paged", page_size=16,
    )

    def round_():
        out, _ = serve_all(eng, MIXED, 8, GREEDY)
        assert eng.quiesce()
        return out

    want = round_()
    # Warm until two consecutive trace-free rounds (join lane assignment
    # varies round to round; one quiet round can be luck).
    quiet = 0
    for _ in range(10):
        t0 = _jw.watch.snapshot()
        round_()
        quiet = quiet + 1 if _jw.watch.snapshot() == t0 else 0
        if quiet >= 2:
            break
    assert quiet >= 2
    r0 = _jw.retrace_total()
    _jw.watch.arm()
    try:
        got = round_()
    finally:
        _jw.watch.disarm()
    assert _jw.retrace_total() == r0
    assert got == want
    eng.stop()
