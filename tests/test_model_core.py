"""Model-core tests: config parsing, ops numerics, KV-cache correctness.

The reference framework has zero tests (SURVEY.md §4); the strategy here follows the
seams it *implies*: the single-host full-forward pass is the numerical oracle that
every cached / sharded execution must match.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.attention import gqa_attention
from cake_tpu.ops.norm import rms_norm
from cake_tpu.ops.rope import apply_rope, rope_table


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny()


@pytest.fixture(scope="module")
def params(cfg):
    return M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)


def fresh_cache(cfg, batch=1, max_seq=64, n_layers=None):
    return init_cache(
        n_layers if n_layers is not None else cfg.num_hidden_layers,
        batch,
        max_seq,
        cfg.num_key_value_heads,
        cfg.head_dim,
        jnp.float32,
    )


# ---------------------------------------------------------------- config


def test_config_from_hf_dict_llama3_8b_schema():
    d = {
        "hidden_size": 4096,
        "intermediate_size": 14336,
        "vocab_size": 128256,
        "num_hidden_layers": 32,
        "num_attention_heads": 32,
        "num_key_value_heads": 8,
        "rms_norm_eps": 1e-5,
        "rope_theta": 500000.0,
        "bos_token_id": 128000,
        "eos_token_id": [128001, 128009],
    }
    c = LlamaConfig.from_hf_dict(d)
    assert c.head_dim == 128
    assert c.num_query_groups == 4
    assert c.eos_token_ids == (128001, 128009)


def test_config_mha_fallback_when_kv_heads_missing():
    # Mirrors config.rs:45-58: absent num_key_value_heads => MHA.
    c = LlamaConfig.from_hf_dict({"num_attention_heads": 8, "hidden_size": 64})
    assert c.num_key_value_heads == 8


def test_config_scalar_eos():
    c = LlamaConfig.from_hf_dict({"eos_token_id": 7})
    assert c.eos_token_ids == (7,)


def test_config_roundtrip_via_json(tmp_path):
    c = LlamaConfig.tiny()
    with open(tmp_path / "config.json", "w") as f:
        json.dump(c.to_hf_dict(), f)
    c2 = LlamaConfig.from_model_dir(tmp_path)
    assert c2 == c


def test_config_validates_divisibility():
    with pytest.raises(ValueError):
        LlamaConfig.tiny(num_attention_heads=3)
    with pytest.raises(ValueError):
        LlamaConfig.tiny(num_attention_heads=4, num_key_value_heads=3)


# ---------------------------------------------------------------- ops


def test_rms_norm_matches_reference_formula():
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 16))
    w = jax.random.normal(jax.random.PRNGKey(2), (16,))
    got = rms_norm(x, w, 1e-5)
    xn = np.asarray(x, np.float64)
    expect = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-5) * np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-5, atol=1e-5)


def test_rope_position_consistency():
    # Applying rope to a row of positions must equal applying per-position.
    cos, sin = rope_table(16, 32, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(3), (1, 5, 2, 16))
    full = apply_rope(x, cos, sin, jnp.arange(5, dtype=jnp.int32)[None, :])
    for p in range(5):
        one = apply_rope(
            x[:, p : p + 1], cos, sin, jnp.array([[p]], jnp.int32)
        )
        np.testing.assert_array_equal(np.asarray(full[:, p : p + 1]), np.asarray(one))


def test_rope_position_zero_is_identity():
    cos, sin = rope_table(16, 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 2, 16))
    out = apply_rope(x, cos, sin, jnp.zeros((1, 1), jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)


def test_rope_llama31_scaling_changes_low_freqs_only():
    from cake_tpu.models.llama.config import RopeScaling
    from cake_tpu.ops.rope import rope_frequencies

    plain = rope_frequencies(128, 500000.0)
    scaled = rope_frequencies(128, 500000.0, RopeScaling())
    # High-frequency (early) components untouched; low-frequency ones shrunk.
    assert np.allclose(plain[:8], scaled[:8])
    assert (scaled[-8:] < plain[-8:]).all()


def test_gqa_attention_matches_naive_mha_expansion():
    b, s, n_q, n_kv, hd = 2, 6, 4, 2, 8
    kq = jax.random.split(jax.random.PRNGKey(5), 3)
    q = jax.random.normal(kq[0], (b, s, n_q, hd))
    k = jax.random.normal(kq[1], (b, s, n_kv, hd))
    v = jax.random.normal(kq[2], (b, s, n_kv, hd))
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    got = np.asarray(gqa_attention(q, k, v, pos, pos))

    # Naive: repeat kv heads, per-head softmax(QK^T/sqrt(d)) with causal mask.
    qn, kn, vn = (np.asarray(t, np.float64) for t in (q, k, v))
    kn = np.repeat(kn, n_q // n_kv, axis=2)
    vn = np.repeat(vn, n_q // n_kv, axis=2)
    expect = np.zeros_like(qn)
    for bi in range(b):
        for h in range(n_q):
            scores = qn[bi, :, h] @ kn[bi, :, h].T / np.sqrt(hd)
            mask = np.tril(np.ones((s, s), bool))
            scores = np.where(mask, scores, -np.inf)
            w = np.exp(scores - scores.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            expect[bi, :, h] = w @ vn[bi, :, h]
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)


def test_attention_ignores_future_and_garbage_slots():
    # Keys at positions beyond the query must not affect output — this is what
    # makes the preallocated cache sound (unwritten slots are masked).
    b, n_q, n_kv, hd, max_s = 1, 2, 1, 4, 8
    keys = jax.random.split(jax.random.PRNGKey(6), 3)
    q = jax.random.normal(keys[0], (b, 1, n_q, hd))
    k = jax.random.normal(keys[1], (b, max_s, n_kv, hd))
    v = jax.random.normal(keys[2], (b, max_s, n_kv, hd))
    qpos = jnp.array([[3]], jnp.int32)
    kpos = jnp.broadcast_to(jnp.arange(max_s, dtype=jnp.int32)[None], (b, max_s))
    base = gqa_attention(q, k, v, qpos, kpos)
    # Poison the future slots.
    k2 = k.at[:, 4:].set(1e6)
    v2 = v.at[:, 4:].set(-1e6)
    poisoned = gqa_attention(q, k2, v2, qpos, kpos)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(poisoned))


# ---------------------------------------------------------------- model


def test_decode_matches_full_prefill_oracle(cfg, params):
    """Prefill+decode with KV cache must reproduce the uncached full forward.

    This is the reference's implicit correctness contract (llama.rs:280-292: with
    cache send 1 token, without send everything) promoted to an executable test.
    """
    tokens = jnp.array([[1, 5, 9, 12, 30, 7]], jnp.int32)
    kv = fresh_cache(cfg)
    logits_p, kv = M.forward(params, tokens[:, :3], kv, jnp.int32(0), jnp.int32(3), cfg)
    outs = [logits_p]
    for t in range(3, 6):
        lg, kv = M.forward(
            params, tokens[:, t : t + 1], kv, jnp.int32(t), jnp.int32(1), cfg
        )
        outs.append(lg)

    for t in range(3, 7):
        kv2 = fresh_cache(cfg)
        full, _ = M.forward(
            params, tokens[:, :t], kv2, jnp.int32(0), jnp.int32(t), cfg
        )
        np.testing.assert_allclose(
            np.asarray(outs[t - 3]), np.asarray(full), rtol=2e-4, atol=2e-4
        )


def test_prefill_padding_does_not_change_logits(cfg, params):
    # Padded prefill (chunk longer than seq_len) must give identical logits at
    # the last valid position.
    tokens = jnp.array([[4, 8, 15, 16]], jnp.int32)
    kv = fresh_cache(cfg)
    exact, _ = M.forward(params, tokens, kv, jnp.int32(0), jnp.int32(4), cfg)
    padded_tokens = jnp.pad(tokens, ((0, 0), (0, 4)))
    kv2 = fresh_cache(cfg)
    padded, _ = M.forward(params, padded_tokens, kv2, jnp.int32(0), jnp.int32(4), cfg)
    np.testing.assert_allclose(np.asarray(exact), np.asarray(padded), rtol=1e-5)


def test_decode_after_padded_prefill_matches_oracle(cfg, params):
    # Garbage written to cache slots by padding must be overwritten/ignored.
    tokens = jnp.array([[4, 8, 15, 16, 23]], jnp.int32)
    padded = jnp.pad(tokens[:, :4], ((0, 0), (0, 4)))
    kv = fresh_cache(cfg)
    _, kv = M.forward(params, padded, kv, jnp.int32(0), jnp.int32(4), cfg)
    dec, _ = M.forward(params, tokens[:, 4:5], kv, jnp.int32(4), jnp.int32(1), cfg)

    kv2 = fresh_cache(cfg)
    oracle, _ = M.forward(params, tokens, kv2, jnp.int32(0), jnp.int32(5), cfg)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(oracle), rtol=2e-4, atol=2e-4)


def test_forward_is_jittable_with_traced_pos(cfg, params):
    fwd = jax.jit(M.forward, static_argnames=("config",))
    kv = fresh_cache(cfg)
    tokens = jnp.array([[1, 2, 3, 4]], jnp.int32)
    logits, kv = fwd(params, tokens, kv, jnp.int32(0), jnp.int32(4), cfg)
    assert logits.shape == (1, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    # Decode twice with the SAME compiled fn (pos is traced, not baked in).
    dec = jax.jit(M.forward, static_argnames=("config",))
    t = jnp.array([[9]], jnp.int32)
    l1, kv = dec(params, t, kv, jnp.int32(4), jnp.int32(1), cfg)
    l2, kv = dec(params, t, kv, jnp.int32(5), jnp.int32(1), cfg)
    size_after_two = dec._cache_size()
    l3, kv = dec(params, t, kv, jnp.int32(6), jnp.int32(1), cfg)
    # Advancing pos must NOT retrace (pos is a traced scalar, not a shape).
    assert dec._cache_size() == size_after_two
    assert not np.allclose(np.asarray(l1), np.asarray(l2))


def test_block_range_sharding_equivalence(cfg, params):
    """Running layers as two stacked ranges equals running them all at once.

    This is the pipeline-stage contract: stage boundaries must not change math
    (the reference's Shardable-unit design, llama.rs:171)."""
    from cake_tpu.ops.rope import rope_table

    tokens = jnp.array([[1, 2, 3]], jnp.int32)
    cos, sin = rope_table(cfg.head_dim, 64, cfg.rope_theta, cfg.rope_scaling)
    x = params["embed"][tokens]
    kv = fresh_cache(cfg)
    full, _ = M.blocks_forward(
        params["layers"], x, kv, cos, sin, jnp.int32(0), cfg
    )

    split = cfg.num_hidden_layers // 2
    kv_a = fresh_cache(cfg, n_layers=split)
    kv_b = fresh_cache(cfg, n_layers=cfg.num_hidden_layers - split)
    xa, _ = M.blocks_forward(
        M.slice_layers(params["layers"], 0, split), x, kv_a, cos, sin, jnp.int32(0), cfg
    )
    xb, _ = M.blocks_forward(
        M.slice_layers(params["layers"], split, cfg.num_hidden_layers),
        xa, kv_b, cos, sin, jnp.int32(0), cfg,
    )
    np.testing.assert_allclose(np.asarray(full), np.asarray(xb), rtol=1e-5, atol=1e-5)


def test_tied_embeddings(cfg):
    tied_cfg = LlamaConfig.tiny(tie_word_embeddings=True)
    p = M.init_params(tied_cfg, jax.random.PRNGKey(1), jnp.float32)
    kv = fresh_cache(tied_cfg)
    logits, _ = M.forward(
        p, jnp.array([[1, 2]], jnp.int32), kv, jnp.int32(0), jnp.int32(2), tied_cfg
    )
    assert logits.shape == (1, tied_cfg.vocab_size)
