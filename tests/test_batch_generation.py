"""Batched generation (models/llama/batch.py): lockstep decode oracles."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import BatchGenerator
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def setup(n_layers=2, seed=21):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def single_row(cfg, params, prompt, n, sampling=GREEDY):
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
        ByteTokenizer(),
        sampling,
    )
    gen.add_message(Message.user(prompt))
    gen.generate(n)
    # BatchResult.token_ids keeps the trailing EOS, same as generated_token_ids.
    return list(gen.generated_token_ids), gen.last_finish_reason


def test_batch_of_one_matches_single_greedy():
    cfg, params = setup()
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), GREEDY, max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    [res] = bg.generate([[Message.user("solo row")]], 9)
    want, reason = single_row(cfg, params, "solo row", 9)
    assert res.token_ids == want
    assert res.finish_reason == reason


def test_mixed_length_batch_matches_per_row_runs():
    """Rows of different prompt lengths (different left-pads) must each match
    their own single-row greedy run exactly."""
    cfg, params = setup(seed=22)
    prompts = [
        "short",
        "a medium length prompt row",
        "the longest row of the batch by a comfortable margin indeed",
    ]
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), GREEDY, max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    results = bg.generate([[Message.user(p)] for p in prompts], 8)
    for p, res in zip(prompts, results):
        want, _ = single_row(cfg, params, p, 8)
        assert res.token_ids == want, p


def test_mixed_length_batch_pallas_kernel_matches_xla():
    """Batched decode on the pad-aware Pallas kernel (per-row starts) must
    reproduce the XLA einsum path's tokens for every left-pad in the batch."""
    cfg, params = setup(seed=27)
    prompts = ["p", "a row that pads the batch bucket", "middle one"]
    dialogs = [[Message.user(p)] for p in prompts]

    def run(impl):
        bg = BatchGenerator(
            dataclasses.replace(cfg, attention_impl=impl), params, ByteTokenizer(),
            GREEDY, max_seq_len=256, cache_dtype=jnp.float32, decode_chunk_size=4,
        )
        return bg.generate(dialogs, 8)

    for got, want in zip(run("pallas"), run("xla")):
        assert got.token_ids == want.token_ids


def test_batch_penalty_rows_same_length_match_single():
    """With equal-length rows the shared ring index is exact; penalty decode
    must match the single-row stream."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8)
    cfg, params = setup(seed=23)
    prompt = "equal length rows"
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), s, max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    results = bg.generate([[Message.user(prompt)]] * 3, 9)
    want, _ = single_row(cfg, params, prompt, 9, s)
    for res in results:
        assert res.token_ids == want


def test_batch_eos_stops_row_and_batch():
    """Force EOS by declaring the greedily-chosen token as an EOS id."""
    cfg, params = setup(seed=24)
    want, _ = single_row(cfg, params, "eos probe", 6)
    assert len(want) >= 3
    eos_id = want[2]  # third generated token becomes EOS
    cfg2 = dataclasses.replace(cfg, eos_token_ids=(eos_id,))

    bg = BatchGenerator(
        cfg2, params, ByteTokenizer(), GREEDY, max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    [res] = bg.generate([[Message.user("eos probe")]], 20)
    assert res.finish_reason == "stop"
    assert res.token_ids[-1] == eos_id
    assert res.token_ids == want[: want.index(eos_id) + 1]
    assert res.text == ByteTokenizer().decode(res.token_ids[:-1])


def test_batch_rejects_overlong_prompt():
    import pytest

    cfg, params = setup()
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), GREEDY, max_seq_len=64,
        cache_dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="max_seq_len"):
        bg.generate([[Message.user("x" * 200)]], 4)


def test_batch_penalty_mixed_lengths_exact():
    """Per-row ring indices: penalty decode is EXACT even with ragged rows."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=6)
    cfg, params = setup(seed=25)
    prompts = ["ab", "a noticeably longer prompt than the first one"]
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), s, max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    results = bg.generate([[Message.user(p)] for p in prompts], 9)
    for p, res in zip(prompts, results):
        want, _ = single_row(cfg, params, p, 9, s)
        assert res.token_ids == want, p


def test_batch_zero_budget_returns_empty():
    cfg, params = setup()
    bg = BatchGenerator(
        cfg, params, ByteTokenizer(), GREEDY, max_seq_len=64,
        cache_dtype=jnp.float32,
    )
    res = bg.generate([[Message.user("x")]], 0)
    assert res[0].token_ids == [] and res[0].text == ""


def test_dp_sharded_batch_matches_single_device():
    """Data-parallel lockstep decode: rows sharded over a 4-device "dp" mesh
    produce exactly the single-device batch results (greedy)."""
    import jax

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(21), jnp.float32)
    dialogs = [
        [Message.user(p)]
        for p in ("alpha", "beta prompt", "c", "delta row four")
    ]
    kw = dict(
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        max_seq_len=128, cache_dtype=jnp.float32, decode_chunk_size=4,
    )
    ref = BatchGenerator(cfg, params, ByteTokenizer(), **kw).generate(
        dialogs, 10
    )
    got = BatchGenerator(cfg, params, ByteTokenizer(), dp=4, **kw).generate(
        dialogs, 10
    )
    assert [r.token_ids for r in got] == [r.token_ids for r in ref]


def test_dp_rejects_indivisible_batch():
    import jax
    import pytest

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(22), jnp.float32)
    gen = BatchGenerator(
        cfg, params, ByteTokenizer(),
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        max_seq_len=128, cache_dtype=jnp.float32, dp=4,
    )
    with pytest.raises(ValueError, match="dp"):
        gen.generate([[Message.user("only three")]] * 3, 4)


def test_batch_windowed_softcap_pallas_matches_xla():
    """The per-family attention knobs (sliding window with the alternating
    gate, softcap, scale override) on the BATCH engine: prefill runs the
    chunk kernel with k_starts=pads, decode the pad-aware decode kernel —
    both must reproduce the XLA path's tokens for ragged left-pads."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2,
        model_type="gemma2",
        sliding_window=16,
        alt_sliding_window=True,
        attn_logit_softcap=30.0,
        query_pre_attn_scalar=144,
        post_block_norms=True,
        final_logit_softcap=20.0,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(28), jnp.float32)
    prompts = ["w", "a windowed batch row that is long", "mid row"]
    dialogs = [[Message.user(p)] for p in prompts]

    def run(impl):
        bg = BatchGenerator(
            dataclasses.replace(cfg, attention_impl=impl), params, ByteTokenizer(),
            GREEDY, max_seq_len=256, cache_dtype=jnp.float32, decode_chunk_size=4,
        )
        return bg.generate(dialogs, 8)

    for got, want in zip(run("pallas"), run("xla")):
        assert got.token_ids == want.token_ids
