"""Prefix KV reuse across reset() boundaries (generator.prefix_cache).

The API resets the generator per request (api/mod.rs:78 parity); multi-turn
chat therefore re-sends the whole dialog every call. With prefix_cache on, the
step's KV survives the reset and the new dialog prefills only past the longest
common token prefix — same token streams, turn-2 prefill cost proportional to
the new tokens only.
"""

import jax
import jax.numpy as jnp

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message, encode_dialog_to_prompt
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 256


def make_gen(cfg, params, prefix_cache, decode_chunk_size=1):
    return LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
        decode_chunk_size=decode_chunk_size,
        prefix_cache=prefix_cache,
    )


def setup(seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def run_dialog(gen, messages, n):
    gen.reset()
    for m in messages:
        gen.add_message(m)
    gen.generate(n)
    return list(gen.generated_token_ids)


def lcp_len(a, b):
    n = 0
    while n < len(a) and n < len(b) and a[n] == b[n]:
        n += 1
    return n


def multi_turn_case(gen, tokenizer):
    """Turn 1 then the API-style turn 2 (full dialog resent). Returns
    (turn2_ids, turn2_prefill_tokens, turn2_prompt_ids, turn1_stream)."""
    user1 = Message.user("tell me about caches, at length please")
    got1 = run_dialog(gen, [user1], 12)
    turn1_stream = list(gen._tokens)
    reply_ids = [t for t in got1 if t not in gen.config.eos_token_ids]
    reply = tokenizer.decode(reply_ids)
    dialog2 = [user1, Message.assistant(reply), Message.user("and now TLBs?")]
    got2 = run_dialog(gen, dialog2, 12)
    ids2 = tokenizer.encode(encode_dialog_to_prompt(dialog2))
    return got2, gen.last_prefill_tokens, ids2, turn1_stream


def test_multi_turn_reuse_matches_fresh_run_and_prefills_only_suffix():
    cfg, params = setup()
    tok = ByteTokenizer()

    reuse = make_gen(cfg, params, prefix_cache=True)
    got2, prefilled, ids2, stream1 = multi_turn_case(reuse, tok)

    fresh = make_gen(cfg, params, prefix_cache=False)
    want2, _, _, _ = multi_turn_case(fresh, tok)
    assert got2 == want2  # token stream unchanged

    # Turn-2 prefill cost = new tokens only: everything shared with the
    # turn-1 stream (prompt + generated reply, minus the never-fed last
    # token) was reused.
    expect_lcp = min(lcp_len(ids2, stream1[:-1]), len(ids2) - 1)
    assert expect_lcp > 0
    assert prefilled == len(ids2) - expect_lcp
    assert prefilled < len(ids2)


def test_reuse_with_fused_decode_chunks():
    cfg, params = setup(seed=32)
    tok = ByteTokenizer()
    reuse = make_gen(cfg, params, prefix_cache=True, decode_chunk_size=4)
    got2, prefilled, ids2, _ = multi_turn_case(reuse, tok)
    fresh = make_gen(cfg, params, prefix_cache=False, decode_chunk_size=4)
    want2, _, _, _ = multi_turn_case(fresh, tok)
    assert got2 == want2
    assert prefilled < len(ids2)


def test_unrelated_dialog_after_reuse_still_exact():
    """A second dialog sharing (almost) nothing must still be correct: the
    stale cache beyond the tiny template LCP is overwritten or masked."""
    cfg, params = setup(seed=33)
    reuse = make_gen(cfg, params, prefix_cache=True)
    run_dialog(reuse, [Message.user("first dialog, long enough to matter")], 10)
    got = run_dialog(reuse, [Message.user("zzz different")], 10)

    fresh = make_gen(cfg, params, prefix_cache=False)
    want = run_dialog(fresh, [Message.user("zzz different")], 10)
    assert got == want


def test_identical_dialog_resubmitted_reuses_all_but_last():
    cfg, params = setup(seed=34)
    reuse = make_gen(cfg, params, prefix_cache=True)
    msgs = [Message.user("same dialog twice")]
    first = run_dialog(reuse, msgs, 8)
    ids = reuse._encode_prompt()
    second = run_dialog(reuse, msgs, 8)
    assert second == first
    # The whole prompt is shared; only the final token (logits source) re-runs.
    assert reuse.last_prefill_tokens == 1 or reuse.last_prefill_tokens == len(
        ids
    ) - lcp_len(ids, ids[:-1])


class _FlakyStep:
    """Wraps a step; raises once at the Nth forward call, then passes through."""

    def __init__(self, inner, fail_at_call):
        self._inner = inner
        self._calls = 0
        self._fail_at = fail_at_call

    def __call__(self, tokens, pos, seq_len):
        self._calls += 1
        if self._calls == self._fail_at:
            raise RuntimeError("injected mid-prefill failure")
        return self._inner(tokens, pos, seq_len)

    def reset(self):
        self._inner.reset()

    @property
    def max_seq_len(self):
        return self._inner.max_seq_len


def test_failed_prefill_does_not_poison_reuse():
    """A prefill that dies partway must not let the next request reuse KV
    slots that were never written: the high-water mark bounds the snapshot."""
    cfg, params = setup(seed=36)
    inner = LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32)
    flaky = _FlakyStep(inner, fail_at_call=0)  # disabled for turn 1
    gen = LlamaGenerator(
        cfg, flaky, ByteTokenizer(), GREEDY, prefill_chunk=8, prefix_cache=True
    )
    long_user = Message.user("a dialog long enough to take several prefill chunks " * 2)
    gen.add_message(long_user)
    gen.generate(6)

    # Request 2: an UNRELATED long dialog whose chunked prefill dies on its
    # second chunk (first chunk call after reset is call N; fail at N+1).
    gen.reset()
    flaky._calls = 0
    flaky._fail_at = 2
    other = Message.user("completely different text that shares only the header " * 2)
    gen.add_message(other)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="injected"):
        gen.generate(4)

    # Request 3 (the retry): must match a fresh-generator run exactly — the
    # failed request's unwritten slots must not be treated as reusable.
    gen.reset()
    flaky._fail_at = 0
    gen.add_message(other)
    gen.generate(6)
    got = list(gen.generated_token_ids)

    fresh = make_gen(cfg, params, prefix_cache=False)
    want = run_dialog(fresh, [other], 6)
    assert got == want


def test_prefix_cache_interacts_with_prefill_chunking():
    """Reused suffix longer than prefill_chunk still prefills in bounded
    chunks over the cache prefix."""
    cfg, params = setup(seed=35)
    step = LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32)
    reuse = LlamaGenerator(
        cfg, step, ByteTokenizer(), GREEDY, prefill_chunk=8, prefix_cache=True
    )
    tok = ByteTokenizer()
    got2, prefilled, ids2, _ = multi_turn_case(reuse, tok)

    fresh = make_gen(cfg, params, prefix_cache=False)
    want2, _, _, _ = multi_turn_case(fresh, tok)
    assert got2 == want2
    assert prefilled < len(ids2)
