"""Tests for the interprocedural resource-lifecycle analyzer
(cake_tpu/analysis/resources.py) and its rule pack
(cake_tpu/analysis/rules/lifecycle.py).

Three layers, mirroring test_locks.py:

  * snippet tests per rule — every rule has a TRUE-POSITIVE (deleting the
    rule fails the test via select=) and negatives pinning the
    false-positive boundaries the real tree depends on (finally release,
    handler release + re-raise, transfer into a sink, refund=True
    rollback);
  * teeth — removing one release call from an otherwise-clean snippet
    flips leak-on-error-path from silent to firing, so the analyzer is
    demonstrably load-bearing rather than vacuously green;
  * real-tree pins — the protocol table ENGAGES the actual serving path
    (all five protocols tracked, the quota choke-point funnel recognized,
    the lease->_lane_leases and grant->_on_close transfers observed) and
    reports zero leak edges, which is what `make verify` gates on.

The analysis package is stdlib-only; nothing here needs jax.
"""

from __future__ import annotations

from pathlib import Path

from cake_tpu.analysis import engine, lint_source
from cake_tpu.analysis import resources as ra
from cake_tpu.analysis.cli import resources_main

REPO = Path(__file__).resolve().parent.parent

# Lifecycle rules skip test files (tests exercise acquire/release APIs
# deliberately out of protocol), so snippets must wear a product path.
PROD = "cake_tpu/runtime/snippet.py"


def lint_rule(src: str, rule: str, path: str = PROD):
    return lint_source(src, path=path, select=[rule])


def rules_of(findings):
    return [f.rule for f in findings]


_REAL = {}


def real_analysis() -> ra.ResourceAnalysis:
    """One shared walk of the real tree (module-level cache: the analysis
    is deterministic and read-only, and ~2s per walk adds up)."""
    if "a" not in _REAL:
        files = engine.collect_files([REPO / "cake_tpu"])
        ctxs = [
            engine.FileContext.parse(str(f), f.read_text()) for f in files
        ]
        _REAL["a"] = ra.resource_analysis(ctxs)
    return _REAL["a"]


# -------------------------------------------------------- leak-on-error-path


class TestLeakOnErrorPath:
    RULE = "leak-on-error-path"

    def test_raise_with_owned_pages_fires(self):
        fs = lint_rule(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        if n > 4:
            raise RuntimeError("boom")
        alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert fs[0].line == 6  # the raise, not the acquire
        assert "still owned" in fs[0].message

    def test_finally_release_is_clean(self):
        fs = lint_rule(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        try:
            if n > 4:
                raise RuntimeError("boom")
        finally:
            alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert fs == []

    def test_handler_release_before_reraise_is_clean(self):
        fs = lint_rule(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        try:
            if n > 4:
                raise ValueError("boom")
        except ValueError:
            alloc.release_pages(pages)
            raise
""",
            self.RULE,
        )
        assert fs == []

    def test_teeth_removing_release_flips_to_firing(self):
        # The load-bearing check: the clean snippet above minus its one
        # release call must FIRE. If this stops flipping, the walk is
        # green because it stopped looking, not because the tree is safe.
        fs = lint_rule(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        try:
            if n > 4:
                raise ValueError("boom")
        except ValueError:
            raise
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_transfer_into_lane_leases_is_clean(self):
        # Ownership parked in the registry _lane_recycle drains: the raise
        # after the store does not leak the lease.
        fs = lint_rule(
            """
class Engine:
    def plan(self, prefix, lane, chain):
        lease = prefix.fork(chain)
        self._lane_leases[lane] = lease
        raise RuntimeError("layout failed")
""",
            self.RULE,
        )
        assert fs == []

    def test_callee_release_is_credited(self):
        # Interprocedural: the cleanup helper's release reaches the
        # caller's owned set through the may-release summary.
        fs = lint_rule(
            """
class Engine:
    def _drop(self, alloc, pages):
        alloc.release_pages(pages)

    def step(self, alloc, n):
        pages = alloc.alloc(n)
        if n > 4:
            self._drop(alloc, pages)
            raise RuntimeError("boom")
        alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert fs == []

    def test_test_files_are_exempt(self):
        fs = lint_rule(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        raise RuntimeError("boom")
""",
            self.RULE,
            path="tests/test_snippet.py",
        )
        assert fs == []


# ------------------------------------------------------------- double-release


class TestDoubleRelease:
    RULE = "double-release"

    def test_same_subject_twice_fires(self):
        fs = lint_rule(
            """
class Engine:
    def drop(self, alloc, pages):
        alloc.release_pages(pages)
        alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "double-free" in fs[0].message

    def test_different_subjects_are_clean(self):
        fs = lint_rule(
            """
class Engine:
    def drop(self, alloc, a, b):
        alloc.release_pages(a)
        alloc.release_pages(b)
""",
            self.RULE,
        )
        assert fs == []

    def test_rebind_between_releases_is_clean(self):
        fs = lint_rule(
            """
class Engine:
    def drop(self, alloc, n):
        pages = alloc.alloc(n)
        alloc.release_pages(pages)
        pages = alloc.alloc(n)
        alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert fs == []

    def test_branch_local_releases_are_clean(self):
        # One release per exclusive branch is one release per path.
        fs = lint_rule(
            """
class Engine:
    def drop(self, alloc, pages, fast):
        if fast:
            alloc.release_pages(pages)
        else:
            alloc.release_pages(pages)
""",
            self.RULE,
        )
        assert fs == []

    def test_release_after_transfer_fires(self):
        # The registry's drain will release the lease again: a direct
        # release after parking it is a double-free in waiting.
        fs = lint_rule(
            """
class Engine:
    def plan(self, prefix, lane, chain):
        lease = prefix.fork(chain)
        self._lane_leases[lane] = lease
        prefix.release(lease)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "transferred" in fs[0].message


# -------------------------------------------------- release-outside-choke-point


class TestReleaseOutsideChokePoint:
    RULE = "release-outside-choke-point"

    def test_adhoc_close_fires(self):
        fs = lint_rule(
            """
class Engine:
    def finish(self, rid):
        self.meter.close(rid)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "_on_close" in fs[0].message

    def test_funnel_lambda_is_clean(self):
        fs = lint_rule(
            """
class Engine:
    def submit(self, handle, rid):
        handle._on_close = lambda: self.meter.close(rid)
""",
            self.RULE,
        )
        assert fs == []

    def test_refund_rollback_is_clean(self):
        fs = lint_rule(
            """
class Engine:
    def shed(self, rid):
        self.meter.close(rid, refund=True)
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------ refund-missing-on-shed


class TestRefundMissingOnShed:
    RULE = "refund-missing-on-shed"

    SHED_LEAK = """
class EngineOverloaded(Exception):
    pass

class Engine:
    def submit(self, rid, cost):
        tok = self.meter.admit(rid, cost)
        if cost > 4:
            raise EngineOverloaded("shed")
        return tok
"""

    def test_shed_without_refund_fires(self):
        fs = lint_rule(self.SHED_LEAK, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "refund=True" in fs[0].message

    def test_shed_edges_belong_to_this_rule_not_leak(self):
        # The same witness must NOT double-report under leak-on-error-path:
        # the shed flavor carries the refund remedy, the generic flavor
        # would mis-prescribe a release.
        assert lint_rule(self.SHED_LEAK, "leak-on-error-path") == []

    def test_refund_on_shed_edge_is_clean(self):
        fs = lint_rule(
            """
class EngineOverloaded(Exception):
    pass

class Engine:
    def submit(self, rid, cost):
        tok = self.meter.admit(rid, cost)
        try:
            self._enqueue(rid)
        except EngineOverloaded:
            self.meter.close(rid, refund=True)
            raise
        return tok
""",
            self.RULE,
        )
        assert fs == []

    def test_non_shed_exception_is_generic_leak(self):
        src = """
class Engine:
    def submit(self, rid, cost):
        tok = self.meter.admit(rid, cost)
        raise RuntimeError("not a shed")
"""
        assert lint_rule(src, self.RULE) == []
        assert rules_of(lint_rule(src, "leak-on-error-path")) == [
            "leak-on-error-path"
        ]


# -------------------------------------------------------------- real-tree pins


class TestRealTreeEngagement:
    """The table must ENGAGE the tree it was written for. A protocol with
    zero tracked sites is a silently-dead check; these pins fail the build
    the day a rename detaches the analyzer from the APIs it guards."""

    def test_all_five_protocols_track_acquires(self):
        a = real_analysis()
        assert len(a.model.protocols) >= 4
        engaged = {
            p for p, t in a.census.items() if t["acquire"]
        }
        assert engaged == {
            "kv-pages",
            "prefix-lease",
            "quota",
            "lanes",
            "retained-kv",
        }

    def test_acquire_site_floor_in_serving(self):
        a = real_analysis()
        per_file: dict[str, int] = {}
        for table in a.census.values():
            for s in table["acquire"]:
                name = Path(s.path).name
                per_file[name] = per_file.get(name, 0) + 1
        assert per_file.get("serving.py", 0) >= 10, per_file
        total = sum(per_file.values())
        assert total >= 15, per_file

    def test_quota_funnel_is_recognized(self):
        # The ONE completion-close site lives inside the _on_close lambda;
        # everything else is a refund. No ad-hoc close escapes the funnel.
        a = real_analysis()
        assert [p for p, _ in a.funnel_sites] == ["quota"]
        (site,) = [s for _, s in a.funnel_sites]
        assert Path(site.path).name == "serving.py"
        assert a.chokes == []
        assert len(a.census["quota"]["refund"]) >= 1

    def test_ownership_transfers_are_observed(self):
        # The two load-bearing handoffs: submit parks the quota grant in
        # handle._on_close; _fork_lane parks the prefix lease in
        # _lane_leases for _lane_recycle to drain.
        a = real_analysis()
        sinks = {(e.proto, e.sink) for e in a.transfers}
        assert ("quota", "_on_close") in sinks
        assert ("prefix-lease", "_lane_leases") in sinks

    def test_real_tree_has_no_leak_edges(self):
        a = real_analysis()
        assert a.leak_edges() == [], [
            str(e) for e in a.leak_edges()
        ]


# ------------------------------------------------------------------------ CLI

# A tiny tree exercising both observed transfers and the quota funnel, so
# the CLI tests don't each re-walk the real tree (one real-tree walk —
# test_check_passes_on_real_tree — pins the `make verify` gate).
SMALL_TREE = """
class Engine:
    def submit(self, handle, rid, cost):
        self.meter.admit(rid, cost)
        handle._on_close = lambda: self.meter.close(rid)

    def plan(self, prefix, lane, chain):
        lease = prefix.fork(chain)
        self._lane_leases[lane] = lease
"""


class TestResourcesCli:
    def test_check_passes_on_real_tree(self, capsys):
        rc = resources_main([str(REPO / "cake_tpu"), "--check"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no leak edges" in out
        assert "5/5 protocol(s)" in out

    def test_report_names_every_protocol(self, tmp_path, capsys):
        (tmp_path / "eng.py").write_text(SMALL_TREE)
        rc = resources_main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        for name in (
            "kv-pages",
            "prefix-lease",
            "quota",
            "lanes",
            "retained-kv",
        ):
            assert name in out  # the table always renders the full model
        assert "owned-set walk" in out
        assert "transferred -> _lane_leases" in out

    def test_dot_emits_graphviz(self, tmp_path, capsys):
        (tmp_path / "eng.py").write_text(SMALL_TREE)
        rc = resources_main([str(tmp_path), "--dot"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("digraph resources {")
        assert '"quota._on_close"' in out  # funnel sink, dashed
        assert '"prefix-lease._lane_leases"' in out

    def test_check_fails_on_leaky_tree(self, tmp_path, capsys):
        (tmp_path / "leaky.py").write_text(
            """
class Engine:
    def step(self, alloc, n):
        pages = alloc.alloc(n)
        raise RuntimeError("boom")
"""
        )
        rc = resources_main([str(tmp_path), "--check"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "leak" in out

    def test_cli_dispatch(self, tmp_path, capsys):
        # The serving CLI routes `resources` to the stdlib-only analysis
        # package before its own argparse (no --model, no jax).
        from cake_tpu import cli as serving_cli

        (tmp_path / "eng.py").write_text(SMALL_TREE)
        rc = serving_cli.main(["resources", str(tmp_path), "--check"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no leak edges" in out


# -------------------------------------------------------------------- timings


class TestSharedWalkPhases:
    def test_resource_walk_phase_is_reported(self):
        res = engine.run_lint(
            [REPO / "cake_tpu" / "analysis"],
            select=["leak-on-error-path"],
        )
        assert any(n == "(resource-walk)" for n, _ in res.timings)

    def test_walk_is_shared_not_rebuilt(self):
        # lifecycle rules and the locks pack ride one project index and
        # one entry-point sweep per ctx list: the analysis caches key on
        # the ctx anchor, so a second consumer gets the same object.
        files = engine.collect_files([REPO / "cake_tpu" / "analysis"])
        ctxs = [
            engine.FileContext.parse(str(f), f.read_text()) for f in files
        ]
        a1 = ra.resource_analysis(ctxs)
        a2 = ra.resource_analysis(ctxs)
        assert a1 is a2
