"""Sliding-window SLI time-series unit tests (obs/timeseries.py).

All on an injected fake clock: bucket alignment, zero-gap
materialization, horizon eviction, the sample-reservoir cap, and the
refusal accounting are closed-form window math, so the tests pin exact
numbers.
"""

import pytest

from cake_tpu.obs.timeseries import SliTimeseries, _percentile


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _ts(window_s=30.0, bucket_s=5.0, **kw):
    clock = _Clock()
    return SliTimeseries(
        window_s=window_s, bucket_s=bucket_s, time_fn=clock, **kw
    ), clock


def test_constructor_validates_geometry():
    with pytest.raises(ValueError):
        SliTimeseries(window_s=10.0, bucket_s=0.0)
    with pytest.raises(ValueError):
        SliTimeseries(window_s=2.0, bucket_s=5.0)


def test_percentile_nearest_rank():
    assert _percentile([], 0.99) == 0.0
    samples = [0.4, 0.1, 0.2, 0.3]
    assert _percentile(samples, 0.0) == 0.1
    assert _percentile(samples, 1.0) == 0.4
    assert _percentile(samples, 0.99) == 0.4  # nearest rank, not interp


def test_single_bucket_point_math():
    ts, clock = _ts()
    ts.observe_ttft(0.1)
    ts.observe_ttft(0.3)
    ts.observe_tokens(10)
    ts.observe_finish("stop")
    clock.t = 1002.0  # same 5s bucket
    out = ts.series()
    assert out["window_s"] == 30.0 and out["bucket_s"] == 5.0
    (p,) = out["points"]
    assert p["ttft_p99_ms"] == 300.0
    assert p["tok_s"] == 2.0           # 10 tokens over the 5s bucket
    assert p["finished"] == 1 and p["refused"] == 0
    assert p["shed_frac"] == 0.0
    assert p["age_s"] == 2.0           # now - bucket start


def test_refusals_feed_shed_frac_and_errors_tally():
    ts, _ = _ts()
    for finish in ("stop", "quota", "shed", "error"):
        ts.observe_finish(finish)
    (p,) = ts.series()["points"]
    # quota + shed are refusals; stop + error are admitted terminals.
    assert p["finished"] == 2 and p["refused"] == 2 and p["errors"] == 1
    assert p["shed_frac"] == 0.5


def test_gaps_materialize_as_zero_points():
    ts, clock = _ts()
    ts.observe_finish("stop")          # bucket 200 (t=1000)
    clock.t = 1011.0                   # bucket 202: one empty gap bucket
    ts.observe_finish("stop")
    points = ts.series()["points"]
    # Leading all-zero history is trimmed; the interior gap is NOT.
    assert [p["finished"] for p in points] == [1, 0, 1]
    assert points[0]["age_s"] == 11.0


def test_window_eviction():
    ts, clock = _ts(window_s=10.0, bucket_s=5.0)
    ts.observe_finish("stop")
    clock.t = 1030.0                   # 6 buckets later, past the horizon
    ts.observe_finish("quota")
    points = ts.series()["points"]
    assert len(points) == 1            # the old bucket left the window
    assert points[0]["refused"] == 1 and points[0]["finished"] == 0


def test_series_is_empty_before_any_traffic():
    ts, _ = _ts()
    assert ts.series()["points"] == []


def test_ttft_reservoir_is_bounded():
    ts, _ = _ts(max_samples=3)
    for i in range(10):
        ts.observe_ttft(0.1 * (i + 1))
        ts.observe_tokens(1)
    (p,) = ts.series()["points"]
    # Reservoir kept the first 3 samples; p99 reads the bounded set.
    assert p["ttft_p99_ms"] == 300.0


def test_observations_in_one_bucket_share_it():
    ts, clock = _ts()
    ts.observe_tokens(4)
    clock.t = 1004.9                   # still bucket floor(1000/5)=200
    ts.observe_tokens(6)
    clock.t = 1005.0                   # rolls to bucket 201
    ts.observe_tokens(5)
    points = ts.series()["points"]
    assert [p["tok_s"] for p in points] == [2.0, 1.0]
