"""Prep-time weight fusion (ops/fuse.py): QKV -> one matmul, gate/up -> one.

The contract under test: fusion is a pure LAYOUT transform — every execution
path (local forward, fused decode scan, batched lockstep, tensor-parallel
shard-major split, quantized weights, Qwen2 biases, MoE shared expert) emits
token streams identical to the unfused weights, because each output column's
dot product is untouched by concatenation along the output dim.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.fuse import (
    fuse_layer_tree,
    fuse_params,
    is_fused,
    unfuse_layer_tree,
)
from cake_tpu.ops.quant import QuantWeight, quantize_layer_tree

jax.config.update("jax_enable_x64", False)


def _tiny(**kw):
    return LlamaConfig.tiny(**kw)


def _tree_allclose(a, b):
    for (ka, va), (kb, vb) in zip(
        sorted(a.items()), sorted(b.items()), strict=True
    ):
        assert ka == kb
        la, lb = jax.tree.leaves(va), jax.tree.leaves(vb)
        for x, y in zip(la, lb, strict=True):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y), err_msg=ka)


def test_round_trip_identity():
    cfg = _tiny(num_hidden_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    layers = params["layers"]
    fused = fuse_layer_tree(layers)
    assert is_fused(fused) and not is_fused(layers)
    assert "wq" not in fused and "w_gate" not in fused
    _tree_allclose(unfuse_layer_tree(fused, cfg), layers)


def test_round_trip_tp_shard_major():
    cfg = _tiny(num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    layers = params["layers"]
    fused = fuse_layer_tree(layers, tp=2)
    _tree_allclose(unfuse_layer_tree(fused, cfg, tp=2), layers)
    # Shard-major layout: the first 1/tp column block is [q_0 | k_0 | v_0].
    hd = cfg.head_dim
    qc = cfg.num_attention_heads * hd // 2
    kc = cfg.num_key_value_heads * hd // 2
    shard0 = np.asarray(fused["wqkv"][..., : qc + 2 * kc])
    np.testing.assert_array_equal(
        shard0[..., :qc], np.asarray(layers["wq"][..., :qc])
    )
    np.testing.assert_array_equal(
        shard0[..., qc : qc + kc], np.asarray(layers["wk"][..., :kc])
    )


def test_fuse_quantize_commute():
    """fuse(quantize(w)) == quantize(fuse(w)) exactly — per-output-channel
    scales ride their columns through the concat."""
    cfg = _tiny(num_hidden_layers=2)
    layers = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)["layers"]
    a = fuse_layer_tree(quantize_layer_tree(layers))
    b = quantize_layer_tree(fuse_layer_tree(layers))
    assert isinstance(a["wqkv"], QuantWeight)
    np.testing.assert_array_equal(np.asarray(a["wqkv"].w), np.asarray(b["wqkv"].w))
    np.testing.assert_array_equal(
        np.asarray(a["wqkv"].scale), np.asarray(b["wqkv"].scale)
    )
    np.testing.assert_array_equal(np.asarray(a["w_gu"].w), np.asarray(b["w_gu"].w))


def test_idempotent():
    cfg = _tiny(num_hidden_layers=2)
    layers = M.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)["layers"]
    fused = fuse_layer_tree(layers)
    assert fuse_layer_tree(fused) is fused


def _forward_argmax(cfg, params, tokens, n_steps=6):
    """Greedy token chain through M.forward (prefill + decode)."""
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    toks = list(tokens)
    logits, kv = M.forward(
        params, jnp.asarray([toks], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(toks)), cfg,
    )
    out = [int(jnp.argmax(logits[0]))]
    toks.append(out[-1])
    for _ in range(n_steps - 1):
        pos = len(toks) - 1
        logits, kv = M.forward(
            params, jnp.asarray([[toks[-1]]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        out.append(int(jnp.argmax(logits[0])))
        toks.append(out[-1])
    return out


@pytest.mark.parametrize("quant", [False, True])
def test_forward_stream_identical(quant):
    cfg = _tiny(num_hidden_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(4), jnp.float32)
    if quant:
        from cake_tpu.ops.quant import quantize_params

        params = quantize_params(params)
    fused = fuse_params(params)
    tokens = [3, 1, 4, 1, 5]
    assert _forward_argmax(cfg, params, tokens) == _forward_argmax(
        cfg, fused, tokens
    )


def test_qwen2_bias_stream_identical():
    cfg = _tiny(num_hidden_layers=2, attention_bias=True)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    assert "bq" in params["layers"]
    fused = fuse_params(params)
    assert "bqkv" in fused["layers"] and "bq" not in fused["layers"]
    tokens = [2, 7, 1]
    assert _forward_argmax(cfg, params, tokens) == _forward_argmax(
        cfg, fused, tokens
    )


def test_tp2_stream_identical():
    """Shard-major fused weights through the real TensorParallelRunner match
    the unfused local step token-for-token (place_tp_model fuses with tp)."""
    from cake_tpu.models.llama.generator import LocalForwardStep
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = _tiny(
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2
    )
    params = M.init_params(cfg, jax.random.PRNGKey(6), jnp.float32)
    local = LocalForwardStep(cfg, params, max_seq_len=64, cache_dtype=jnp.float32)
    tp = TensorParallelRunner(
        cfg, params, tp=2, max_seq_len=64, cache_dtype=jnp.float32
    )
    assert is_fused(jax.tree.map(lambda x: x, tp.layer_params))
    toks = np.asarray([[3, 1, 4, 1, 5]], np.int32)
    a = local(toks, 0, 5)
    b = tp(toks, 0, 5)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)
    assert int(np.argmax(a[0])) == int(np.argmax(b[0]))


def test_moe_shared_expert_fuses():
    cfg = _tiny(
        num_hidden_layers=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        shared_expert_intermediate_size=32,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    fused = fuse_params(params)
    lf = fused["layers"]
    # Expert weights keep the grouped [n, E, in, out] layout; shared expert
    # and QKV fuse.
    assert "w_gate" in lf and lf["w_gate"].ndim == 4
    assert "sh_gu" in lf and "sh_gate" not in lf
    assert "wqkv" in lf
    tokens = [1, 2, 3]
    assert _forward_argmax(cfg, params, tokens) == _forward_argmax(
        cfg, fused, tokens
    )
