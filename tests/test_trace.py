"""Observability (utils/trace.py): span registry, memory report, profiler hook."""

import threading
import time

from cake_tpu.utils import trace


def test_span_registry_accumulates():
    reg = trace.SpanRegistry()
    with reg.span("a"):
        time.sleep(0.01)
    with reg.span("a"):
        pass
    with reg.span("b"):
        pass
    snap = reg.snapshot()
    assert snap["a"]["count"] == 2
    assert snap["b"]["count"] == 1
    assert snap["a"]["total_s"] >= 0.01
    assert snap["a"]["min_s"] <= snap["a"]["max_s"]
    assert "a: n=2" in reg.report()
    reg.clear()
    assert reg.snapshot() == {}


def test_span_registry_thread_safe():
    reg = trace.SpanRegistry()

    def work():
        for _ in range(200):
            with reg.span("x"):
                pass

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.snapshot()["x"]["count"] == 1600


def test_span_records_on_exception():
    reg = trace.SpanRegistry()
    try:
        with reg.span("err"):
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert reg.snapshot()["err"]["count"] == 1


def test_memory_report_has_host_and_devices():
    m = trace.memory_report()
    assert m.get("host_peak_rss_bytes", 0) > 0
    assert isinstance(m.get("devices"), list) and m["devices"]


def test_jax_profile_noop_without_dir():
    with trace.jax_profile(None):
        pass  # must not touch the profiler


def test_jax_profile_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    with trace.jax_profile(str(tmp_path)):
        jnp.ones((8, 8)).sum().block_until_ready()
    # xplane dumps land under plugins/profile/<run>/
    dumped = list(tmp_path.rglob("*.xplane.pb"))
    assert dumped, list(tmp_path.rglob("*"))


def test_log_memory_smoke(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="cake_tpu.trace"):
        trace.log_memory("test")
    assert any("[mem:test]" in r.message for r in caplog.records)
