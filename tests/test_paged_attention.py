"""Ragged paged decode attention: kernel (interpret) vs gather fallback vs the
dense kernel/XLA oracles.

The load-bearing property is INDIRECTION correctness: the same logical tokens
scattered across different physical pages must attend identically, and both
paged read paths must match the dense cache holding the same history — the
failure mode the `prefetch-ref-unused` lint rule also guards (a kernel that
ignores its block table and reads page 0 everywhere passes uniform-content
tests; these are deliberately non-uniform).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama.batch import decode_positions
from cake_tpu.models.llama.paged_cache import PageAllocator
from cake_tpu.ops.pallas.decode_attention import decode_attention
from cake_tpu.ops.pallas.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
)

B, N_Q, N_KV, HD = 3, 4, 2, 64
PS = 128  # kernel page size: the 128-lane tile
PER_SEQ = 3  # up to 3 pages per sequence -> 384 slots


def setup(seed=0, lengths=(130, 257, 40), pads=(3, 0, 10), n_pages=12):
    """A pool whose physical pages are deliberately out of order (the LIFO
    free list hands out high pages first), plus the dense mirror."""
    rng = np.random.default_rng(seed)
    lengths = np.asarray(lengths, np.int32)
    pads = np.asarray(pads, np.int32)
    alloc = PageAllocator(n_pages, PS, B, PER_SEQ)
    for r in range(B):
        alloc.map_range(r, int(pads[r]), int(lengths[r]))
    kp = jnp.asarray(
        rng.normal(size=(n_pages, N_KV, PS, HD)), jnp.float32
    )
    vp = jnp.asarray(
        rng.normal(size=(n_pages, N_KV, PS, HD)), jnp.float32
    )
    q = jnp.asarray(rng.normal(size=(B, 1, N_Q, HD)), jnp.float32)
    # Dense mirror: the gathered view IS the dense cache for mapped slots.
    from cake_tpu.models.llama.paged_cache import gather_pages

    bt = jnp.asarray(alloc.block_tables)
    dense_k = gather_pages(kp, bt)
    dense_v = gather_pages(vp, bt)
    return q, kp, vp, dense_k, dense_v, bt, jnp.asarray(lengths), jnp.asarray(pads)


def xla_grids(lengths, pads):
    q_pos = (lengths - 1 - pads)[:, None]
    _, k_pos, _ = decode_positions(jnp.int32(0), pads, PER_SEQ * PS)
    return q_pos, k_pos


def test_kernel_matches_gather_fallback_ragged_lengths():
    q, kp, vp, _, _, bt, lengths, pads = setup()
    got = paged_decode_attention(
        q, kp, vp, lengths, bt, pads, interpret=True
    )
    q_pos, k_pos = xla_grids(lengths, pads)
    want = paged_decode_attention_xla(q, kp, vp, q_pos, k_pos, bt)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )


def test_kernel_matches_dense_kernel_same_history():
    # Three-way: paged kernel == dense kernel fed the gathered dense view.
    q, kp, vp, dense_k, dense_v, bt, lengths, pads = setup(seed=1)
    got = paged_decode_attention(
        q, kp, vp, lengths, bt, pads, interpret=True
    )
    want = decode_attention(
        q, dense_k, dense_v, lengths, pads, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )


def test_physical_permutation_invariance():
    """Same logical tokens, two different physical layouts -> same output.
    THE indirection test: a kernel reading page 0 for every sequence fails."""
    rng = np.random.default_rng(7)
    n_pages = 9
    logical = rng.normal(size=(B, PER_SEQ * PS, N_KV, HD)).astype(np.float32)
    lengths = jnp.asarray([300, 290, 280], jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, 1, N_Q, HD)), jnp.float32)

    def build(order):
        tables = np.asarray(order, np.int32).reshape(B, PER_SEQ)
        kp = np.zeros((n_pages, N_KV, PS, HD), np.float32)
        vp = np.zeros_like(kp)
        for r in range(B):
            for lp in range(PER_SEQ):
                chunk = logical[r, lp * PS : (lp + 1) * PS]  # [PS, n_kv, hd]
                kp[tables[r, lp]] = np.moveaxis(chunk, 1, 0)
                vp[tables[r, lp]] = np.moveaxis(chunk, 1, 0) * 0.5
        return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables)

    kp1, vp1, bt1 = build([0, 1, 2, 3, 4, 5, 6, 7, 8])
    kp2, vp2, bt2 = build([8, 3, 5, 0, 7, 1, 6, 2, 4])
    o1 = paged_decode_attention(q, kp1, vp1, lengths, bt1, interpret=True)
    o2 = paged_decode_attention(q, kp2, vp2, lengths, bt2, interpret=True)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)
    # Sanity that the table matters at all: a wrong table changes the output.
    o3 = paged_decode_attention(q, kp2, vp2, lengths, bt1, interpret=True)
    assert float(jnp.abs(o1 - o3).max()) > 1e-3


def test_sequence_spanning_three_pages_crosses_boundaries():
    # One sequence whose live window covers 3 pages, with the decode position
    # in the last one; another stopping mid-page-1.
    q, kp, vp, _, _, bt, lengths, pads = setup(
        seed=3, lengths=(PER_SEQ * PS - 1, 140, 70), pads=(0, 5, 0)
    )
    got = paged_decode_attention(
        q, kp, vp, lengths, bt, pads, interpret=True
    )
    q_pos, k_pos = xla_grids(lengths, pads)
    want = paged_decode_attention_xla(q, kp, vp, q_pos, k_pos, bt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_window_folds_into_pruning_start():
    q, kp, vp, _, _, bt, lengths, pads = setup(seed=4)
    got = paged_decode_attention(
        q, kp, vp, lengths, bt, pads, window=64, interpret=True
    )
    q_pos, k_pos = xla_grids(lengths, pads)
    want = paged_decode_attention_xla(
        q, kp, vp, q_pos, k_pos, bt, window=64
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_untiled_page_size_is_refused_by_kernel():
    q, kp, vp, _, _, bt, lengths, pads = setup()
    with pytest.raises(ValueError, match="128-lane"):
        paged_decode_attention(
            q, kp[:, :, :96], vp[:, :, :96], lengths, bt, pads,
            interpret=True,
        )


def test_unmapped_tail_pages_are_harmless():
    # Lanes whose live window ends mid-table leave later entries unmapped;
    # the kernel clamps into the live range and never touches them.
    q, kp, vp, _, _, bt, lengths, pads = setup(
        seed=5, lengths=(100, 90, 80), pads=(0, 0, 0)
    )
    assert (np.asarray(bt)[:, 1:] < 0).all()  # only page 0 mapped per lane
    got = paged_decode_attention(
        q, kp, vp, lengths, bt, pads, interpret=True
    )
    q_pos, k_pos = xla_grids(lengths, pads)
    want = paged_decode_attention_xla(q, kp, vp, q_pos, k_pos, bt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
