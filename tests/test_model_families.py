"""Qwen2 and Mistral model families, pinned against HF transformers.

The reference supports Llama-3 only (SURVEY.md §0); this framework runs the
whole Llama-family decoder lineage through ONE model core
(models/llama/model.py): Qwen2 adds QKV projection bias
(config.attention_bias), Mistral adds sliding-window attention and an explicit
head_dim (config.sliding_window / head_dim_override). Like
tests/test_cross_impl.py, the oracle is an external implementation: a
randomly-initialized transformers model saved with ``save_pretrained`` is a
REAL HF checkpoint directory, loaded through this framework's own
config/safetensors path and compared token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import (
    Message,
    encode_dialog,
    encode_dialog_chatml,
    encode_dialog_mistral,
)
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.io.safetensors_io import load_params


def ours_greedy(model_dir, prompt_ids, n_steps, max_seq=128):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, max_seq, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    logits, kv = fwd(
        params, jnp.asarray([prompt_ids], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(prompt_ids)), cfg,
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


# ----------------------------------------------------------------- Qwen2


def make_qwen2_checkpoint(tmp_path, seed=0):
    cfg = transformers.Qwen2Config(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        use_sliding_window=False,
    )
    torch.manual_seed(seed)
    model = transformers.Qwen2ForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_qwen2_config_parses_bias_and_window_gate(tmp_path):
    make_qwen2_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "qwen2"
    assert cfg.attention_bias  # Qwen2's QKV bias is the family's signature
    # use_sliding_window=False must gate off the sliding_window field that
    # Qwen2 configs carry anyway.
    assert cfg.sliding_window is None


def test_qwen2_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_qwen2_checkpoint(tmp_path, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    want = hf_greedy(hf_model, prompt, 16)
    got = ours_greedy(tmp_path, prompt, 16)
    assert got == want


def test_qwen2_bias_tensors_loaded(tmp_path):
    make_qwen2_checkpoint(tmp_path, seed=2)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    for k in ("bq", "bk", "bv"):
        assert k in params["layers"]
    assert params["layers"]["bq"].shape == (3, 64)
    assert params["layers"]["bk"].shape == (3, 32)  # 2 kv heads x head_dim 16


# ----------------------------------------------------------------- Mistral


def make_mistral_checkpoint(
    tmp_path, seed=0, sliding_window=None, head_dim=None
):
    kw = {}
    if head_dim is not None:
        kw["head_dim"] = head_dim
    cfg = transformers.MistralConfig(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        sliding_window=sliding_window,
        attn_implementation="eager",
        **kw,
    )
    torch.manual_seed(seed)
    model = transformers.MistralForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_mistral_greedy_full_causal(tmp_path):
    """sliding_window=None Mistral == Llama numerics with its own template."""
    hf_model = make_mistral_checkpoint(tmp_path, seed=3)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "mistral"
    assert cfg.sliding_window is None
    prompt = [256, 11, 205, 499, 3, 3, 64]
    assert ours_greedy(tmp_path, prompt, 12) == hf_greedy(hf_model, prompt, 12)


def test_mistral_sliding_window_logits_match_transformers(tmp_path):
    """Prompt much longer than the window: full-position logits must match,
    proving the window mask (not just causal) is applied."""
    hf_model = make_mistral_checkpoint(tmp_path, seed=4, sliding_window=8)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.sliding_window == 8
    rng = np.random.default_rng(0)
    prompt = [256] + [int(t) for t in rng.integers(0, 512, 40)]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()

    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )

    # And the window genuinely bites: full-causal logits at the last position
    # must NOT match (otherwise this test proves nothing).
    import dataclasses

    full = dataclasses.replace(cfg, sliding_window=None)
    kv2 = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits_full, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv2, jnp.int32(0), full,
        cached_prefill=False,
    )
    assert not np.allclose(
        np.asarray(logits_full[0][-1]), hf_logits[-1], atol=1e-3
    )


def test_mistral_sliding_window_greedy_decode(tmp_path):
    """Greedy decode walks past the window edge: decode-path masking parity."""
    hf_model = make_mistral_checkpoint(tmp_path, seed=5, sliding_window=6)
    prompt = [256, 11, 205, 499, 3, 3, 64, 90, 17, 2]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_mistral_head_dim_override(tmp_path):
    """head_dim decoupled from hidden_size // heads (Mistral-Nemo style)."""
    hf_model = make_mistral_checkpoint(tmp_path, seed=6, head_dim=32)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.head_dim == 32 and cfg.hidden_size == 64
    prompt = [256, 5, 77, 140]
    assert ours_greedy(tmp_path, prompt, 10) == hf_greedy(hf_model, prompt, 10)


# ----------------------------------------------------------------- templates


def test_chatml_template_text():
    msgs = [
        Message.system("You are terse."),
        Message.user("hi"),
        Message.assistant("hello"),
        Message.user("again"),
    ]
    assert encode_dialog_chatml(msgs) == (
        "<|im_start|>system\nYou are terse.<|im_end|>\n"
        "<|im_start|>user\nhi<|im_end|>\n"
        "<|im_start|>assistant\nhello<|im_end|>\n"
        "<|im_start|>user\nagain<|im_end|>\n"
        "<|im_start|>assistant\n"
    )


def test_mistral_template_text():
    msgs = [
        Message.system("Be brief."),
        Message.user("hi"),
        Message.assistant("hello"),
        Message.user("again"),
    ]
    assert encode_dialog_mistral(msgs) == (
        "<s>[INST] Be brief.\n\nhi [/INST]hello</s>[INST] again [/INST]"
    )


def test_encode_dialog_dispatch():
    msgs = [Message.user("x")]
    assert encode_dialog(msgs, "llama").startswith("<|begin_of_text|>")
    assert encode_dialog(msgs, "qwen2").startswith("<|im_start|>")
    assert encode_dialog(msgs, "mistral").startswith("<s>[INST]")
    with pytest.raises(ValueError):
        encode_dialog(msgs, "gpt2")


# ----------------------------------------------------------- composition


def test_qwen2_fused_decode_matches_stepwise(tmp_path):
    """The fused decode scan (models/llama/fused.py) carries the bias path."""
    from cake_tpu.models.llama.fused import build_decode_fn

    make_qwen2_checkpoint(tmp_path, seed=7)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    prompt = [256, 9, 33, 71]
    want = ours_greedy(tmp_path, prompt, 8)

    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",))
    logits, kv = fwd(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(prompt)), cfg,
    )
    first = jnp.argmax(logits, -1).astype(jnp.int32)
    decode = build_decode_fn(cfg, 7, 0.0, None, None, 1.0)
    toks, *_ = decode(
        params, kv, first, jnp.int32(len(prompt)), jax.random.PRNGKey(0),
        jnp.full((1, 0), -1, jnp.int32), jnp.int32(0),
    )
    got = [int(first[0])] + [int(t) for t in np.asarray(toks)[0]]
    assert got == want


def test_mistral_window_quantized_still_runs(tmp_path):
    """int8 quantization composes with the sliding-window + bias-free path."""
    from cake_tpu.ops.quant import quantize_params

    make_mistral_checkpoint(tmp_path, seed=8, sliding_window=6)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = quantize_params(load_params(tmp_path, cfg, jnp.float32))
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits, _ = M.forward(
        params, jnp.asarray([[256, 4, 9]], jnp.int32), kv, jnp.int32(0),
        jnp.int32(3), cfg,
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_serving_engine_uses_family_template(monkeypatch):
    """The API batch engine renders prompts with the family template
    (code-review r2 finding: it hard-coded llama3)."""
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.runtime.serving import BatchEngine

    cfg = LlamaConfig.tiny(num_hidden_layers=2, model_type="qwen2",
                           attention_bias=False)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4, admission_window=0.01,
    )
    seen = []
    tok = eng.tokenizer
    orig = tok.encode
    monkeypatch.setattr(
        tok, "encode", lambda s: (seen.append(s), orig(s))[1]
    )
    eng.start()
    try:
        h = eng.submit(
            [Message.user("hi")], 2,
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        )
        list(h.tokens())
    finally:
        eng.stop()
    assert any("<|im_start|>user\nhi<|im_end|>" in s for s in seen)


def test_batch_generator_uses_family_template():
    from cake_tpu.models.llama.batch import BatchGenerator
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer

    cfg = LlamaConfig.tiny(num_hidden_layers=2, model_type="mistral")
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    tok = ByteTokenizer()
    seen = []
    orig = tok.encode
    tok.encode = lambda s: (seen.append(s), orig(s))[1]
    gen = BatchGenerator(
        cfg, params, tok,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        max_seq_len=128, cache_dtype=jnp.float32,
    )
    gen.generate([[Message.user("x")]], 2)
    assert seen and all(s.startswith("<s>[INST]") for s in seen)


def test_mistral_template_system_edge_cases():
    # System-only dialog renders as one instruction turn, not an empty prompt.
    assert encode_dialog_mistral([Message.system("Be terse.")]) == (
        "<s>[INST] Be terse. [/INST]"
    )
    # A system message after the first user turn would rewrite rendered
    # history — rejected.
    with pytest.raises(ValueError):
        encode_dialog_mistral(
            [Message.user("a"), Message.assistant("b"), Message.system("late")]
        )


def test_qwen2_max_window_layers_gate(tmp_path):
    import json

    make_qwen2_checkpoint(tmp_path)
    cfg_path = tmp_path / "config.json"
    d = json.loads(cfg_path.read_text())
    # Common shipped shape: use_sliding_window on, threshold never reached.
    d["use_sliding_window"] = True
    d["sliding_window"] = 16
    d["max_window_layers"] = d["num_hidden_layers"]
    cfg_path.write_text(json.dumps(d))
    assert LlamaConfig.from_model_dir(tmp_path).sliding_window is None
    # All layers windowed (threshold 0): uniform window, supported.
    d["max_window_layers"] = 0
    cfg_path.write_text(json.dumps(d))
    assert LlamaConfig.from_model_dir(tmp_path).sliding_window == 16
    # Mixed per-layer windows: explicit error, not silent wrong numerics.
    d["max_window_layers"] = 1
    cfg_path.write_text(json.dumps(d))
    with pytest.raises(ValueError, match="max_window_layers"):
        LlamaConfig.from_model_dir(tmp_path)


def test_chatml_default_system_prompt():
    """Qwen2's template injects its default system block when the dialog has
    none (matching transformers apply_chat_template)."""
    out = encode_dialog_chatml([Message.user("hi")])
    assert out == (
        "<|im_start|>system\nYou are a helpful assistant.<|im_end|>\n"
        "<|im_start|>user\nhi<|im_end|>\n"
        "<|im_start|>assistant\n"
    )


def test_qwen2_windowed_config_roundtrip():
    """to_hf_dict -> from_hf_dict preserves sliding_window and
    attention_bias for qwen2 (review finding: the window was silently
    gated off and bias=False flipped to True on reload)."""
    cfg = LlamaConfig.tiny(
        model_type="qwen2", attention_bias=False, sliding_window=16
    )
    back = LlamaConfig.from_hf_dict(cfg.to_hf_dict())
    assert back.sliding_window == 16
    assert back.attention_bias is False


def test_llama2_template_text():
    from cake_tpu.models.llama.chat import encode_dialog_llama2

    msgs = [
        Message.system("Be safe."),
        Message.user("hi"),
        Message.assistant("hello"),
        Message.user("again"),
    ]
    assert encode_dialog_llama2(msgs) == (
        "<s>[INST] <<SYS>>\nBe safe.\n<</SYS>>\n\nhi [/INST] hello </s>"
        "<s>[INST] again [/INST]"
    )
    # No system: plain turns.
    assert encode_dialog_llama2([Message.user("x")]) == "<s>[INST] x [/INST]"


def test_chat_template_override():
    """config.chat_template overrides the family dispatch (--chat-template)."""
    import dataclasses

    cfg = LlamaConfig.tiny()
    assert cfg.dialog_template == "llama"
    cfg2 = dataclasses.replace(cfg, chat_template="llama2")
    assert encode_dialog([Message.user("q")], cfg2.dialog_template).startswith(
        "<s>[INST]"
    )


# ----------------------------------------------------------------- Phi-3


def make_phi3_checkpoint(tmp_path, seed=0, sliding_window=None):
    cfg = transformers.Phi3Config(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        pad_token_id=0,
        bos_token_id=256,
        eos_token_id=260,
        sliding_window=sliding_window,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.Phi3ForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_phi3_config_parses_and_fused_split(tmp_path):
    make_phi3_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "phi3"
    params = load_params(tmp_path, cfg, jnp.float32)
    # Fused qkv/gate_up split into the standard layout at load.
    assert params["layers"]["wq"].shape == (3, 64, 64)
    assert params["layers"]["wk"].shape == (3, 64, 32)
    assert params["layers"]["w_gate"].shape == (3, 64, 128)


def test_phi3_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_phi3_checkpoint(tmp_path, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_phi3_sliding_window_greedy(tmp_path):
    hf_model = make_phi3_checkpoint(tmp_path, seed=2, sliding_window=8)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.sliding_window == 8
    rng = np.random.default_rng(4)
    prompt = [256] + [int(t) for t in rng.integers(0, 512, 30)]
    assert ours_greedy(tmp_path, prompt, 12) == hf_greedy(hf_model, prompt, 12)


def test_phi3_worker_range_fused_split(tmp_path):
    """A worker's layer-range load splits the fused tensors for just its
    range (the config threads through master/worker loading)."""
    make_phi3_checkpoint(tmp_path, seed=3)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    shard = load_params(tmp_path, cfg, jnp.float32, layer_range=(1, 3))
    assert shard["layers"]["wv"].shape == (2, 64, 32)


def test_phi3_longrope_rejected():
    with pytest.raises(ValueError, match="longrope"):
        LlamaConfig.from_hf_dict(
            {
                "model_type": "phi3",
                "hidden_size": 64,
                "num_attention_heads": 4,
                "rope_scaling": {"type": "longrope", "short_factor": [1.0]},
            }
        )


def test_phi3_template_text():
    from cake_tpu.models.llama.chat import encode_dialog_phi3

    msgs = [Message.system("Be terse."), Message.user("hi")]
    assert encode_dialog_phi3(msgs) == (
        "<|system|>\nBe terse.<|end|>\n<|user|>\nhi<|end|>\n<|assistant|>\n"
    )
