"""Latency attribution (obs/critpath.py + the serving engine's live
accounting + GET /explain).

Three layers under test:

  * synthetic span-tree ORACLES — hand-built ring events with known phase
    answers: the decomposition sums to >= 95% of the wall, and a crafted
    mixed-length epoch gives the short lane the higher convoy_frac;
  * the REAL engine — a batch-8 mixed prompt-length serve on a tiny model:
    /explain-grade attribution for every request, short > long convoy,
    aggregate cake_phase_seconds / convoy meter populated;
  * the HTTP surface — /explain's 200/400/404 taxonomy.
"""

import json
import threading
import types
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.obs import critpath
from cake_tpu.obs.timeline import timeline
from cake_tpu.runtime.api import ApiServer
from cake_tpu.runtime.serving import BatchEngine, SamplingConfig

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


# ------------------------------------------------------------ synthetic


def _ev(ph, name, mono, **kw):
    e = {"ph": ph, "name": name, "wall": mono, "mono": mono}
    e.update(kw)
    return e


def _span(name, t0, t1, **kw):
    return _ev("X", name, t0, dur=t1 - t0, **kw)


def mixed_epoch_events():
    """Two co-batched lanes: a 4-token-prompt short request (3 completion
    tokens) and a 64-token-prompt long one (25 tokens) sharing one prefill
    (bucket 64) and three 8-token decode chunks."""
    return [
        _ev("B", "request", 1.0, id=1, rid="short", track="lane0",
            args={"prompt_tokens": 4, "queue_wait_s": 0.5}),
        _ev("B", "request", 1.0, id=2, rid="long", track="lane1",
            args={"prompt_tokens": 64, "queue_wait_s": 0.5}),
        _span("prefill", 1.0, 2.0, track="engine",
              args={"bucket": 64, "lanes": 2}),
        _span("decode-chunk", 2.0, 3.0, track="engine",
              args={"slot": 64, "n": 8, "live": 2}),
        _ev("E", "", 3.0, id=1, args={"finish_reason": "stop",
                                      "completion_tokens": 3}),
        _span("decode-chunk", 3.0, 4.0, track="engine",
              args={"slot": 72, "n": 8, "live": 1}),
        _span("decode-chunk", 4.0, 5.0, track="engine",
              args={"slot": 80, "n": 8, "live": 1}),
        _ev("E", "", 5.0, id=2, args={"finish_reason": "length",
                                      "completion_tokens": 25}),
    ]


def test_oracle_phase_sum_and_values():
    events = mixed_epoch_events()
    res = critpath.explain(events, "short")
    assert res is not None and not res["in_flight"]
    p = res["phases"]
    # wall = 0.5 queue + 2.0 span (1.0 -> 3.0).
    assert res["wall_s"] == pytest.approx(2.5)
    assert p["queue"] == pytest.approx(0.5)
    # Prefill: own share 4/64 of the 1s shared bucket.
    assert p["prefill"] == pytest.approx(1.0 * 4 / 64)
    # Decode: 2 of the chunk's 8 tokens (completion 3, first from prefill).
    assert p["decode"] == pytest.approx(1.0 * 2 / 8)
    # Convoy: the padded prefill remainder + the unconsumed chunk tail.
    assert p["convoy"] == pytest.approx(1.0 * 60 / 64 + 1.0 * 6 / 8)
    # Named phases cover the wall >= 95% (here: exactly).
    assert res["coverage"] >= 0.95
    assert sum(p.values()) == pytest.approx(res["wall_s"], rel=1e-6)


def test_oracle_short_lane_convoy_exceeds_long():
    events = mixed_epoch_events()
    short = critpath.explain(events, "short")
    long_ = critpath.explain(events, "long")
    assert short["convoy_frac"] > long_["convoy_frac"]
    # The long lane consumed every chunk token and its full-width prompt:
    # zero convoy.
    assert long_["phases"]["convoy"] == pytest.approx(0.0)
    assert long_["coverage"] >= 0.95
    assert short["dominant"] == "convoy"


def test_oracle_stall_and_spec_and_wire_attribution():
    events = [
        _ev("B", "request", 0.0, id=9, rid="r", track="lane0",
            args={"prompt_tokens": 32, "queue_wait_s": 0.0}),
        _span("prefill", 0.0, 1.0, track="engine", args={"bucket": 32}),
        # Verify round: 1s, accepted 2 of k=3 (+1) positions.
        _span("spec-round", 1.0, 2.0, track="engine",
              args={"slot": 32, "accepted": 2, "k": 3}),
        # Chunk with a 0.5s watchdog stall inside it.
        _span("decode-chunk", 2.0, 3.0, track="engine",
              args={"slot": 34, "n": 4}),
        _ev("i", "epoch-stall", 2.9, track="engine",
            args={"op": "decode", "stall_s": 0.5}),
        # Wire hop inside the prefill dispatch.
        _span("wire.w0", 0.2, 0.6, track="wire"),
        _ev("E", "", 3.0, id=9, args={"finish_reason": "error",
                                      "completion_tokens": 5}),
    ]
    res = critpath.explain(events, "r")
    p = res["phases"]
    assert p["stall"] == pytest.approx(0.5)
    # completion 5 -> first from prefill, 2 via spec, 2 via the chunk.
    assert p["spec_accepted"] == pytest.approx(1.0 * 2 / 4)
    assert p["spec_wasted"] == pytest.approx(1.0 * 2 / 4)
    assert p["wire"] == pytest.approx(0.4)
    assert res["wire_nodes"] == {"w0": pytest.approx(0.4)}
    # Wire nests inside the prefill dispatch: pulled out of prefill, not
    # decode. The stalled chunk's remaining 0.5s splits 2/4 each way.
    assert p["prefill"] == pytest.approx(1.0 - 0.4)
    assert p["decode"] == pytest.approx(0.5 * 2 / 4)
    assert p["convoy"] == pytest.approx(0.5 * 2 / 4)
    assert sum(p.values()) == pytest.approx(res["wall_s"], rel=1e-6)


def test_oracle_join_and_unknown_and_in_flight():
    events = [
        _ev("B", "request", 5.0, id=4, rid="j", track="lane2",
            args={"prompt_tokens": 8, "queue_wait_s": 1.0, "join_slot": 64}),
        _span("join", 5.0, 5.4, rid="j", track="engine",
              args={"lane": 2, "slot": 64}),
        # Another request's epoch prefill BEFORE the join: must not count.
        _span("prefill", 1.0, 2.0, track="engine", args={"bucket": 64}),
        _span("decode-chunk", 5.4, 6.4, track="engine",
              args={"slot": 64, "n": 8}),
        _ev("E", "", 6.4, id=4, args={"finish_reason": "stop",
                                      "completion_tokens": 9}),
    ]
    res = critpath.explain(events, "j")
    assert res["phases"]["prefill"] == pytest.approx(0.4)
    assert res["phases"]["decode"] == pytest.approx(1.0)
    assert res["phases"]["convoy"] == pytest.approx(0.0)
    assert critpath.explain(events, "nope") is None
    # Open request: explained to the newest event, flagged in_flight.
    open_events = [e for e in events if e.get("ph") != "E"]
    res2 = critpath.explain(open_events, "j")
    assert res2["in_flight"]
    assert critpath.request_ids(events) == ["j"]


def test_oracle_fork_attribution_is_request_relative():
    """Prefix-fork spans attribute relative to the request: the epoch
    fork splits own-share/convoy, the request's own join fork is all its
    own, and ANOTHER request's join (fork included) is convoy — never
    this request's prefix_fork."""
    events = [
        _ev("B", "request", 0.0, id=1, rid="a", track="lane0",
            args={"prompt_tokens": 32, "queue_wait_s": 0.0}),
        # Epoch prefill 1s with a 0.2s layout fork (2 lanes) inside it.
        _span("prefill", 0.0, 1.0, track="engine",
              args={"bucket": 32, "lanes": 2}),
        _span("prefix-fork", 0.1, 0.3, track="engine", args={"lanes": 2}),
        # Another request "b" joins mid-epoch, with its own 0.1s fork.
        _span("join", 1.0, 1.5, rid="b", track="engine",
              args={"lane": 1, "slot": 40}),
        _span("prefix-fork", 1.1, 1.2, track="engine",
              args={"lane": 1, "slot": 40}),
        _span("decode-chunk", 1.5, 2.5, track="engine",
              args={"slot": 40, "n": 4}),
        _ev("E", "", 2.5, id=1, args={"finish_reason": "stop",
                                      "completion_tokens": 5}),
    ]
    res = critpath.explain(events, "a")
    p = res["phases"]
    # Epoch fork: a's share is 1/2 lanes' worth; b's join fork is NOT a's.
    assert p["prefix_fork"] == pytest.approx(0.2 / 2)
    # Prefill net of the fork, full-width prompt -> all own.
    assert p["prefill"] == pytest.approx(0.8)
    # Convoy: the epoch fork's other-lane half + b's whole join.
    assert p["convoy"] == pytest.approx(0.2 / 2 + 0.5)
    assert p["decode"] == pytest.approx(1.0)
    assert sum(p.values()) == pytest.approx(res["wall_s"], rel=1e-6)
    # And b's own view: the join (net of fork) is prefill, fork is fork.
    events_b = events + [
        _ev("B", "request", 1.0, id=2, rid="b", track="lane1",
            args={"prompt_tokens": 8, "queue_wait_s": 0.0,
                  "join_slot": 40}),
        _ev("E", "", 2.5, id=2, args={"finish_reason": "stop",
                                      "completion_tokens": 5}),
    ]
    res_b = critpath.explain(events_b, "b")
    assert res_b["phases"]["prefill"] == pytest.approx(0.4)
    assert res_b["phases"]["prefix_fork"] == pytest.approx(0.1)


def test_render_and_dominant():
    res = critpath.explain(mixed_epoch_events(), "short")
    text = critpath.render(res)
    assert "convoy" in text and "dominant phase: convoy" in text
    assert critpath.dominant({"queue": 2.0, "decode": 1.0}) == "queue"
    # Named phases win ties against the host/other complements.
    assert critpath.dominant({"host": 1.0, "decode": 1.0}) == "decode"


# ------------------------------------------------------------ real engine


def _setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def test_engine_batch8_mixed_lengths_explain():
    """The acceptance gate: a batch-8 mixed prompt-length serve whose
    /explain decomposition sums to >= 95% of each request's measured
    end-to-end latency, with short requests showing the higher
    convoy_frac."""
    cfg, params = _setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=8, max_batch=8,
        admission_window=0.1,
    )
    eng.start()
    try:
        import time as _t

        short_prompts = ["a", "bb", "ccc", "dddd"]
        long_prompts = [
            "the quick brown fox jumps over the lazy dog " * 2,
            "pack my box with five dozen liquor jugs and then " * 2,
            "sphinx of black quartz judge my vow every day now " * 2,
            "how vexingly quick daft zebras jump over the fence " * 2,
        ]
        # Client-side end-to-end measurement per request: submit stamps
        # t0, a drain thread stamps the moment text() returns — the
        # phase-sum gate below compares against THIS, not the response's
        # own wall (host/other are complements of that by construction).
        t0s, done_at, drains = {}, {}, []
        mlock = threading.Lock()

        def submit(prompt, n):
            t0 = _t.monotonic()
            h = eng.submit([Message.user(prompt)], n, GREEDY)
            t0s[h.request_id] = t0

            def drain():
                h.text()
                with mlock:
                    done_at[h.request_id] = _t.monotonic()

            th = threading.Thread(target=drain, daemon=True)
            th.start()
            drains.append(th)
            return h

        shorts = [submit(p, 2) for p in short_prompts]
        longs = [submit(p, 24) for p in long_prompts]
        for th in drains:
            th.join(timeout=120)
        assert eng.stats["max_rows"] == 8  # genuinely co-batched
        events = timeline.snapshot()
        results = {}
        for h in shorts + longs:
            res = critpath.explain(events, h.request_id)
            assert res is not None, h.request_id
            p = res["phases"]
            total = sum(p.values())
            # Decomposition sums to >= 95% of the CLIENT-measured
            # end-to-end latency (small absolute slack for the consumer
            # thread's wakeup after the final token).
            elapsed = done_at[h.request_id] - t0s[h.request_id]
            assert total >= 0.95 * elapsed - 0.05, (h.request_id, res,
                                                   elapsed)
            assert total <= elapsed + 0.05, (h.request_id, res, elapsed)
            results[h.request_id] = res
        short_fracs = [results[h.request_id]["convoy_frac"] for h in shorts]
        long_fracs = [results[h.request_id]["convoy_frac"] for h in longs]
        # Every short co-batched request pays a higher lockstep tax than
        # every long one (pinned pairwise, not just on the means).
        assert min(short_fracs) > max(long_fracs), (short_fracs, long_fracs)
        # Aggregate plane: phase histograms + the per-epoch convoy meter.
        # (Every request observed prefill; a short request that hit EOS on
        # its prefill sample legitimately never saw a decode chunk.) The
        # meter finalizes in the epoch's finally, a beat after the last
        # stream closes — wait it out.
        import time as _t

        deadline = _t.monotonic() + 10.0
        while (
            eng.convoy_stats["epochs"] == 0 and _t.monotonic() < deadline
        ):
            _t.sleep(0.01)
        ps = eng.phase_stats()
        assert ps["phases"].get("prefill", {}).get("requests", 0) >= 8
        assert ps["phases"].get("decode", {}).get("requests", 0) >= len(longs)
        assert ps["phases"].get("convoy", {}).get("seconds", 0.0) > 0.0
        assert ps["convoy"]["epochs"] >= 1
        assert 0.0 < ps["convoy"]["frac_last"] <= 1.0
        from cake_tpu.utils import metrics

        hist = metrics.registry.histogram("cake_phase_seconds")
        assert hist.percentile(50, phase="decode") >= 0.0
        conv = metrics.registry.histogram("cake_convoy_seconds")
        assert conv.dump()["series"], "convoy histogram never observed"
    finally:
        eng.stop()


def test_engine_join_attribution():
    """A request joining a RUNNING epoch gets its join prefill attributed
    as prefill (the span opens BEFORE the join dispatch now)."""
    cfg, params = _setup(seed=33)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=2,
        admission_window=0.02,
    )
    eng.start()
    try:
        h1 = eng.submit([Message.user("hold the epoch open")], 40, GREEDY)
        import time as _t

        while eng.stats["batches"] == 0:
            _t.sleep(0.01)
        _t.sleep(0.2)  # let the epoch pass a few chunk boundaries
        h2 = eng.submit([Message.user("joiner")], 4, GREEDY)
        h2.text()
        h1.text()
        if eng.stats["joins"]:
            res = critpath.explain(timeline.snapshot(), h2.request_id)
            assert res is not None
            assert res["phases"]["prefill"] > 0.0
            assert sum(res["phases"].values()) >= 0.95 * res["wall_s"]
    finally:
        eng.stop()


# ------------------------------------------------------------ HTTP surface


def test_explain_endpoint_taxonomy():
    """GET /explain: 400 without request_id, 404 for unknown ids, 200 with
    the phase decomposition for a served request."""
    cfg, params = _setup(seed=35)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=2,
    )
    api = ApiServer(
        generator=types.SimpleNamespace(sampling=GREEDY), engine=eng,
    )
    server = api.make_server("127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        h = eng.submit([Message.user("explain me")], 4, GREEDY)
        h.text()
        with urllib.request.urlopen(
            f"{base}/explain?request_id={h.request_id}", timeout=10
        ) as r:
            body = json.load(r)
        assert body["request_id"] == h.request_id
        assert body["phases"]["decode"] >= 0.0
        assert body["dominant"] in critpath.PHASES
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/explain", timeout=10)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/explain?request_id=chatcmpl-nope", timeout=10
            )
        assert ei.value.code == 404
        # /stats carries the phases block the CLI renders.
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.load(r)
        assert "phases" in stats and "convoy" in stats["phases"]
    finally:
        server.shutdown()
        eng.stop()


def test_explain_cli_offline_jsonl(tmp_path, capsys):
    """``cake-tpu explain --jsonl``: the offline sweep over a
    --trace-jsonl stream (no server, no jax)."""
    from cake_tpu.cli import _explain_main

    path = tmp_path / "trace.jsonl"
    path.write_text(
        "\n".join(json.dumps(e) for e in mixed_epoch_events()) + "\n"
    )
    assert _explain_main(["--jsonl", str(path)]) == 0
    out = capsys.readouterr().out
    assert "request short" in out and "request long" in out
    assert "dominant phase: convoy" in out
    assert _explain_main(
        ["--jsonl", str(path), "--request-id", "short", "--json"]
    ) == 0
    res = json.loads(capsys.readouterr().out.strip())
    assert res["request_id"] == "short"
    assert _explain_main(
        ["--jsonl", str(path), "--request-id", "missing"]
    ) == 1
    capsys.readouterr()


def test_cli_renders_phases_block():
    from cake_tpu.cli import _render_stats

    text = _render_stats({
        "model": "m", "uptime_s": 1.0, "metrics": {},
        "phases": {
            "phases": {
                "decode": {"seconds": 2.0, "requests": 4},
                "convoy": {"seconds": 1.0, "requests": 4},
            },
            "convoy": {
                "epochs": 2, "seconds_total": 1.0,
                "frac_last": 0.25, "frac_mean": 0.3,
            },
        },
    })
    assert "decode" in text and "convoy" in text
    assert "frac_last=0.250" in text
