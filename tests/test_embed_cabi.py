"""C-ABI embeddable worker (native/embed.c -> libcakeembed.so).

The reference ships its embedding surface as a C-ABI cdylib any host can
link (cake-ios/src/lib.rs:9-56 through uniffi); round 2 only had the Python
``cake_tpu.embed`` counterpart. These tests prove the native library from a
REAL non-Python host: a small C program (tests/embed_host.c) links the
.so, starts a worker, and a distributed master generates through it —
token-exact against the local oracle.
"""

import os
import shutil
import site
import subprocess
import sys
import sysconfig
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.io.safetensors_io import save_tiny_checkpoint

REPO = Path(__file__).resolve().parents[1]
LIB = REPO / "cake_tpu" / "native" / "libcakeembed.so"
HOST_SRC = Path(__file__).parent / "embed_host.c"

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def _build_artifacts(tmp_path):
    """Compile the cdylib (if stale/missing) and the C host program."""
    cc = shutil.which("gcc") or shutil.which("clang")
    if cc is None:
        pytest.skip("no C compiler")
    from cake_tpu.native.build import build_embed

    if build_embed(verbose=False) is None:
        pytest.skip("libcakeembed.so could not be built here")
    host = tmp_path / "embed_host"
    subprocess.run(
        [cc, "-O2", "-Wall", "-Werror", str(HOST_SRC), "-o", str(host),
         f"-L{LIB.parent}", "-lcakeembed", f"-Wl,-rpath,{LIB.parent}"],
        check=True,
    )
    return host


def _host_env():
    """The embedded interpreter starts from the BASE prefix, not this venv:
    hand it our site-packages + repo on PYTHONPATH, and the CPU-safe JAX env
    (the axon tunnel is single-slot; a second registered process deadlocks).
    """
    env = dict(os.environ)
    paths = [str(REPO), *site.getsitepackages()]
    purelib = sysconfig.get_path("purelib")
    if purelib not in paths:
        paths.append(purelib)
    env["PYTHONPATH"] = ":".join(paths)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # The C ABI (like cake-ios) has no dtype parameter; precision comes from
    # env — f32 here so the token oracle is exact vs the f32 local run.
    env["CAKE_EMBED_DTYPE"] = "f32"
    return env


def test_c_host_worker_serves_token_exact(tmp_path):
    """A pure-C host links the cdylib, becomes a worker, and the master's
    stream through it matches the local oracle exactly."""
    import yaml

    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep

    host = _build_artifacts(tmp_path)
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(51), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo_dict = {
        "cnode": {"host": "placeholder", "layers": ["model.layers.1-2"]}
    }
    topo_path = tmp_path / "topology.yml"
    topo_path.write_text(yaml.safe_dump(topo_dict))

    def oracle():
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=96, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        gen.add_message(Message.user("c abi host"))
        gen.generate(5)
        return gen.generated_token_ids

    want = oracle()

    proc = subprocess.Popen(
        [str(host), "cnode", str(model_dir), str(topo_path)],
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=_host_env(),
        text=True,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("READY "), (line, proc.stderr.read())
        port = int(line.split()[1])

        topo = Topology.from_dict(topo_dict)
        topo.nodes["cnode"].host = f"127.0.0.1:{port}"
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=96
        )
        try:
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
            gen.add_message(Message.user("c abi host"))
            gen.generate(5)
            got = gen.generated_token_ids
        finally:
            step.close()
    finally:
        try:
            proc.stdin.close()
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise
    assert rc == 0, proc.stderr.read()
    assert got == want
