"""Cross-implementation numerical parity vs HuggingFace transformers.

SURVEY.md §7 step 2 sets the oracle bar: reproduce a known-good
implementation's tokens for a fixed seed. No real checkpoint is downloadable
in this environment (zero egress), so the known-good implementation comes to
us instead: a randomly-initialized ``transformers`` LlamaForCausalLM (torch,
CPU, f32) is saved with ``save_pretrained`` — a REAL HF checkpoint directory
(config.json + model.safetensors) — loaded through this framework's own
config/safetensors path, and greedy-decoded side by side. This pins, against
an external implementation rather than repo-vs-repo:

  * checkpoint format compatibility (HF tensor names, config schema),
  * RoPE convention (rotate-half, position indexing),
  * GQA head grouping, attention masking/upcast, RMSNorm epsilon placement,
  * logits head slicing and greedy argmax agreement token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.io.safetensors_io import load_params
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig

GEOMS = [
    # (heads, kv_heads): MHA and GQA variants.
    (4, 4),
    (4, 2),
]


def make_hf_checkpoint(tmp_path, n_heads, n_kv, seed=0, tie=False):
    hf_cfg = transformers.LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=n_heads,
        num_key_value_heads=n_kv,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=tie,
        bos_token_id=256,
        eos_token_id=260,
        attention_bias=False,
        mlp_bias=False,
    )
    torch.manual_seed(seed)
    model = transformers.LlamaForCausalLM(hf_cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


def ours_greedy(model_dir, prompt_ids, n_steps):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, kv = fwd(
        params, tokens, kv, jnp.int32(0), jnp.int32(len(prompt_ids)), cfg
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


@pytest.mark.parametrize("n_heads,n_kv", GEOMS)
def test_greedy_tokens_match_transformers(tmp_path, n_heads, n_kv):
    """16-step greedy token equality, MHA and GQA (the §7 step-2 oracle).
    Value-level logits agreement is pinned by the prefill test below."""
    hf_model = make_hf_checkpoint(tmp_path, n_heads, n_kv, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    want = hf_greedy(hf_model, prompt, 16)
    got = ours_greedy(tmp_path, prompt, 16)
    assert got == want


def test_prefill_logits_match_transformers(tmp_path):
    """Full-position logits agreement (not just argmax) on the prompt."""
    hf_model = make_hf_checkpoint(tmp_path, 4, 2, seed=2)
    prompt = [256, 11, 205, 499, 3, 3, 64]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()

    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=2e-4, rtol=2e-4
    )


def test_tied_embeddings_checkpoint(tmp_path):
    """tie_word_embeddings=True checkpoints (Llama 3.2 style): no lm_head
    tensor on disk; the loader must reuse the embedding."""
    hf_model = make_hf_checkpoint(tmp_path, 4, 2, seed=3, tie=True)
    prompt = [256, 88, 10, 400]
    want = hf_greedy(hf_model, prompt, 10)
    got = ours_greedy(tmp_path, prompt, 10)
    assert got == want
