"""Rolling-window KV cache (sliding-window models, models/llama/cache.py).

The reference's sliding-window trim is the buggy part of its cache
(cache.rs:105-116, SURVEY §2.6); here the window bound is exact: KV memory is
window + chunk budget, position p lives in slot p % cache_len, and slot
positions are reconstructed at read time. Oracles: HF transformers (external
truth) and the dense-cache path (internal equivalence) — the rolling layout
must be invisible in the tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def _win_cfg(**kw):
    kw.setdefault("model_type", "mistral")
    kw.setdefault("sliding_window", 8)
    kw.setdefault("num_hidden_layers", 3)
    return LlamaConfig.tiny(**kw)


def drive_chunked(step, prompt_ids, n_steps, chunk=16):
    """Prefill in fixed chunks then greedy-decode; returns generated ids."""
    pos = 0
    logits = None
    ids = list(prompt_ids)
    while pos < len(ids):
        part = ids[pos : pos + chunk]
        buf = np.zeros((1, chunk), np.int32)
        buf[0, : len(part)] = part
        logits = step(buf, pos, len(part))
        pos += len(part)
    out = []
    for _ in range(n_steps):
        nxt = int(np.argmax(logits[0]))
        out.append(nxt)
        logits = step(np.asarray([[nxt]], np.int32), pos, 1)
        pos += 1
    return out


def test_rolling_activates_and_shrinks_cache():
    cfg = _win_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    dense = LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    roll = LocalForwardStep(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32, rolling_budget=16
    )
    assert not dense.rolling and roll.rolling
    assert dense._kv.max_seq_len == 256
    assert roll._kv.max_seq_len == 128  # round_up(8 + 16) to the 128 tile
    assert roll.max_seq_len == 256  # the LOGICAL bound is unchanged


def test_rolling_matches_dense_oracle_across_wraparound():
    """Greedy ids identical to the dense cache while decode wraps the ring
    several times (prompt 40 + 120 generated >> cache_len 128)."""
    cfg = _win_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    rng = np.random.default_rng(0)
    prompt = [int(t) for t in rng.integers(0, 256, 40)]

    dense = LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    roll = LocalForwardStep(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32, rolling_budget=16
    )
    want = drive_chunked(dense, prompt, 120)
    got = drive_chunked(roll, prompt, 120)
    assert got == want


def test_rolling_matches_transformers(tmp_path):
    """External oracle: rolling-cache greedy ids == HF transformers on a real
    Mistral checkpoint with a window far smaller than the prompt."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from cake_tpu.io.safetensors_io import load_params

    hf_cfg = transformers.MistralConfig(
        hidden_size=64, intermediate_size=128, vocab_size=512,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False, bos_token_id=256, eos_token_id=260,
        sliding_window=8, attn_implementation="eager",
    )
    torch.manual_seed(9)
    hf = transformers.MistralForCausalLM(hf_cfg).eval().to(torch.float32)
    hf.save_pretrained(tmp_path, safe_serialization=True)

    rng = np.random.default_rng(3)
    prompt = [256] + [int(t) for t in rng.integers(0, 512, 39)]
    ids = torch.tensor([prompt], dtype=torch.long)
    want = []
    with torch.no_grad():
        for _ in range(20):
            nxt = int(torch.argmax(hf(ids).logits[0, -1]))
            want.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)

    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    roll = LocalForwardStep(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32, rolling_budget=16
    )
    assert roll.rolling
    assert drive_chunked(roll, prompt, 20) == want


def test_rolling_fused_decode_matches_stepwise():
    """decode_chunk (fused scan) over the rolling cache == per-step decode."""
    cfg = _win_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)

    def run(decode_chunk_size):
        step = LocalForwardStep(
            cfg, params, max_seq_len=256, cache_dtype=jnp.float32,
            rolling_budget=16,
        )
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(), GREEDY,
            decode_chunk_size=decode_chunk_size, prefill_chunk=16,
        )
        gen.add_message(Message.user("rolling cache fused decode oracle"))
        gen.generate(24)
        return gen.generated_token_ids

    assert run(6) == run(1)


def test_rolling_rejects_oversized_chunk():
    cfg = _win_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    roll = LocalForwardStep(
        cfg, params, max_seq_len=512, cache_dtype=jnp.float32, rolling_budget=16
    )
    # room = 128 - 8 = 120; a 121-token chunk could evict live-window keys.
    with pytest.raises(ValueError, match="rolling"):
        roll(np.zeros((1, 121), np.int32), 0, 121)


def test_rolling_disables_prefix_reuse():
    """A rolling cache cannot carry a KV prefix across reset() — turn 2 must
    re-prefill and still produce oracle tokens."""
    cfg = _win_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(4), jnp.float32)
    step = LocalForwardStep(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32, rolling_budget=16
    )
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), GREEDY, prefill_chunk=16, prefix_cache=True
    )
    gen.add_message(Message.user("first turn with some words"))
    gen.generate(12)
    first = list(gen.generated_token_ids)
    gen.reset()
    assert gen._reusable == []  # no stale-slot reuse
    gen.add_message(Message.user("first turn with some words"))
    gen.generate(12)
    assert list(gen.generated_token_ids) == first


def test_rolling_noop_for_dense_models():
    """rolling_budget on a full-causal model is ignored (no window to bound)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    step = LocalForwardStep(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32, rolling_budget=16
    )
    assert not step.rolling
    assert step._kv.max_seq_len == 256