"""Per-tenant SLO tracking (obs/slo.py) + the admission feedback seams.

Pins the documented SLI contract (TTFT misses include tokenless deadline/
error deaths; deadline rate counts only deadline-carrying requests), the
multiwindow burn-rate math (min(fast, slow) per objective, max across
objectives), the FairQueue quantum-weight and WaitEstimator shed-scale
feedback, and the GET /slo endpoint.
"""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.obs.slo import SloObjectives, SloTracker
from cake_tpu.runtime.admission import FairQueue, WaitEstimator
from cake_tpu.utils import metrics


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def tracker(clock, **kw):
    obj = SloObjectives(
        ttft_ms=kw.pop("ttft_ms", 100.0),
        ttft_target=kw.pop("ttft_target", 0.9),
        deadline_rate=kw.pop("deadline_rate", 0.9),
    )
    return SloTracker(
        obj, fast_window_s=kw.pop("fast", 12.0),
        slow_window_s=kw.pop("slow", 120.0), time_fn=clock, **kw,
    )


# ----------------------------------------------------------------- burn math


def test_ttft_burn_rate_windows():
    clock = FakeClock()
    t = tracker(clock)
    for _ in range(10):
        t.observe_ttft("good", 0.05)   # within the 100 ms objective
        t.observe_ttft("bad", 0.5)     # 5x over it
    assert t.burn("good") == 0.0
    # 100% misses against a 10% budget: burn = 10 in BOTH windows.
    assert t.burn("bad") == pytest.approx(10.0)
    snap = t.snapshot()
    assert snap["tenants"]["bad"]["fast"]["burn"]["ttft"] == pytest.approx(
        10.0
    )
    assert snap["tenants"]["bad"]["slow"]["burn"]["ttft"] == pytest.approx(
        10.0
    )
    # p99 reflects the actual samples.
    assert snap["tenants"]["bad"]["fast"]["ttft_p99_s"] == pytest.approx(
        0.5
    )


def test_burn_needs_both_windows():
    """min(fast, slow): once the misses age out of the FAST window the
    headline burn drops to 0 even though the slow window still sees them
    — and a long-past incident alone never re-triggers."""
    clock = FakeClock()
    t = tracker(clock)
    for _ in range(5):
        t.observe_ttft("bad", 0.5)
    assert t.burn("bad") > 1.0
    clock.t += 30.0  # past the 12 s fast window, inside the 120 s slow one
    assert t.snapshot()["tenants"]["bad"]["slow"]["burn"]["ttft"] > 1.0
    assert t.burn("bad") == 0.0


def test_deadline_rate_and_tokenless_ttft_miss():
    clock = FakeClock()
    t = tracker(clock)
    # 3 deadline-carrying requests: 2 hit, 1 expires queued (tokenless).
    t.observe_finish("a", "stop", tokens=10, had_deadline=True)
    t.observe_finish("a", "length", tokens=8, had_deadline=True)
    t.observe_finish(
        "a", "deadline", had_deadline=True, got_first_token=False
    )
    w = t.snapshot()["tenants"]["a"]["fast"]
    assert w["deadline_hit_rate"] == pytest.approx(2 / 3, abs=1e-3)
    # The tokenless death is also a TTFT miss by definition.
    assert w["burn"]["ttft"] == pytest.approx((1 / 1) / 0.1)
    # Deadline burn: (1/3) / 0.1.
    assert w["burn"]["deadline"] == pytest.approx((1 / 3) / 0.1, abs=0.05)
    # A tenant with no deadline-carrying traffic reports None, not 1.0.
    t.observe_finish("b", "stop", tokens=4)
    assert t.snapshot()["tenants"]["b"]["fast"]["deadline_hit_rate"] is None


def test_deadline_sli_excludes_error_and_cancelled_outcomes():
    """Errored/cancelled deadline-carrying requests are neither hits nor
    misses: a tenant whose deadline traffic all errored must NOT read as
    100% hit rate (errors surface in the error-rate SLI instead)."""
    clock = FakeClock()
    t = tracker(clock)
    t.observe_finish("a", "error", had_deadline=True)
    t.observe_finish("a", "cancelled", had_deadline=True)
    w = t.snapshot()["tenants"]["a"]["fast"]
    assert w["deadline_hit_rate"] is None  # no countable deadline sample
    assert w["error_rate"] == pytest.approx(0.5)
    t.observe_finish("a", "deadline", had_deadline=True,
                     got_first_token=False)
    w = t.snapshot()["tenants"]["a"]["fast"]
    assert w["deadline_hit_rate"] == 0.0  # 0 hits / 1 countable sample


def test_goodput_and_shed_rate():
    clock = FakeClock()
    t = tracker(clock, fast=10.0)
    t.observe_finish("a", "stop", tokens=30)
    t.observe_finish("a", "length", tokens=20)
    t.observe_finish("a", "error")          # contributes no good tokens
    t.observe_refusal("a", "shed")
    t.observe_refusal("a", "quota")
    w = t.snapshot()["tenants"]["a"]["fast"]
    assert w["goodput_tok_s"] == pytest.approx(50 / 10.0)
    assert w["error_rate"] == pytest.approx(1 / 3, abs=1e-3)
    assert w["shed_rate"] == pytest.approx(2 / 5)
    # The 503-vs-429 split survives into the window breakdown.
    assert w["refusals"] == {"shed": 1, "quota": 1}


def test_adjustments_and_transition_events():
    clock = FakeClock()
    t = tracker(clock)
    for _ in range(5):
        t.observe_ttft("bad", 0.5)
        t.observe_ttft("good", 0.01)
    adj = t.adjustments()
    assert adj["good"] == {
        "burn": 0.0, "quantum_weight": 1.0, "shed_scale": 1.0
    }
    assert adj["bad"]["burn"] > 1.0
    assert 1.0 < adj["bad"]["quantum_weight"] <= 4.0
    assert 1.0 < adj["bad"]["shed_scale"] <= 4.0
    burning = [
        e for e in metrics.flight.snapshot() if e["event"] == "slo-burn"
    ]
    assert len(burning) == 1 and burning[0]["state"] == "burning"
    # Recovery (misses age out of the fast window) emits the transition
    # exactly once.
    clock.t += 30.0
    t.adjustments()
    t.adjustments()
    events = [
        e for e in metrics.flight.snapshot() if e["event"] == "slo-burn"
    ]
    assert [e["state"] for e in events] == ["burning", "recovered"]


def test_tenant_eviction_bounds_label_space():
    clock = FakeClock()
    t = SloTracker(
        SloObjectives(), fast_window_s=10, slow_window_s=20,
        max_tenants=3, time_fn=clock,
    )
    for i in range(10):
        t.observe_ttft(f"t{i}", 0.01)
    assert len(t.snapshot()["tenants"]) == 3


def test_refresh_metrics_zeroes_evicted_tenant_gauges():
    """An LRU-evicted tenant's exported burn gauge must not stand as a
    permanent false alert — the next refresh zeroes its series."""
    clock = FakeClock()
    t = SloTracker(
        SloObjectives(ttft_ms=100.0, ttft_target=0.9),
        fast_window_s=10, slow_window_s=20, max_tenants=2, time_fn=clock,
    )
    t.observe_ttft("ghost", 5.0)  # burning
    t.refresh_metrics()
    head = metrics.registry.gauge("cake_slo_tenant_burn")
    assert head.value(tenant="ghost") > 1.0
    t.observe_ttft("a", 0.01)
    t.observe_ttft("b", 0.01)  # evicts "ghost" (max_tenants=2)
    assert "ghost" not in t.snapshot()["tenants"]
    t.refresh_metrics()
    assert head.value(tenant="ghost") == 0.0


# ------------------------------------------------------------ feedback seams


def test_fair_queue_weight_biases_service():
    class Req:
        def __init__(self, tenant):
            self.tenant = tenant

    q = FairQueue(fair=True, quantum=1)
    for _ in range(6):
        q.append(Req("a"))
        q.append(Req("b"))
    q.set_weight("a", 3.0)
    taken = q.take(8, lambda r: "take")
    by_tenant = [r.tenant for r in taken]
    # One DRR rotation grants a 3 quanta for b's 1: a drains 3:1.
    assert by_tenant.count("a") == 6
    assert by_tenant.count("b") == 2
    # Weight 1.0 removes the entry; service reverts to even shares.
    q.set_weight("a", 1.0)
    assert q.weight("a") == 1.0
    # fair=False has no subqueues for a weight to act on: silent no-op.
    fifo = FairQueue(fair=False, quantum=1)
    fifo.set_weight("a", 3.0)
    assert fifo.weight("a") == 1.0


def test_wait_estimator_scale_inflates_estimate():
    est = WaitEstimator()
    est.observe(1.0)
    base = est.estimate(0, 8)
    assert est.estimate(0, 8, scale=3.0) == pytest.approx(3 * base)
    assert est.estimate(0, 8, scale=0.5) == base  # never deflates


@pytest.fixture(scope="module")
def tiny_engine():
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=64, cache_dtype=jnp.float32,
        serve=ServeConfig(
            max_batch=2, decode_chunk_size=4,
            slo_ttft_ms=100.0, slo_ttft_target=0.9,
            slo_deadline_rate=0.9,
            slo_fast_window_s=10.0, slo_slow_window_s=60.0,
        ),
    )
    yield eng
    eng.stop()


def test_engine_feedback_applies_weights_and_shed_scale(tiny_engine):
    from cake_tpu.runtime.serving import EngineOverloaded

    eng = tiny_engine
    for _ in range(5):
        eng.slo.observe_ttft("abuser", 5.0)  # 50x over the objective
    eng._apply_slo_feedback(force=True)
    assert eng._queue.weight("abuser") > 1.0
    assert eng._slo_shed_scale["abuser"] > 1.0
    # The scaled estimate sheds the burning tenant's doomed deadline while
    # the same deadline from a compliant tenant still queues.
    eng._wait_est.observe(1.0)
    with pytest.raises(EngineOverloaded):
        eng._maybe_shed(8, deadline_s=2.0, tenant="abuser")
    eng._maybe_shed(8, deadline_s=2.0, tenant="calm")  # no raise
    # Recovery resets both knobs.
    eng.slo._time = lambda: 1e9  # everything ages out
    eng._apply_slo_feedback(force=True)
    assert eng._queue.weight("abuser") == 1.0
    assert "abuser" not in eng._slo_shed_scale


def test_engine_resets_weight_of_tracker_evicted_tenant(tiny_engine):
    """A burning (weighted) tenant the tracker LRU-evicts must still get
    its fair-queue weight reset — a boosted share must never outlive the
    burn that earned it."""
    import time as _time

    eng = tiny_engine
    eng.slo._time = _time.monotonic
    for _ in range(5):
        eng.slo.observe_ttft("ghost", 5.0)
    eng._apply_slo_feedback(force=True)
    assert eng._queue.weight("ghost") > 1.0
    # Churn enough other tenants to evict "ghost" from the tracker.
    for i in range(eng.slo.max_tenants + 5):
        eng.slo.observe_ttft(f"filler{i}", 0.001)
    assert "ghost" not in eng.slo.snapshot()["tenants"]
    eng._apply_slo_feedback(force=True)
    assert eng._queue.weight("ghost") == 1.0


def test_fail_request_feeds_error_sli(tiny_engine):
    """Error finishes that bypass _RowState.finish (a joiner stranded by
    a worker failure) still land in the tenant's error/TTFT SLIs."""
    import time as _time

    from cake_tpu.runtime.serving import _fail_request, _Request, StreamHandle

    eng = tiny_engine
    eng.slo._time = _time.monotonic
    from cake_tpu.models.llama.generator import SamplingConfig

    req = _Request(
        [1, 2, 3], 4, SamplingConfig(), StreamHandle(3, "rid-x"),
        rid="rid-x", tenant="victim", deadline=_time.monotonic() + 9,
    )
    _fail_request(req, "worker died", engine=eng)
    w = eng.slo.snapshot()["tenants"]["victim"]["fast"]
    assert w["error_rate"] == 1.0
    assert w["burn"]["ttft"] > 0  # tokenless error = TTFT miss
    assert req.handle.finish_reason == "error"


def test_slo_endpoint(tiny_engine):
    from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig
    from cake_tpu.runtime.api import ApiServer

    eng = tiny_engine
    eng.slo._time = __import__("time").monotonic  # restore real clock
    eng.slo.observe_finish(
        "storm", "deadline", had_deadline=True, got_first_token=False
    )
    eng.slo.observe_ttft("gold", 0.01)
    eng.slo.observe_finish("gold", "stop", tokens=5)

    step = type(
        "S", (), {"max_seq_len": 64, "trace_id": None}
    )()
    gen = LlamaGenerator.__new__(LlamaGenerator)  # route-only server
    gen.step = step
    gen.sampling = SamplingConfig()
    api = ApiServer.__new__(ApiServer)
    api.generator = gen
    api.model_name = "tiny"
    api.default_max_tokens = 8
    api.stream_write_timeout = 5.0
    api.engine = eng
    api.events_jsonl = None
    api.trace_jsonl = None
    api._lock = threading.Lock()
    api._started = 0
    server = api.make_server("127.0.0.1", 0)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{server.server_address[1]}"
        with urllib.request.urlopen(base + "/slo", timeout=10) as r:
            body = json.load(r)
        assert body["objectives"]["ttft_ms"] == 100.0
        assert body["windows"] == {"fast_s": 10.0, "slow_s": 60.0}
        assert body["tenants"]["storm"]["burn_rate"] > 0
        assert body["tenants"]["gold"]["burn_rate"] == 0.0
        # /metrics refreshes the cake_slo_* gauges at scrape time.
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        assert "cake_slo_tenant_burn" in text
        assert 'cake_slo_burn_rate{objective="ttft"' in text
    finally:
        server.shutdown()
