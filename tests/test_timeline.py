"""Timeline profiler (cake_tpu/obs/timeline.py): span trees, Perfetto export
schema, bounded-ring eviction, flow arrows, concurrent JSONL streams.

The export contract these tests pin is what Perfetto/chrome://tracing depend
on: valid trace-event JSON, every "B" matched by an "E" on its track, flow
events that land inside real slices. No jax needed anywhere here.
"""

import json
import threading

from cake_tpu.obs.timeline import (
    Timeline,
    export_events,
    load_jsonl,
    validate_export,
)

# ------------------------------------------------------------- span trees


def test_nested_spans_record_parent_ids():
    tl = Timeline()
    with tl.span("outer") as outer_id:
        with tl.span("inner") as inner_id:
            pass
    events = tl.snapshot()
    inner = next(e for e in events if e["name"] == "inner")
    outer = next(e for e in events if e["name"] == "outer")
    assert inner["parent"] == outer_id
    assert "parent" not in outer or outer["parent"] is None
    assert inner["id"] == inner_id
    # Both clocks on every event.
    for e in events:
        assert "wall" in e and "mono" in e


def test_span_attrs_and_request_id_ride_along():
    tl = Timeline()
    with tl.span("work", rid="req-1", track="lane0", args={"k": 3}):
        pass
    (ev,) = tl.snapshot()
    assert ev["rid"] == "req-1"
    assert ev["track"] == "lane0"
    assert ev["args"] == {"k": 3}
    assert ev["dur"] >= 0


def test_begin_end_pairs_by_id():
    tl = Timeline()
    sid = tl.begin("request", rid="r", track="lane1")
    tl.instant("first-token", rid="r", track="lane1")
    tl.end(sid, args={"finish_reason": "stop"})
    trace = tl.export()
    assert validate_export(trace) == []
    phases = [e["ph"] for e in trace["traceEvents"] if e["ph"] != "M"]
    assert phases.count("B") == 1 and phases.count("E") == 1
    b = next(e for e in trace["traceEvents"] if e["ph"] == "B")
    e = next(e for e in trace["traceEvents"] if e["ph"] == "E")
    assert b["name"] == e["name"] == "request"
    assert e["ts"] >= b["ts"]


def test_open_span_is_not_half_exported():
    """A B without its E yet (request still running) must not emit a lone
    "B" — the schema contract is every exported B has a matching E."""
    tl = Timeline()
    tl.begin("request", track="lane0")
    trace = tl.export()
    assert validate_export(trace) == []
    assert all(e["ph"] not in ("B", "E") for e in trace["traceEvents"])


def test_aggregate_total_and_self_time():
    tl = Timeline()
    import time

    with tl.span("outer"):
        time.sleep(0.01)
        with tl.span("inner"):
            time.sleep(0.01)
    agg = tl.aggregate()
    assert agg["outer"]["count"] == 1
    assert agg["inner"]["count"] == 1
    # Outer total covers inner; outer SELF excludes it.
    assert agg["outer"]["total_s"] >= agg["inner"]["total_s"]
    assert agg["outer"]["self_s"] < agg["outer"]["total_s"]


# ------------------------------------------------------------- exporter


def test_export_assigns_pids_by_node_and_tids_by_track():
    tl = Timeline(node="master")
    with tl.span("a", track="engine"):
        pass
    with tl.span("b", track="wire"):
        pass
    with tl.span("c", node="worker0", track="ops"):
        pass
    trace = tl.export()
    assert validate_export(trace) == []
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    procs = {
        e["args"]["name"] for e in meta if e["name"] == "process_name"
    }
    assert procs == {"master", "worker0"}
    threads = {
        e["args"]["name"] for e in meta if e["name"] == "thread_name"
    }
    assert {"engine", "wire", "ops"} <= threads
    a = next(e for e in trace["traceEvents"] if e.get("name") == "a")
    c = next(e for e in trace["traceEvents"] if e.get("name") == "c")
    assert a["pid"] != c["pid"]


def test_flow_events_pair_and_validate():
    tl = Timeline()
    with tl.span("wire.w0", track="wire"):
        tl.flow_start(42, "hop", track="wire")
    with tl.span("worker.chunk", node="w0", track="ops"):
        tl.flow_end(42, "hop", node="w0", track="ops")
    trace = tl.export()
    assert validate_export(trace) == []
    s = next(e for e in trace["traceEvents"] if e["ph"] == "s")
    f = next(e for e in trace["traceEvents"] if e["ph"] == "f")
    assert s["id"] == f["id"] == 42
    assert f["bp"] == "e"
    # The two ends live on different pids: the cross-node arrow.
    assert s["pid"] != f["pid"]


def test_validator_catches_orphan_flow_and_unpaired_b():
    bad = {
        "traceEvents": [
            {"ph": "B", "name": "x", "pid": 1, "tid": 1, "ts": 0.0},
            {"ph": "f", "name": "hop", "pid": 1, "tid": 1, "ts": 1.0,
             "id": 7, "bp": "e"},
        ]
    }
    problems = validate_export(bad)
    assert any("never closed" in p for p in problems)
    assert any("no 's'" in p for p in problems)


def test_validator_reports_idless_flow_instead_of_crashing():
    problems = validate_export(
        {"traceEvents": [{"ph": "s", "name": "hop", "pid": 1, "tid": 1,
                          "ts": 0.0}]}
    )
    assert any("lacks an id" in p for p in problems)


def test_validator_catches_flow_outside_any_slice():
    # An arrow anchored in empty space on its track renders detached.
    bad = {
        "traceEvents": [
            {"ph": "X", "name": "op", "pid": 1, "tid": 1, "ts": 0.0,
             "dur": 5.0},
            {"ph": "s", "name": "hop", "pid": 1, "tid": 1, "ts": 2.0,
             "id": 1},           # inside the slice: fine
            {"ph": "f", "name": "hop", "pid": 1, "tid": 1, "ts": 99.0,
             "id": 1, "bp": "e"},  # way past it: flagged
        ]
    }
    problems = validate_export(bad)
    assert any("lands in no slice" in p and "99.0" in p for p in problems)
    assert not any("2.0" in p for p in problems)


def test_request_id_filter_keeps_the_requests_pairs():
    tl = Timeline()
    sid = tl.begin("request", rid="want", track="lane0")
    tl.begin("request", rid="other", track="lane1")
    tl.end(sid)
    events = tl.snapshot(request_id="want")
    assert {e.get("rid") for e in events if e.get("ph") == "B"} == {"want"}
    # The E (which carries no rid itself) is retained through its B's id.
    assert any(e["ph"] == "E" for e in events)
    trace = tl.export(request_id="want")
    assert validate_export(trace) == []
    assert any(e["ph"] == "B" for e in trace["traceEvents"])


# ------------------------------------------------------------- bounded ring


def test_ring_eviction_bounds_and_export_stays_valid():
    tl = Timeline(capacity=16)
    # Far more spans than capacity: the ring keeps the newest 16 events and
    # the exporter drops eviction orphans (an E whose B was evicted) rather
    # than emitting an unpaired end.
    for i in range(100):
        sid = tl.begin(f"s{i}")
        tl.end(sid)
    assert len(tl.snapshot()) == 16
    trace = tl.export()
    assert validate_export(trace) == []
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "B"]
    assert names and all(n >= "s92" for n in names)  # newest survive


def test_eviction_orphan_end_is_dropped():
    tl = Timeline(capacity=4)
    sid = tl.begin("victim")
    for i in range(4):  # push the B out of the ring; keep the E
        tl.instant(f"i{i}")
    tl.end(sid)
    ring = tl.snapshot()
    assert any(e["ph"] == "E" for e in ring)  # orphan E is IN the ring
    trace = tl.export()
    assert validate_export(trace) == []
    assert all(e["ph"] not in ("B", "E") for e in trace["traceEvents"])


# ------------------------------------------------------------- JSONL sink


def test_concurrent_streams_write_valid_jsonl(tmp_path):
    """N threads spanning concurrently while the JSONL sink is attached:
    every line must parse (whole-line appends), and the rebuilt export must
    validate — the `--trace-jsonl` + `cake-tpu trace --jsonl` path."""
    path = str(tmp_path / "trace.jsonl")
    tl = Timeline(capacity=64)  # smaller than the event count: sink >> ring
    tl.attach_jsonl(path)

    def work(t):
        for i in range(50):
            with tl.span(f"t{t}.work", track=f"lane{t}", args={"i": i}):
                pass

    threads = [threading.Thread(target=work, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tl.attach_jsonl(None)

    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    assert len(lines) == 6 * 50
    events = [json.loads(ln) for ln in lines]  # every line valid JSON
    assert events == load_jsonl(path)
    trace = export_events(events)
    assert validate_export(trace) == []
    assert len([e for e in trace["traceEvents"] if e["ph"] == "X"]) == 300


def test_export_events_roundtrips_through_json():
    tl = Timeline()
    with tl.span("a", rid="r", args={"n": 1}):
        tl.counter("hbm", {"bytes_in_use": 123.0}, track="mem")
    trace = json.loads(json.dumps(tl.export()))
    assert validate_export(trace) == []
    c = next(e for e in trace["traceEvents"] if e["ph"] == "C")
    assert c["args"] == {"bytes_in_use": 123.0}


# ------------------------------------------------------------- integrations


def test_trace_spans_bridge_into_timeline():
    """utils/trace.py's global registry feeds the timeline (the satellite:
    hop/stage spans merge into the Perfetto view with both clocks)."""
    from cake_tpu.obs.timeline import timeline
    from cake_tpu.utils import trace

    with trace.span("hop.test-node"):
        pass
    assert trace.spans.snapshot()["hop.test-node"]["count"] == 1
    names = {e["name"] for e in timeline.snapshot()}
    assert "hop.test-node" in names


def test_eight_stream_paged_serving_exports_connected_trace():
    """Acceptance: the PR 4 capacity scenario (8 concurrent short streams
    through a paged pool at HALF the dense footprint) exports ONE
    Perfetto-loadable trace: per-lane request tracks from admission to
    finish, engine prefill/decode/page-extend spans, and the memory counter
    track — all schema-valid."""
    import jax
    import jax.numpy as jnp

    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.obs.timeline import timeline
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(21), jnp.float32)
    pages_per_seq = 256 // 16
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32,
        serve=ServeConfig(
            max_batch=8, decode_chunk_size=4, admission_window=0.1,
            kv_mode="paged", page_size=16,
            max_pages=4 * pages_per_seq,  # half the dense 8-lane footprint
        ),
    )
    eng.start()
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    try:
        handles = [
            eng.submit([Message.user(f"stream number {i}")], 20, greedy)
            for i in range(8)
        ]
        rids = [h.request_id for h in handles]
        for h in handles:
            assert sum(1 for _ in h.tokens()) >= 1
    finally:
        eng.stop()

    trace = timeline.export()
    assert validate_export(trace) == []
    events = trace["traceEvents"]
    names = {e["name"] for e in events if e["ph"] != "M"}
    assert {"epoch", "prefill", "decode-chunk", "page-extend"} <= names
    # Per-lane tracks: every admitted request renders as a closed B/E pair
    # on a laneN thread, admission -> finish.
    lane_tracks = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "thread_name"
        and e["args"]["name"].startswith("lane")
    }
    assert len(lane_tracks) == 8
    req_b = [e for e in events if e["ph"] == "B" and e["name"] == "request"]
    assert {e["args"]["request_id"] for e in req_b} == set(rids)
    assert len([e for e in events if e["ph"] == "E"]) == len(req_b)
    # The memory counter track (host RSS on CPU; HBM on real devices) and
    # the paged-pool occupancy counters line up on the same clock.
    counters = {e["name"] for e in events if e["ph"] == "C"}
    assert "host_rss" in counters and "kv_pages" in counters
    # The raw ring events carry the sampling phase tag (chart args stay
    # numeric); "prefill" fires unthrottled so it always survives the ring.
    tags = {
        e.get("tag") for e in timeline.snapshot()
        if e.get("ph") == "C" and e["name"] == "host_rss"
    }
    assert "prefill" in tags or "epoch-end" in tags
    # Perfetto-loadable: serializes as strict JSON.
    json.dumps(trace)


def test_flight_events_carry_mono_and_span_id():
    """FlightRecorder events gain a monotonic clock and, when a timeline
    span is open, its id (the satellite's /events <-> trace link)."""
    from cake_tpu.obs.timeline import timeline
    from cake_tpu.utils import metrics

    with timeline.span("epoch") as sid:
        ev = metrics.flight.record("admitted", "req-x", lane=2)
    assert ev["span"] == sid
    assert "mono" in ev and "ts" in ev
    outside = metrics.flight.record("finished", "req-x")
    assert "span" not in outside
