"""Test environment: force an 8-device virtual CPU mesh before JAX initializes.

Multi-device sharding/pipeline tests run against virtual CPU devices (the TPU
analogue of the reference's "spawn N workers on localhost" testability seam,
SURVEY.md §4) — real-chip behavior is covered by bench.py and the driver's
dryrun_multichip pass.
"""

import os

# FORCE cpu: the ambient environment pins JAX_PLATFORMS=axon (single-slot TPU
# tunnel — concurrent processes deadlock on it, and tests must not hold the chip).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (must come after the env setup above)

# A sitecustomize may have registered the TPU backend and programmatically set
# jax_platforms before this conftest ran; the env var alone does not win. Force
# the config so tests always see the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

# XLA-CPU's default matmul precision runs f32 dots through a ~bf16 fast path,
# which breaks exact cached-vs-uncached oracles; tests pin full f32.
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_telemetry():
    """Fresh span/metric/flight state for every test.

    trace.spans, metrics.registry, and metrics.flight are process-global by
    design (one registry serves the whole runtime); without this reset a test
    asserting on counts would see whatever earlier test modules recorded.
    Cleared BEFORE the test (leaked state from module-scoped fixtures is the
    common offender), and call sites re-create metrics on first use, so
    clearing can never leave a stale metric object recording off-registry.
    """
    import sys

    from cake_tpu.utils import metrics, trace

    trace.spans.clear()
    metrics.registry.clear()
    metrics.flight.clear()
    metrics.flight.attach_jsonl(None)  # a leaked sink would cross test files
    from cake_tpu.obs.timeline import timeline

    timeline.clear()
    timeline.attach_jsonl(None)
    from cake_tpu.obs.cluster import cluster

    cluster.clear()  # federated reports/offsets are process-global too
    # jitwatch state (trace counts, seen signatures, ARMED flag) is process-
    # global too; a leaked armed watchdog would flag every later compile.
    # Only touched when some earlier import created it — obs.timeline above
    # is stdlib-light, but jitwatch pulls jax at tracked_jit time.
    jw = sys.modules.get("cake_tpu.obs.jitwatch")
    if jw is not None:
        jw.watch.clear()
    yield


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """Drop compiled executables after each test module.

    One pytest process compiles thousands of XLA programs across the suite;
    accumulated compiler/executable state has produced a segfault inside
    XLA-CPU's backend_compile deep into the run (observed twice at ~85%,
    in whichever module compiles next — not that module's fault, and never
    reproducible standalone). Per-module cache clearing bounds the live
    state; cross-module recompiles cost seconds and nothing else (jit
    caches refill transparently; lru-cached wrapper FUNCTIONS stay valid).
    """
    yield
    jax.clear_caches()
