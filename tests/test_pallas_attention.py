"""Pallas attention kernels vs the XLA einsum path (interpret mode on CPU).

The XLA path (ops/attention.py) is the numerics oracle — it mirrors the
reference's f32-upcast softmax (attention.rs:96-118). The Pallas kernels must
match it to float tolerance for every GQA ratio, ragged length, and batch shape
the model can produce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.attention import gqa_attention, gqa_attention_hm
from cake_tpu.ops.pallas.chunk_prefill import chunk_prefill_attention
from cake_tpu.ops.pallas.decode_attention import decode_attention
from cake_tpu.ops.pallas.flash_attention import flash_attention


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "b,s,n_q,n_kv,d",
    [
        (1, 128, 4, 2, 64),
        (2, 200, 8, 8, 32),  # ragged length, MHA
        (1, 300, 4, 1, 64),  # MQA, two q blocks + ragged
        (2, 96, 16, 4, 128),
    ],
)
def test_flash_matches_xla_prefill(b, s, n_q, n_kv, d):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand(kq, b, s, n_q, d)
    k = _rand(kk, b, s, n_kv, d)
    v = _rand(kv, b, s, n_kv, d)
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))

    ref = gqa_attention(q, k, v, positions, positions)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,max_seq,n_q,n_kv,d,lens",
    [
        (1, 256, 4, 2, 64, [100]),
        (2, 256, 8, 8, 32, [1, 250]),  # fresh sequence and nearly-full cache
        (1, 200, 4, 1, 64, [130]),  # ragged cache tail block
        (3, 128, 16, 4, 128, [128, 64, 7]),
    ],
)
def test_decode_matches_xla(b, max_seq, n_q, n_kv, d, lens):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand(kq, b, 1, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    lengths = jnp.asarray(lens, jnp.int32)

    # Oracle: head-major XLA attention with per-row position masks.
    q_positions = (lengths - 1)[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    ref = gqa_attention_hm(q, k_cache, v_cache, q_positions, kv_positions)
    out = decode_attention(q, k_cache, v_cache, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,max_seq,n_q,n_kv,d,lens,starts",
    [
        (2, 256, 4, 2, 64, [100, 256], [0, 37]),  # one unpadded, one padded row
        (3, 256, 8, 8, 32, [250, 250, 250], [249, 128, 5]),  # start in any block
        (1, 200, 4, 1, 64, [130], [60]),  # ragged tail + ragged start
    ],
)
def test_decode_with_starts_matches_xla(b, max_seq, n_q, n_kv, d, lens, starts):
    """Pad-aware decode (left-padded batches): row r attends [starts[r], lens[r])."""
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = _rand(kq, b, 1, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    lengths = jnp.asarray(lens, jnp.int32)
    starts_j = jnp.asarray(starts, jnp.int32)

    # Oracle: positions < start get the far-future sentinel (batch.py's
    # PAD_SENTINEL convention) so the causal mask hides them.
    q_positions = (lengths - 1)[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    kv_positions = jnp.where(
        kv_positions < starts_j[:, None], jnp.int32(2**30), kv_positions
    )
    ref = gqa_attention_hm(q, k_cache, v_cache, q_positions, kv_positions)
    out = decode_attention(q, k_cache, v_cache, lengths, starts_j, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_model_forward_pallas_vs_xla():
    """Full-model parity: prefill + a few decode steps under both impls."""
    cfg_x = LlamaConfig.tiny(attention_impl="xla")
    cfg_p = LlamaConfig.tiny(attention_impl="pallas")
    params = M.init_params(cfg_x, jax.random.PRNGKey(0), jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg_x.vocab_size, (1, 9)), jnp.int32
    )

    def run(cfg):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
            jnp.float32,
        )
        logits, kv = M.forward(params, tokens, kv, jnp.int32(0), jnp.int32(9), cfg)
        outs = [logits]
        pos = 9
        for _ in range(3):
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)[:, None]
            logits, kv = M.forward(
                params, nxt, kv, jnp.int32(pos), jnp.int32(1), cfg
            )
            outs.append(logits)
            pos += 1
        return outs

    for got, want in zip(run(cfg_p), run(cfg_x)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
        )


@pytest.mark.parametrize(
    "win,softcap,scale,flag",
    [
        (64, None, None, None),          # plain sliding window (Mistral)
        (64, 30.0, 0.11, True),          # Gemma-2 local layer: all three knobs
        (64, 30.0, 0.11, False),         # Gemma-2 global layer: gate off
        (None, 50.0, 0.2, None),         # softcap + scale, no window
    ],
)
def test_flash_attention_variants_match_xla(win, softcap, scale, flag):
    """Window / softcap / scale-override prefill parity (the per-family knobs)."""
    b, s, n_q, n_kv, d = 2, 300, 8, 2, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(kq, b, s, n_q, d)
    k = _rand(kk, b, s, n_kv, d)
    v = _rand(kv, b, s, n_kv, d)
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    wf = None if flag is None else jnp.bool_(flag)

    ref = gqa_attention(
        q, k, v, positions, positions,
        window=win, window_flag=wf, scale=scale, softcap=softcap,
    )
    out = flash_attention(
        q, k, v, wf, window=win, scale=scale, softcap=softcap, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "win,softcap,scale,flag",
    [
        (64, None, None, None),
        (64, 30.0, 0.13, True),
        (64, None, None, False),
        (None, 25.0, None, None),
    ],
)
def test_decode_attention_variants_match_xla(win, softcap, scale, flag):
    """Windowed decode = raised pruning start; softcap/scale in-kernel."""
    b, max_seq, n_q, n_kv, d = 2, 256, 8, 2, 64
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(4), 3)
    q = _rand(kq, b, 1, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    lengths = jnp.asarray([100, 256], jnp.int32)
    wf = None if flag is None else jnp.bool_(flag)

    q_positions = (lengths - 1)[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    ref = gqa_attention_hm(
        q, k_cache, v_cache, q_positions, kv_positions,
        window=win, window_flag=wf, scale=scale, softcap=softcap,
    )
    out = decode_attention(
        q, k_cache, v_cache, lengths, None, wf,
        window=win, scale=scale, softcap=softcap, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "win,softcap,scale,flag",
    [
        (None, None, None, None),        # dense cached prefill (the serving path)
        (32, None, None, None),          # windowed continuation
        (32, 20.0, 0.15, True),          # Gemma-2 local layer
        (32, None, None, False),         # Gemma-2 global layer
    ],
)
def test_chunk_prefill_matches_xla(win, softcap, scale, flag):
    """Chunk-of-queries vs live cache prefix, per-row offsets (batch layout)."""
    b, max_seq, n_q, n_kv, d, chunk = 2, 256, 8, 2, 64, 48
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
    q = _rand(kq, b, chunk, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    q_starts = jnp.asarray([60, 10], jnp.int32)
    lengths = q_starts + chunk
    wf = None if flag is None else jnp.bool_(flag)

    q_pos = q_starts[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    kv_pos = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    # Dead-tail slots masked with the far-future sentinel, like the oracle in
    # test_decode_with_starts_matches_xla.
    kv_pos = jnp.where(kv_pos >= lengths[:, None], jnp.int32(2**30), kv_pos)
    ref = gqa_attention_hm(
        q, k_cache, v_cache, q_pos, kv_pos,
        window=win, window_flag=wf, scale=scale, softcap=softcap,
    )
    out = chunk_prefill_attention(
        q, k_cache, v_cache, q_starts, lengths, wf,
        window=win, scale=scale, softcap=softcap, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_chunk_prefill_small_chunk_and_ragged_blocks():
    """Chunk smaller than a q block and a cache that needs block_k shrinking."""
    b, max_seq, n_q, n_kv, d, chunk = 1, 200, 4, 1, 64, 10
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(6), 3)
    q = _rand(kq, b, chunk, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    q_starts = jnp.asarray([123], jnp.int32)
    lengths = q_starts + chunk

    q_pos = q_starts[:, None] + jnp.arange(chunk, dtype=jnp.int32)[None, :]
    kv_pos = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    kv_pos = jnp.where(kv_pos >= lengths[:, None], jnp.int32(2**30), kv_pos)
    ref = gqa_attention_hm(q, k_cache, v_cache, q_pos, kv_pos)
    out = chunk_prefill_attention(
        q, k_cache, v_cache, q_starts, lengths, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_model_forward_pallas_vs_xla_gemma2_knobs():
    """Full-model parity with every attention knob live: sliding window with
    the alternating per-layer gate, softcap, scale override — chunked prefill
    continuation plus decode steps under both impls."""
    base = dict(
        model_type="gemma2",
        sliding_window=16,
        alt_sliding_window=True,
        attn_logit_softcap=30.0,
        query_pre_attn_scalar=144,
        post_block_norms=True,
        final_logit_softcap=20.0,
    )
    cfg_x = LlamaConfig.tiny(attention_impl="xla", **base)
    cfg_p = LlamaConfig.tiny(attention_impl="pallas", **base)
    params = M.init_params(cfg_x, jax.random.PRNGKey(7), jnp.float32)
    rng = np.random.default_rng(7)
    first = jnp.asarray(rng.integers(0, cfg_x.vocab_size, (1, 8)), jnp.int32)
    cont = jnp.asarray(rng.integers(0, cfg_x.vocab_size, (1, 6)), jnp.int32)

    def run(cfg):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
            jnp.float32,
        )
        outs = []
        logits, kv = M.forward(params, first, kv, jnp.int32(0), jnp.int32(8), cfg)
        outs.append(logits)
        # chunked-prefill continuation at pos 8 (the serving path)
        logits, kv = M.forward(
            params, cont, kv, jnp.int32(8), jnp.int32(6), cfg, cached_prefill=True
        )
        outs.append(logits)
        pos = 14
        for _ in range(3):
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)[:, None]
            logits, kv = M.forward(
                params, nxt, kv, jnp.int32(pos), jnp.int32(1), cfg
            )
            outs.append(logits)
            pos += 1
        return outs

    for got, want in zip(run(cfg_p), run(cfg_x)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
        )


def test_chunk_prefill_fully_padded_q_blocks_write_finite_zeros():
    """q blocks covering ONLY left-pad slots have no executed kv block; the
    kernel must still initialize their output (exact zeros) — stale VMEM
    there would poison later layers through 0 * NaN in the p@v dot."""
    b, max_seq, n_q, n_kv, d, chunk = 1, 64, 4, 2, 64, 48
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(8), 3)
    q = _rand(kq, b, chunk, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    pads = jnp.asarray([32], jnp.int32)  # two full 16-row q blocks of pure pad
    q_starts = jnp.zeros((b,), jnp.int32)
    lengths = jnp.asarray([chunk], jnp.int32)

    out = chunk_prefill_attention(
        q, k_cache, v_cache, q_starts, lengths, None, pads,
        block_q=16, block_k=16, interpret=True,
    )
    out = np.asarray(out)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[:, :32], np.zeros_like(out[:, :32]))
    # Valid rows still match the XLA oracle with sentinel-masked pads.
    q_pos = jnp.broadcast_to(jnp.arange(chunk, dtype=jnp.int32)[None], (b, chunk))
    kv_pos = jnp.broadcast_to(jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq))
    kv_pos = jnp.where(
        (kv_pos < pads[:, None]) | (kv_pos >= lengths[:, None]),
        jnp.int32(2**30), kv_pos,
    )
    ref = np.asarray(gqa_attention_hm(q, k_cache, v_cache, q_pos, kv_pos))
    np.testing.assert_allclose(out[:, 32:], ref[:, 32:], atol=2e-5, rtol=2e-5)
