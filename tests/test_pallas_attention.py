"""Pallas attention kernels vs the XLA einsum path (interpret mode on CPU).

The XLA path (ops/attention.py) is the numerics oracle — it mirrors the
reference's f32-upcast softmax (attention.rs:96-118). The Pallas kernels must
match it to float tolerance for every GQA ratio, ragged length, and batch shape
the model can produce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.ops.attention import gqa_attention, gqa_attention_hm
from cake_tpu.ops.pallas.decode_attention import decode_attention
from cake_tpu.ops.pallas.flash_attention import flash_attention


def _rand(key, *shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize(
    "b,s,n_q,n_kv,d",
    [
        (1, 128, 4, 2, 64),
        (2, 200, 8, 8, 32),  # ragged length, MHA
        (1, 300, 4, 1, 64),  # MQA, two q blocks + ragged
        (2, 96, 16, 4, 128),
    ],
)
def test_flash_matches_xla_prefill(b, s, n_q, n_kv, d):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = _rand(kq, b, s, n_q, d)
    k = _rand(kk, b, s, n_kv, d)
    v = _rand(kv, b, s, n_kv, d)
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))

    ref = gqa_attention(q, k, v, positions, positions)
    out = flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,max_seq,n_q,n_kv,d,lens",
    [
        (1, 256, 4, 2, 64, [100]),
        (2, 256, 8, 8, 32, [1, 250]),  # fresh sequence and nearly-full cache
        (1, 200, 4, 1, 64, [130]),  # ragged cache tail block
        (3, 128, 16, 4, 128, [128, 64, 7]),
    ],
)
def test_decode_matches_xla(b, max_seq, n_q, n_kv, d, lens):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand(kq, b, 1, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    lengths = jnp.asarray(lens, jnp.int32)

    # Oracle: head-major XLA attention with per-row position masks.
    q_positions = (lengths - 1)[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    ref = gqa_attention_hm(q, k_cache, v_cache, q_positions, kv_positions)
    out = decode_attention(q, k_cache, v_cache, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "b,max_seq,n_q,n_kv,d,lens,starts",
    [
        (2, 256, 4, 2, 64, [100, 256], [0, 37]),  # one unpadded, one padded row
        (3, 256, 8, 8, 32, [250, 250, 250], [249, 128, 5]),  # start in any block
        (1, 200, 4, 1, 64, [130], [60]),  # ragged tail + ragged start
    ],
)
def test_decode_with_starts_matches_xla(b, max_seq, n_q, n_kv, d, lens, starts):
    """Pad-aware decode (left-padded batches): row r attends [starts[r], lens[r])."""
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = _rand(kq, b, 1, n_q, d)
    k_cache = _rand(kk, b, n_kv, max_seq, d)
    v_cache = _rand(kv, b, n_kv, max_seq, d)
    lengths = jnp.asarray(lens, jnp.int32)
    starts_j = jnp.asarray(starts, jnp.int32)

    # Oracle: positions < start get the far-future sentinel (batch.py's
    # PAD_SENTINEL convention) so the causal mask hides them.
    q_positions = (lengths - 1)[:, None]
    kv_positions = jnp.broadcast_to(
        jnp.arange(max_seq, dtype=jnp.int32)[None], (b, max_seq)
    )
    kv_positions = jnp.where(
        kv_positions < starts_j[:, None], jnp.int32(2**30), kv_positions
    )
    ref = gqa_attention_hm(q, k_cache, v_cache, q_positions, kv_positions)
    out = decode_attention(q, k_cache, v_cache, lengths, starts_j, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_model_forward_pallas_vs_xla():
    """Full-model parity: prefill + a few decode steps under both impls."""
    cfg_x = LlamaConfig.tiny(attention_impl="xla")
    cfg_p = LlamaConfig.tiny(attention_impl="pallas")
    params = M.init_params(cfg_x, jax.random.PRNGKey(0), jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg_x.vocab_size, (1, 9)), jnp.int32
    )

    def run(cfg):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
            jnp.float32,
        )
        logits, kv = M.forward(params, tokens, kv, jnp.int32(0), jnp.int32(9), cfg)
        outs = [logits]
        pos = 9
        for _ in range(3):
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)[:, None]
            logits, kv = M.forward(
                params, nxt, kv, jnp.int32(pos), jnp.int32(1), cfg
            )
            outs.append(logits)
            pos += 1
        return outs

    for got, want in zip(run(cfg_p), run(cfg_x)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
        )
