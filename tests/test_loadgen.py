"""Loadgen unit tests: arrival processes, workload shapes, the open-loop
runner/report, and capture->replay planning (cake_tpu/loadgen/*).

Everything here is stdlib-only and fast — no jax, no sockets: the
targets are fakes with the ``chat()`` interface. The live end-to-end
path (real --api master, real engine) is the ``make loadgen-smoke``
gate; the in-proc path is the bench's ``frontdoor`` section.
"""

import random

import pytest

from cake_tpu.loadgen import replay as replay_mod
from cake_tpu.loadgen.arrivals import bursty, make_arrivals, poisson, take_until
from cake_tpu.loadgen.client import Result
from cake_tpu.loadgen.runner import Shot, build_report, run_shots
from cake_tpu.loadgen.workload import (
    PROMPT_UNIT,
    TenantSpec,
    make_dist,
    parse_tenants,
    pick_tenant,
    prompt_units,
    synth_prompt,
)


class TestArrivals:
    @pytest.mark.parametrize(
        "spec", ["poisson:20", "bursty:30,2,0.5,0.25", "ramp:5,40,2.0"]
    )
    def test_deterministic_and_monotonic(self, spec):
        a = take_until(make_arrivals(spec, random.Random(7)), 3.0)
        b = take_until(make_arrivals(spec, random.Random(7)), 3.0)
        assert a == b and a, f"{spec} must be seeded-reproducible"
        assert all(y > x for x, y in zip(a, a[1:])), "offsets must increase"
        assert all(0.0 <= t < 3.0 for t in a)

    def test_poisson_rate_is_roughly_right(self):
        n = len(take_until(poisson(50.0, random.Random(3)), 10.0))
        assert 350 < n < 650  # ~500 expected; wide seeded bounds

    def test_bursty_silent_off_phase_emits_nothing(self):
        # off_rate=0: every offset falls inside an ON phase. With mean
        # phases of 0.2s ON / 10s OFF over 3s, a leaked OFF arrival
        # would be near-certain to show as a huge count.
        train = take_until(bursty(100.0, 0.0, 0.2, 10.0, random.Random(5)), 3.0)
        assert 0 < len(train) < 100

    @pytest.mark.parametrize(
        "spec",
        ["poisson:", "poisson:1,2", "bursty:1,2,3", "drizzle:5",
         "poisson:abc"],
    )
    def test_bad_spec_shapes_raise_at_parse(self, spec):
        with pytest.raises(ValueError):
            make_arrivals(spec, random.Random(0))

    @pytest.mark.parametrize(
        "spec", ["poisson:0", "bursty:0,1,1,1", "ramp:0,0,1", "ramp:1,2,0"]
    )
    def test_bad_spec_values_raise_on_first_draw(self, spec):
        # The processes are lazy generators: value validation fires when
        # the train is first consumed, not at parse time.
        with pytest.raises(ValueError):
            take_until(make_arrivals(spec, random.Random(0)), 1.0)


class TestWorkload:
    def test_synth_prompt_roundtrip(self):
        for units in (1, 2, 7, 40):
            p = synth_prompt(units)
            assert p == PROMPT_UNIT * units
            assert prompt_units(p) == units
        assert synth_prompt(0) == PROMPT_UNIT  # floor at one unit

    def test_dists(self):
        rng = random.Random(11)
        assert make_dist("fixed:12", rng)() == 12
        uni = make_dist("uniform:3,9", rng)
        assert all(3 <= uni() <= 9 for _ in range(200))
        logn = make_dist("lognormal:2.0,0.8", rng)
        assert all(logn() >= 1 for _ in range(200))

    @pytest.mark.parametrize(
        "spec", ["fixed:", "uniform:9,3", "uniform:0,5", "zipf:2", "fixed:a"]
    )
    def test_bad_dists_raise(self, spec):
        with pytest.raises(ValueError):
            make_dist(spec, random.Random(0))

    def test_parse_tenants(self):
        assert parse_tenants("interactive:3@2,batch:1") == [
            TenantSpec("interactive", 3.0, 2),
            TenantSpec("batch", 1.0, None),
        ]

    @pytest.mark.parametrize(
        "spec", ["", "noweight", "t:0", "t:-1", "t:1@7", "t:x"]
    )
    def test_bad_tenants_raise(self, spec):
        with pytest.raises(ValueError):
            parse_tenants(spec)

    def test_pick_tenant_respects_weights(self):
        specs = parse_tenants("heavy:9,light:1")
        rng = random.Random(2)
        picks = [pick_tenant(specs, rng).name for _ in range(500)]
        assert 380 < picks.count("heavy") < 490


class _FakeTarget:
    """chat() that answers instantly from a scripted status map and an
    affine tokenizer (tokens = overhead + per_unit * units)."""

    def __init__(self, overhead=7, per_unit=3, status_for=None):
        self.overhead = overhead
        self.per_unit = per_unit
        self.status_for = status_for or {}
        self.calls: list = []

    def chat(self, prompt, max_tokens, tenant=None, priority=None,
             deadline_s=None, prompt_units=0):
        units = prompt_units or len(prompt) // len(PROMPT_UNIT)
        self.calls.append((units, max_tokens, tenant, priority))
        status = self.status_for.get(tenant, 200)
        res = Result(
            tenant=tenant or "default", status=status,
            prompt_units=units, max_tokens=max_tokens,
            deadline_s=deadline_s,
        )
        if status == 200:
            res.finish_reason = "length"
            res.prompt_tokens = self.overhead + self.per_unit * units
            res.completion_tokens = max_tokens
            res.ttft_s = 0.010 * units
            res.tpot_s = 0.002
        elif status == 429:
            res.finish_reason = "quota"
        elif status == 503:
            res.finish_reason = "shed"
        return res


class TestReplay:
    def test_calibrate_recovers_affine_map(self):
        overhead, per_unit = replay_mod.calibrate(_FakeTarget(7, 3))
        assert (overhead, per_unit) == (7.0, 3.0)
        for ptok in (10, 13, 40, 127):
            units = replay_mod.units_for_tokens(ptok, overhead, per_unit)
            assert 7 + 3 * units == ptok

    def test_calibrate_raises_on_failure_and_degeneracy(self):
        with pytest.raises(RuntimeError, match="probe"):
            replay_mod.calibrate(_FakeTarget(status_for={None: 503}))
        with pytest.raises(RuntimeError, match="degenerate"):
            replay_mod.calibrate(_FakeTarget(overhead=9, per_unit=0))

    def _trace(self):
        return [
            {"request_id": "a", "t_wall": 100.0, "tenant": "default",
             "prompt_tokens": 13, "max_tokens": 6, "finish_reason": "stop"},
            {"request_id": "b", "t_wall": 101.0, "tenant": "bob",
             "priority": 2, "prompt_tokens": 22, "max_tokens": 4,
             "deadline_s": 30.0, "finish_reason": "quota"},
            {"request_id": "c", "t_wall": 102.5, "tenant": "bob",
             "prompt_tokens": 16, "completion_tokens": 5,
             "finish_reason": "stop"},
        ]

    def test_plan_from_trace_preserves_everything(self):
        shots = replay_mod.plan_from_trace(
            self._trace(), speed=2.0, calibration=(7.0, 3.0)
        )
        # Gaps scaled by speed; t0 anchors at zero.
        assert [s.t_offset for s in shots] == [0.0, 0.5, 1.25]
        # prompt_tokens invert through the calibration: 13->2, 22->5, 16->3.
        assert [s.prompt_units for s in shots] == [2, 5, 3]
        assert [prompt_units(s.prompt) for s in shots] == [2, 5, 3]
        # "default" maps to no-tenant-field; identities otherwise kept —
        # the refused record ("b", a 429) is replayed too: a refusal is
        # part of the offered load.
        assert [s.tenant for s in shots] == [None, "bob", "bob"]
        assert [s.priority for s in shots] == [None, 2, None]
        assert [s.deadline_s for s in shots] == [None, 30.0, None]
        # max_tokens falls back to completion_tokens when unrecorded.
        assert [s.max_tokens for s in shots] == [6, 4, 5]

    def test_plan_without_calibration_uses_tokens_as_units(self):
        shots = replay_mod.plan_from_trace(self._trace())
        assert [s.prompt_units for s in shots] == [13, 22, 16]
        assert [s.t_offset for s in shots] == [0.0, 1.0, 2.5]

    def test_plan_validates_speed_and_empty(self):
        assert replay_mod.plan_from_trace([]) == []
        with pytest.raises(ValueError):
            replay_mod.plan_from_trace(self._trace(), speed=0.0)

    def test_trace_expectation(self):
        assert replay_mod.trace_expectation(self._trace()) == {
            "count": 3,
            "tenants": {"default": 1, "bob": 2},
            "prompt_tokens_total": 51,
        }


class TestRunnerAndReport:
    def test_run_shots_open_loop_results(self):
        target = _FakeTarget(status_for={"capped": 429})
        shots = [
            Shot(0.02, synth_prompt(2), 2, 4, tenant="capped"),
            Shot(0.0, synth_prompt(3), 3, 5, tenant="ok", deadline_s=9.0),
        ]
        results, duration, capped = run_shots(target, shots, max_inflight=4)
        assert capped == 0 and duration > 0
        # Results come back in schedule order (sorted by offset).
        assert [r.tenant for r in results] == ["ok", "capped"]
        assert [r.t_offset for r in results] == [0.0, 0.02]
        assert results[0].status == 200 and results[1].status == 429

    def test_run_shots_survives_a_raising_target(self):
        class _Boom:
            def chat(self, *a, **k):
                raise ConnectionError("nope")

        (res,), _, _ = run_shots(
            _Boom(), [Shot(0.0, synth_prompt(1), 1, 2)], max_inflight=2
        )
        assert res.status == 0 and res.finish_reason == "error"
        assert "ConnectionError" in res.error

    def test_build_report_shape(self):
        target = _FakeTarget(status_for={"abuser": 429, "shed": 503})
        shots = (
            [Shot(0.0, synth_prompt(2), 2, 4, tenant="good",
                  deadline_s=9.0)] * 2
            + [Shot(0.0, synth_prompt(2), 2, 4, tenant="abuser")]
            + [Shot(0.0, synth_prompt(2), 2, 4, tenant="shed")]
        )
        results, duration, capped = run_shots(target, shots, max_inflight=8)
        report = build_report(results, duration, inflight_capped=capped)
        assert report["n_requests"] == 4 and report["n_ok"] == 2
        assert report["n_quota_429"] == 1 and report["n_shed_503"] == 1
        assert report["refusal_429_frac"] == 0.25
        assert report["refusal_503_frac"] == 0.25
        assert report["n_errors"] == 0
        assert report["deadline_met_frac"] == 1.0
        assert report["ttft_p99_ms"] == 20.0    # 0.010 * 2 units
        assert report["tpot_mean_ms"] == 2.0
        assert report["prompt_tokens_total"] == 2 * (7 + 3 * 2)
        assert report["completion_tokens_total"] == 8
        assert report["inflight_capped"] == 0
        assert report["tenants"]["good"] == {
            "n": 2, "ok": 2, "quota_429": 0, "shed_503": 0,
            "prompt_tokens": 26, "completion_tokens": 8,
        }
        assert report["tenants"]["abuser"]["quota_429"] == 1

    def test_build_report_empty_run(self):
        report = build_report([], 0.0)
        assert report["n_requests"] == 0
        assert report["refusal_429_frac"] == 0.0
        assert report["goodput_tok_s"] == 0.0
        assert report["deadline_met_frac"] is None
        assert report["tpot_mean_ms"] is None
