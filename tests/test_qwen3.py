"""Qwen3 / Qwen3-MoE family: pinned against transformers.

Family deltas over Qwen2 (HF modeling_qwen3.Qwen3Attention): per-head
RMSNorm on q and k after projection, before RoPE ("only on the head dim");
no QKV bias; decoupled head_dim; ChatML template WITHOUT a default system
prompt. Qwen3-MoE routes like Qwen2-MoE but renormalizes top-k
(norm_topk_prob=True) and has no shared expert.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.io.safetensors_io import load_params
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message, encode_dialog
from cake_tpu.models.llama.config import LlamaConfig


def make_qwen3_checkpoint(tmp_path, seed=0, head_dim=24):
    hf_cfg = transformers.models.qwen3.Qwen3Config(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=head_dim,  # decoupled (64/4 != 24), the shipped-model shape
        rope_theta=1000000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        attention_bias=False,
    )
    torch.manual_seed(seed)
    model = (
        transformers.models.qwen3.Qwen3ForCausalLM(hf_cfg)
        .eval()
        .to(torch.float32)
    )
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def make_qwen3_moe_checkpoint(tmp_path, seed=0):
    hf_cfg = transformers.models.qwen3_moe.Qwen3MoeConfig(
        hidden_size=64,
        intermediate_size=128,
        moe_intermediate_size=48,
        vocab_size=512,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        num_experts=4,
        num_experts_per_tok=2,
        norm_topk_prob=True,
        decoder_sparse_step=1,
        rope_theta=1000000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        attention_bias=False,
    )
    torch.manual_seed(seed)
    model = (
        transformers.models.qwen3_moe.Qwen3MoeForCausalLM(hf_cfg)
        .eval()
        .to(torch.float32)
    )
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


def ours_greedy(model_dir, prompt_ids, n_steps):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    tokens = jnp.asarray([prompt_ids], jnp.int32)
    logits, kv = fwd(
        params, tokens, kv, jnp.int32(0), jnp.int32(len(prompt_ids)), cfg
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


def test_qwen3_config_parses(tmp_path):
    make_qwen3_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "qwen3"
    assert cfg.qk_norm
    assert not cfg.attention_bias
    assert cfg.head_dim == 24  # decoupled from hidden/heads
    assert cfg.dialog_template == "qwen3"


def test_qwen3_qk_norm_tensors_loaded(tmp_path):
    make_qwen3_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    assert params["layers"]["q_norm"].shape == (3, 24)
    assert params["layers"]["k_norm"].shape == (3, 24)


def test_qwen3_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_qwen3_checkpoint(tmp_path, seed=11)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    want = hf_greedy(hf_model, prompt, 16)
    got = ours_greedy(tmp_path, prompt, 16)
    assert got == want


def test_qwen3_prefill_logits_match_transformers(tmp_path):
    hf_model = make_qwen3_checkpoint(tmp_path, seed=12)
    prompt = [256, 11, 205, 499, 3, 3, 64]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=2e-4, rtol=2e-4
    )


def test_qwen3_moe_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_qwen3_moe_checkpoint(tmp_path, seed=13)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "qwen3_moe"
    assert cfg.num_local_experts == 4
    assert cfg.norm_topk_prob
    assert cfg.shared_expert_intermediate_size is None
    prompt = [256, 5, 77, 390, 12, 12]
    want = hf_greedy(hf_model, prompt, 12)
    got = ours_greedy(tmp_path, prompt, 12)
    assert got == want


def test_qwen3_template_no_default_system():
    """Qwen3's ChatML omits the Qwen2 default system prompt: a systemless
    dialog starts at the first user turn (tokenizer_config parity)."""
    text = encode_dialog([Message.user("hi")], "qwen3")
    assert text == "<|im_start|>user\nhi<|im_end|>\n<|im_start|>assistant\n"
    with_sys = encode_dialog(
        [Message.system("be brief"), Message.user("hi")], "qwen3"
    )
    assert with_sys.startswith("<|im_start|>system\nbe brief<|im_end|>\n")


def test_qwen3_tp_matches_local(tmp_path):
    """qk-norm rides the shared block core: the tensor-parallel runner must
    reproduce the local stream exactly (per-head norms replicate)."""
    from cake_tpu.models.llama.generator import (
        LlamaGenerator,
        LocalForwardStep,
        SamplingConfig,
    )
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.tensor import TensorParallelRunner

    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    cfg = LlamaConfig.tiny(num_hidden_layers=2, model_type="qwen3", qk_norm=True)
    params = M.init_params(cfg, jax.random.PRNGKey(90), jnp.float32)
    assert "q_norm" in params["layers"]

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
        gen.add_message(Message.user("qwen3 tp"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32))
    got = run(
        TensorParallelRunner(cfg, params, tp=2, max_seq_len=128, cache_dtype=jnp.float32)
    )
    assert got == want


def test_qwen3_moe_norm_topk_default_matches_hf():
    """A qwen3_moe config.json OMITTING norm_topk_prob must default False —
    the HF Qwen3MoeConfig class default (shipped checkpoints set True
    explicitly; the field, not the brand, decides)."""
    cfg = LlamaConfig.from_hf_dict(
        {"model_type": "qwen3_moe", "num_attention_heads": 4,
         "num_key_value_heads": 2, "hidden_size": 64}
    )
    assert cfg.norm_topk_prob is False
    cfg2 = LlamaConfig.from_hf_dict(
        {"model_type": "qwen3_moe", "norm_topk_prob": True,
         "num_attention_heads": 4, "num_key_value_heads": 2, "hidden_size": 64}
    )
    assert cfg2.norm_topk_prob is True


def test_qwen3_moe_quantizer_writes_family_names(tmp_path):
    """The quantizer's output uses the Qwen-MoE tensor-name layout for
    qwen3_moe (not Mixtral's): hf_tensor_dict stays THE inverse of the
    loader's mapping for every declared family."""
    from cake_tpu.io.quantizer import quantize_checkpoint
    from cake_tpu.io.safetensors_io import open_checkpoint, save_tiny_checkpoint

    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="qwen3_moe", qk_norm=True,
        num_local_experts=4, num_experts_per_tok=2,
        shared_expert_intermediate_size=None,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(91), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    reader = open_checkpoint(src)
    assert "model.layers.0.mlp.experts.0.gate_proj.weight" in reader
    assert "model.layers.0.mlp.gate.weight" in reader
    dst = quantize_checkpoint(src, tmp_path / "q", "int4", dtype=jnp.float32)
    qreader = open_checkpoint(dst)
    assert "model.layers.0.mlp.experts.0.gate_proj.weight.q8" in qreader
    assert "model.layers.0.self_attn.q_norm.weight" in qreader
    loaded = load_params(dst, cfg, jnp.float32)
    from cake_tpu.ops.quant import QuantWeight, quantize_params

    assert isinstance(loaded["layers"]["w_gate"], QuantWeight)
