"""Replica router unit tests (runtime/router.py).

Contracts: round-robin among healthy members per refresh; ejection on
reported failure with ``failover`` re-picking the group NOW (None when no
healthy member remains); standby rejoin after the cooldown (gated on the
heartbeat monitor when attached) with a ``rejoin`` event; ``prefer`` pins
the next pick for deterministic chaos runs.
"""

from __future__ import annotations

import time

import pytest

from cake_tpu.runtime.router import ReplicaRouter
from cake_tpu.utils import metrics


def two_member_router(**kw):
    return ReplicaRouter({"w0": ["w0", "w0b"]}, **kw)


def test_round_robin_among_members():
    r = two_member_router()
    picks = [r.refresh()["w0"] for _ in range(4)]
    assert picks == ["w0", "w0b", "w0", "w0b"]
    # The route is stable between refreshes.
    assert r.route("w0") == "w0b"


def test_route_unknown_primary_is_identity():
    r = two_member_router()
    assert r.route("not-a-primary") == "not-a-primary"


def test_group_must_contain_primary():
    with pytest.raises(ValueError):
        ReplicaRouter({"w0": ["w1", "w2"]})


def test_failover_ejects_and_repicks():
    r = two_member_router()
    assert r.refresh()["w0"] == "w0"
    assert r.failover("w0") == "w0b"
    assert r.route("w0") == "w0b"
    assert r.snapshot()["ejected"] == ["w0"]
    assert metrics.registry.counter(
        "cake_failover_total"
    ).value(node="w0") == 1
    assert any(
        e["event"] == "failover" and e["node"] == "w0" and e["to"] == "w0b"
        for e in metrics.flight.snapshot()
    )
    # Ejected members sit out subsequent refreshes too.
    assert r.refresh()["w0"] == "w0b"


def test_failover_with_no_healthy_member_returns_none():
    r = two_member_router()
    assert r.failover("w0") == "w0b"
    assert r.failover("w0b") is None  # both down: caller degrades to error
    solo = ReplicaRouter({"w0": ["w0"]})
    assert solo.failover("w0") is None  # no replica at all


def test_cooldown_rejoin_emits_event():
    r = two_member_router(cooldown_s=0.01)
    r.prefer("w0")
    assert r.failover("w0") == "w0b"
    time.sleep(0.02)
    r.prefer("w0")
    assert r.refresh()["w0"] == "w0"  # probation served: standby rejoins
    assert r.snapshot()["ejected"] == []
    assert any(
        e["event"] == "rejoin" and e["node"] == "w0"
        for e in metrics.flight.snapshot()
    )
    assert metrics.registry.counter(
        "cake_replica_rejoin_total"
    ).value(node="w0") == 1


def test_monitor_gates_rotation_and_rejoin():
    class FakeMonitor:
        def __init__(self):
            self.down: set[str] = set()

        def healthy(self, node):
            return node not in self.down

    mon = FakeMonitor()
    r = two_member_router(cooldown_s=0.0, monitor=mon)
    mon.down.add("w0")
    # An unhealthy member never failed a hop, but the monitor keeps it out.
    assert [r.refresh()["w0"] for _ in range(3)] == ["w0b"] * 3
    # Ejection + zero cooldown still defers to the monitor...
    r.report_failure("w0b")
    assert r.failover("w0b") is None  # w0 down per monitor, w0b ejected
    # ...until the heartbeat sees the node again.
    mon.down.clear()
    r.prefer("w0")
    assert r.refresh()["w0"] == "w0"


def test_report_success_clears_probation_early():
    r = two_member_router(cooldown_s=60.0)
    r.report_failure("w0")
    r.prefer("w0")
    assert r.refresh()["w0"] == "w0b"  # long cooldown: still out
    r.report_success("w0")
    r.prefer("w0")
    assert r.refresh()["w0"] == "w0"


def test_routed_counter_moves_per_refresh():
    r = two_member_router()
    before = metrics.registry.counter(
        "cake_replica_routed_total"
    ).value(node="w0")
    r.prefer("w0")
    r.refresh()
    assert metrics.registry.counter(
        "cake_replica_routed_total"
    ).value(node="w0") == before + 1
