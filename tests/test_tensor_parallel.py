"""Tensor parallelism vs the single-device oracle (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import LocalForwardStep
from cake_tpu.parallel.tensor import TensorParallelRunner, validate_tp

MAX_SEQ = 64


def _cfg(**kw):
    return LlamaConfig.tiny(**kw)


def _drive(step, tokens):
    """Prefill the prompt then decode 3 greedy tokens; return all logits."""
    n = tokens.shape[1]
    outs = [step(tokens, 0, n)]
    pos = n
    for _ in range(3):
        nxt = np.argmax(outs[-1], -1).astype(np.int32)[:, None]
        outs.append(step(nxt, pos, 1))
        pos += 1
    return np.stack(outs)


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_matches_local(tp):
    cfg = _cfg(num_attention_heads=8, num_key_value_heads=4)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tokens = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 10)).astype(
        np.int32
    )

    local = LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32)
    tp_step = TensorParallelRunner(
        cfg, params, tp=tp, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    ref = _drive(local, tokens)
    got = _drive(tp_step, tokens)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_tp_batch2():
    cfg = _cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    tokens = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)).astype(
        np.int32
    )
    local = LocalForwardStep(
        cfg, params, max_seq_len=MAX_SEQ, batch_size=2, cache_dtype=jnp.float32
    )
    tp_step = TensorParallelRunner(
        cfg, params, tp=2, max_seq_len=MAX_SEQ, batch_size=2,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        _drive(tp_step, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )


def test_tp_validation():
    with pytest.raises(ValueError, match="must divide"):
        validate_tp(_cfg(), 3)  # 2 kv heads not divisible by 3


def test_tp_reset_isolates_state():
    cfg = _cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tokens = np.random.default_rng(2).integers(0, cfg.vocab_size, (1, 6)).astype(
        np.int32
    )
    step = TensorParallelRunner(
        cfg, params, tp=2, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    a = _drive(step, tokens)
    step.reset()
    b = _drive(step, tokens)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_pp_x_tp_matches_local():
    """2-D mesh: 2 pipeline stages x 2-way tensor parallelism on 4 devices."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = _cfg(num_attention_heads=8, num_key_value_heads=4)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tokens = np.random.default_rng(3).integers(0, cfg.vocab_size, (1, 10)).astype(
        np.int32
    )
    local = LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32)
    pp_tp = PipelineRunner(
        cfg, params, [(0, 2), (2, 4)], tp=2, max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        _drive(pp_tp, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )


def test_pp_x_tp_ragged_stages():
    """Ragged boundaries (padded inert layers) still correct under tp."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = _cfg(num_attention_heads=8, num_key_value_heads=4, num_hidden_layers=5)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    tokens = np.random.default_rng(4).integers(0, cfg.vocab_size, (1, 7)).astype(
        np.int32
    )
    local = LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32)
    pp_tp = PipelineRunner(
        cfg, params, [(0, 3), (3, 5)], tp=2, max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        _drive(pp_tp, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )
