"""Per-rule regression tests for cake_tpu/analysis.

Every shipped rule gets at least one TRUE-POSITIVE snippet (the test fails if
the rule is deleted or stops firing) and negative snippets pinning the
false-positive boundaries the real tree depends on (static-arg casts, rebind
donation, guarded mutations, narrowed excepts).

The analysis package is stdlib-only; none of these tests need jax.
"""

from __future__ import annotations

from cake_tpu.analysis import engine, lint_source


def rules_of(findings):
    return [f.rule for f in findings]


def lint_rule(src: str, rule: str, path: str = "snippet.py"):
    """Run ONE rule over a snippet (select= raises if the rule was deleted,
    so deleting a rule fails every test that names it)."""
    return lint_source(src, path=path, select=[rule])


# ------------------------------------------------------------ host-sync-in-jit


class TestHostSyncInJit:
    RULE = "host-sync-in-jit"

    def test_item_in_decorated_jit(self):
        fs = lint_rule(
            """
import jax

@jax.jit
def step(x):
    return x.item()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert ".item()" in fs[0].message

    def test_np_asarray_in_reachable_helper(self):
        # The sync hides one call deep: step -> helper -> np.asarray.
        fs = lint_rule(
            """
import jax
import numpy as np

def helper(y):
    return np.asarray(y)

def step(x):
    return helper(x) + 1

run = jax.jit(step)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_cast_of_traced_param(self):
        fs = lint_rule(
            """
import jax

def step(x, n):
    return x * int(n)

run = jax.jit(step)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_static_arg_cast_is_exempt(self):
        # int(n) on a static arg is concrete Python — the idiom every Pallas
        # kernel wrapper in ops/pallas/ uses.
        fs = lint_rule(
            """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def step(x, n):
    return x * int(n)
""",
            self.RULE,
        )
        assert fs == []

    def test_jitted_bound_method(self):
        fs = lint_rule(
            """
import jax

class Backend:
    def __init__(self):
        self._step = jax.jit(self._impl)

    def _impl(self, x):
        return float(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_sync_outside_jit_is_fine(self):
        fs = lint_rule(
            """
import numpy as np

def host_side(x):
    return np.asarray(x).item()
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------- jit-in-hot-loop


class TestJitInHotLoop:
    RULE = "jit-in-hot-loop"

    def test_jit_constructed_in_loop(self):
        fs = lint_rule(
            """
import jax

def drive(f, steps):
    for s in steps:
        y = jax.jit(f)(s)
    return y
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_partial_jit_in_while(self):
        fs = lint_rule(
            """
import functools
import jax

def drive(f, xs):
    while xs:
        g = functools.partial(jax.jit, static_argnums=(1,))(f)
        xs = g(xs, 1)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_jit_hoisted_before_loop_is_fine(self):
        fs = lint_rule(
            """
import jax

def drive(f, steps):
    g = jax.jit(f)
    for s in steps:
        y = g(s)
    return y
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------- unhashable-static-arg


class TestUnhashableStaticArg:
    RULE = "unhashable-static-arg"

    def test_list_annotated_static_argnum(self):
        fs = lint_rule(
            """
import jax

def step(x, shape: list):
    return x

run = jax.jit(step, static_argnums=(1,))
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_dict_default_static_argname(self):
        fs = lint_rule(
            """
import jax

def step(x, opts={"a": 1}):
    return x

run = jax.jit(step, static_argnames=("opts",))
""",
            self.RULE,
            # The snippet also trips mutable-default-arg; selecting one rule
            # keeps the assertion precise.
        )
        assert rules_of(fs) == [self.RULE]

    def test_static_name_matching_no_param(self):
        fs = lint_rule(
            """
import jax

def step(x):
    return x

run = jax.jit(step, static_argnames=("block_q",))
""",
            self.RULE,
        )
        assert "matches no parameter" in fs[0].message

    def test_hashable_static_is_fine(self):
        fs = lint_rule(
            """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def kernel(x, block_q: int = 128, interpret: bool = False):
    return x
""",
            self.RULE,
        )
        assert fs == []


# ---------------------------------------------------------- donation-after-use


class TestDonationAfterUse:
    RULE = "donation-after-use"

    def test_read_after_donating_call(self):
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv

step = jax.jit(impl, donate_argnums=(1,))

def drive(params, kv):
    out = step(params, kv)
    return out, kv.sum()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "donated" in fs[0].message

    def test_donate_argnames_resolved_through_signature(self):
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv

step = jax.jit(impl, donate_argnames=("kv",))

def drive(params, kv):
    out = step(params, kv)
    log(kv)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_loop_reuse_without_rebind(self):
        # The donated buffer is read at the TOP of the next iteration.
        fs = lint_rule(
            """
import jax

def impl(kv):
    return kv

step = jax.jit(impl, donate_argnums=(0,))

def drive(kv, n):
    for _ in range(n):
        check(kv)
        out = step(kv)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_rebind_is_the_blessed_pattern(self):
        # `logits, kv = step(kv)` — what the whole tree does.
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv, kv

step = jax.jit(impl, donate_argnums=(1,))

def drive(params, kv):
    for _ in range(8):
        logits, kv = step(params, kv)
    return logits
""",
            self.RULE,
        )
        assert fs == []

    def test_read_before_call_is_fine(self):
        fs = lint_rule(
            """
import jax

def impl(kv):
    return kv

step = jax.jit(impl, donate_argnums=(0,))

def drive(kv):
    check(kv)
    return step(kv)
""",
            self.RULE,
        )
        assert fs == []


# ----------------------------------------------------- unlocked-shared-mutation


class TestUnlockedSharedMutation:
    RULE = "unlocked-shared-mutation"

    POSITIVE = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def clear(self):
        self._items = []
"""

    def test_unlocked_mutation_of_guarded_attr(self):
        fs = lint_rule(self.POSITIVE, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "_items" in fs[0].message

    def test_condition_counts_as_lock(self):
        fs = lint_rule(
            """
import threading

class Queue:
    def __init__(self):
        self._cv = threading.Condition()
        self._q = []

    def put(self, x):
        with self._cv:
            self._q.append(x)
            self._cv.notify()

    def drop_all(self):
        self._q.clear()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_all_mutations_guarded_is_fine(self):
        fs = lint_rule(
            """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def clear(self):
        with self._lock:
            self._items = []
""",
            self.RULE,
        )
        assert fs == []

    def test_init_and_unguarded_attrs_exempt(self):
        # _threads is never lock-guarded anywhere -> single-owner state, not
        # flagged (the worker accept-loop pattern).
        fs = lint_rule(
            """
import threading

class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self._conns = set()
        self._threads = []

    def accept(self, c, t):
        with self._lock:
            self._conns.add(c)
        self._threads.append(t)
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------ frame-field-drift


class TestFrameFieldDrift:
    RULE = "frame-field-drift"

    PROTO = """
def forward_frame(x, ranges, pos):
    header = {"ranges": ranges, "pos": pos}
    header["ghost"] = 1
    return Frame(3, header, payload=x)


def error_frame(msg):
    return Frame(6, {"error": msg})
"""

    CLIENT = """
def unpack(frame):
    if "error" in frame.header:
        raise RuntimeError(frame.header["error"])
    h = frame.header
    return h["ranges"], h.get("pos"), h.get("phantom")
"""

    def _run(self, srcs):
        return engine.run_lint(
            list(srcs), select=[self.RULE], reader=lambda p: srcs[str(p)]
        )

    def test_pack_only_and_read_only_fields_flagged(self):
        res = self._run({"proto.py": self.PROTO, "client.py": self.CLIENT})
        flagged = {f.message.split("'")[1] for f in res.findings}
        assert flagged == {"ghost", "phantom"}

    def test_symmetric_contract_is_clean(self):
        res = self._run(
            {
                "proto.py": """
def forward_frame(x, pos):
    return Frame(3, {"pos": pos}, payload=x)
""",
                "client.py": """
def unpack(frame):
    return frame.header["pos"]
""",
            }
        )
        assert res.findings == []

    def test_rule_needs_a_proto_file(self):
        res = self._run({"client.py": self.CLIENT})
        assert res.findings == []

    def test_real_tree_contract_is_symmetric(self):
        repo = __import__("pathlib").Path(__file__).resolve().parent.parent
        res = engine.run_lint([repo / "cake_tpu"], select=[self.RULE])
        assert res.findings == [], [f.render() for f in res.findings]


# ---------------------------------------------------------- mutable-default-arg


class TestMutableDefaultArg:
    RULE = "mutable-default-arg"

    def test_list_default(self):
        fs = lint_rule("def f(x, acc=[]):\n    return acc\n", self.RULE)
        assert rules_of(fs) == [self.RULE]

    def test_dict_call_kwonly_default(self):
        fs = lint_rule(
            "def f(x, *, opts=dict()):\n    return opts\n", self.RULE
        )
        assert rules_of(fs) == [self.RULE]

    def test_none_default_is_fine(self):
        fs = lint_rule(
            """
def f(x, acc=None):
    acc = [] if acc is None else acc
    return acc
""",
            self.RULE,
        )
        assert fs == []

    def test_call_with_list_arg_is_not_a_default(self):
        # BatchResult(text="", token_ids=[]) at a CALL site is fine.
        fs = lint_rule("r = Result(text='', token_ids=[])\n", self.RULE)
        assert fs == []


# ---------------------------------------------------------- bare-except-swallow


class TestBareExceptSwallow:
    RULE = "bare-except-swallow"

    def test_except_exception_pass(self):
        fs = lint_rule(
            """
try:
    probe()
except Exception:
    pass
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_bare_except_continue(self):
        fs = lint_rule(
            """
while True:
    try:
        step()
    except:
        continue
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_narrow_except_pass_is_fine(self):
        # `except OSError: pass` around socket close is the tree's idiom.
        fs = lint_rule(
            """
try:
    sock.close()
except OSError:
    pass
""",
            self.RULE,
        )
        assert fs == []

    def test_logged_broad_except_is_fine(self):
        fs = lint_rule(
            """
try:
    step()
except Exception as e:
    log.debug("step failed: %s", e)
""",
            self.RULE,
        )
        assert fs == []


# ----------------------------------------------------- MsgType drift (PR 3)


class TestMsgTypeDrift:
    RULE = "frame-field-drift"

    PROTO = """
from enum import IntEnum

class MsgType(IntEnum):
    HELLO = 1
    ORPHAN = 2
    UNREAD = 3

def hello_frame():
    return Frame(MsgType.HELLO, {})

def unread_frame():
    return Frame(MsgType.UNREAD, {})
"""

    WORKER = """
import proto

def serve(frame):
    if frame.type == proto.MsgType.HELLO:
        return "hi"
"""

    def _run(self, srcs):
        return engine.run_lint(
            list(srcs), select=[self.RULE], reader=lambda p: srcs[str(p)]
        )

    def test_member_without_producer_and_without_consumer(self):
        res = self._run({"proto.py": self.PROTO, "worker.py": self.WORKER})
        msgs = sorted(f.message for f in res.findings)
        assert len(msgs) == 2
        assert "MsgType.ORPHAN has no producer" in msgs[0]
        assert "MsgType.UNREAD is produced but never consumed" in msgs[1]

    def test_match_case_and_dispatch_dict_count_as_consumers(self):
        worker = """
import proto

HANDLERS = {proto.MsgType.UNREAD: print}

def serve(frame):
    match frame.type:
        case proto.MsgType.HELLO:
            return "hi"
"""
        proto_src = self.PROTO.replace("    ORPHAN = 2\n", "")
        res = self._run({"proto.py": proto_src, "worker.py": worker})
        assert res.findings == []

    def test_lone_proto_does_not_flag_unconsumed(self):
        # Without the consumer files in the run, "never consumed" cannot be
        # judged; "no producer" still can (builders live in proto.py).
        res = self._run({"proto.py": self.PROTO})
        assert [
            f.message.split(" ")[0] for f in res.findings
        ] == ["MsgType.ORPHAN"]


# ------------------------------------------------------------- sharding pack


class TestUnknownMeshAxis:
    RULE = "unknown-mesh-axis"

    def test_typod_axis_flagged(self):
        fs = lint_rule(
            """
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

TP_AXIS = "tp"
mesh = Mesh(np.array([0]), (TP_AXIS,))
spec = P(None, "tpp")
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "'tpp'" in fs[0].message

    def test_axis_constant_resolved_through_import(self):
        srcs = {
            "pkg/tensor.py": (
                "import numpy as np\n"
                "from jax.sharding import Mesh\n"
                'TP_AXIS = "tp"\n'
                "mesh = Mesh(np.array([0]), (TP_AXIS,))\n"
            ),
            "pkg/user.py": (
                "from jax.sharding import PartitionSpec as P\n"
                "from pkg.tensor import TP_AXIS\n"
                "good = P(None, TP_AXIS)\n"
                'bad = P("stage")\n'
            ),
        }
        res = engine.run_lint(
            list(srcs), select=[self.RULE], reader=lambda p: srcs[str(p)]
        )
        assert len(res.findings) == 1
        assert "'stage'" in res.findings[0].message
        assert res.findings[0].path == "pkg/user.py"

    def test_no_mesh_in_run_is_silent(self):
        fs = lint_rule(
            """
from jax.sharding import PartitionSpec as P

spec = P("anything")
""",
            self.RULE,
        )
        assert fs == []

    def test_unresolvable_axis_name_is_skipped(self):
        fs = lint_rule(
            """
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

mesh = Mesh(np.array([0]), ("tp",))

def spec_for(axis_name):
    return P(None, axis_name)
""",
            self.RULE,
        )
        assert fs == []


class TestSpecArityMismatch:
    RULE = "spec-arity-mismatch"

    def test_in_specs_count_vs_params(self):
        fs = lint_rule(
            """
def outer(mesh, P, shard_map):
    def body(a, b):
        return a
    return shard_map(body, mesh=mesh, in_specs=(P(), P(), P()),
                     out_specs=P())
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "3 spec(s)" in fs[0].message and "2 positional" in fs[0].message

    def test_out_specs_tuple_vs_return_arity(self):
        fs = lint_rule(
            """
def outer(mesh, P, checked_shard_map):
    def body(a, b):
        return a, b
    return checked_shard_map(body, mesh=mesh, in_specs=(P(), P()),
                             out_specs=(P(),))
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "returns a 2-tuple" in fs[0].message

    def test_matching_site_is_clean_and_nested_returns_ignored(self):
        fs = lint_rule(
            """
def outer(mesh, P, shard_map):
    def body(a, b):
        def inner(c):
            return c, c, c
        return a, inner(b)
    return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                     out_specs=(P(), P()))
""",
            self.RULE,
        )
        assert fs == []

    def test_defaulted_trailing_params_are_optional(self):
        # shard_map(body) with fewer operands than params is valid when the
        # tail params have defaults — the specs match what is passed.
        fs = lint_rule(
            """
def outer(mesh, P, shard_map):
    def body(a, b, scale=1.0):
        return a
    return shard_map(body, mesh=mesh, in_specs=(P(), P()), out_specs=P())
""",
            self.RULE,
        )
        assert fs == []

    def test_specs_above_param_count_still_flagged(self):
        fs = lint_rule(
            """
def outer(mesh, P, shard_map):
    def body(a, b, scale=1.0):
        return a
    return shard_map(body, mesh=mesh, in_specs=(P(), P(), P(), P()),
                     out_specs=P())
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "2-3 positional" in fs[0].message

    def test_forwarding_wrapper_site_is_checked(self):
        # The sequence.py _shard_specs idiom: any call forwarding both
        # in_specs= and out_specs= with a resolvable body.
        fs = lint_rule(
            """
class Runner:
    def build(self):
        def body(a):
            return a
        return self._shard_specs(body, in_specs=(P(), P()), out_specs=P())
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_pallas_call_in_specs_exempt(self):
        # pallas_call's in_specs obey the KERNEL contract (refs include
        # outputs + scratch) — rules/pallas.py owns that surface.
        fs = lint_rule(
            """
def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def run(pl, x):
    return pl.pallas_call(kern, grid=(1,), in_specs=[pl.BlockSpec()],
                          out_specs=pl.BlockSpec())(x)
""",
            self.RULE,
        )
        assert fs == []


# --------------------------------------------------------------- pallas pack


class TestBlockSpecIndexMapArity:
    RULE = "blockspec-indexmap-arity"

    def test_lambda_arity_vs_grid_rank(self):
        fs = lint_rule(
            """
def run(pl, x):
    return pl.pallas_call(
        kern,
        grid=(4, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "takes 1 argument(s)" in fs[0].message

    def test_prefetch_grid_spec_adds_leading_args(self):
        # num_scalar_prefetch=2 + rank-2 grid: maps take 4 args; the named
        # 3-arg map (resolved through the local grid_spec binding) fails.
        fs = lint_rule(
            """
def idx3(i, j, s):
    return (i, j)

def run(pl, pltpu, x):
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(2, 2),
        in_specs=[pl.BlockSpec((1, 8), idx3)],
        out_specs=pl.BlockSpec((1, 8), lambda i, j, s, t: (i, j)),
    )
    return pl.pallas_call(kern, grid_spec=gs)(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "2 scalar-prefetch" in fs[0].message

    def test_grid_through_local_name_and_matching_arity_clean(self):
        fs = lint_rule(
            """
def run(pl, x):
    grid = (4, 4, 2)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[pl.BlockSpec((8, 128), lambda i, j, k: (i, k))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j, k: (i, j)),
    )(x)
""",
            self.RULE,
        )
        assert fs == []

    def test_nested_def_binding_does_not_shadow_grid(self):
        # A nested helper's own `grid` lives in a different namespace; the
        # pallas_call's grid= must resolve to the ENCLOSING scope's tuple.
        fs = lint_rule(
            """
def run(pl, x):
    grid = (4, 4)
    def helper():
        grid = (8,)
        return grid
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )(x)
""",
            self.RULE,
        )
        assert fs == []


class TestGridBlockRankMismatch:
    RULE = "grid-block-rank-mismatch"

    def test_block_rank_vs_index_tuple(self):
        fs = lint_rule(
            """
def run(pl, x):
    return pl.pallas_call(
        kern,
        grid=(4,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
    )(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "rank 2" in fs[0].message and "3-tuple" in fs[0].message

    def test_named_index_map_checked(self):
        fs = lint_rule(
            """
def kv_index(i, j):
    return (i, j, 0)

def run(pl, x):
    return pl.pallas_call(
        kern,
        grid=(4, 2),
        in_specs=[pl.BlockSpec((1, 8, 128), kv_index)],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )(x)
""",
            self.RULE,
        )
        assert fs == []


class TestTracedBlockDim:
    RULE = "traced-block-dim"

    def test_traced_param_in_block_shape(self):
        fs = lint_rule(
            """
import jax

@jax.jit
def run(x, bq):
    return pl.pallas_call(
        kern, grid=(4,),
        in_specs=[pl.BlockSpec((bq, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
    )(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "`bq`" in fs[0].message

    def test_static_param_is_exempt(self):
        # The block_q/block_k static-knob idiom of every ops/pallas wrapper.
        fs = lint_rule(
            """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("bq",))
def run(x, bq):
    bq = min(bq, 128)
    return pl.pallas_call(
        kern, grid=(4,),
        in_specs=[pl.BlockSpec((bq, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
    )(x)
""",
            self.RULE,
        )
        assert fs == []

    def test_traced_param_in_grid(self):
        fs = lint_rule(
            """
import jax

@jax.jit
def run(x, n):
    return pl.pallas_call(
        kern, grid=(n, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, j)),
    )(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "grid entry" in fs[0].message

    def test_unjitted_wrapper_is_not_flagged(self):
        fs = lint_rule(
            """
def run(pl, x, bq):
    return pl.pallas_call(
        kern, grid=(4,),
        in_specs=[pl.BlockSpec((bq, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
    )(x)
""",
            self.RULE,
        )
        assert fs == []

    # The ops/pallas/paged_prefill.py family shape (ISSUE 9 convention:
    # new kernel family => rule engagement pinned positive AND negative):
    # a jitted wrapper whose block geometry derives page_size from a pool
    # operand's SHAPE (static at trace time — clean), vs one that takes
    # page_size as a traced parameter (flagged).
    PAGED_SHAPE = """
import functools
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

@functools.partial(jax.jit, static_argnames=("block_q",))
def paged_chunk(q, k_pages, qs, tables, block_q=128):
    page_size = {PAGE_EXPR}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(4, 2),
        in_specs=[
            pl.BlockSpec((1, block_q), lambda b, i, qs, tables: (b, i)),
            pl.BlockSpec(
                (1, page_size), lambda b, i, qs, tables: (tables[b, i], 0)
            ),
        ],
        out_specs=pl.BlockSpec((1, block_q), lambda b, i, qs, tables: (b, i)),
    )
    return pl.pallas_call(
        functools.partial(kern, block_q=block_q), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(qs, tables, q, k_pages)
"""

    def test_paged_family_shape_derived_page_size_is_clean(self):
        src = self.PAGED_SHAPE.replace("{PAGE_EXPR}", "k_pages.shape[2]")
        assert lint_rule(src, self.RULE) == []

    def test_paged_family_traced_page_size_is_flagged(self):
        src = self.PAGED_SHAPE.replace(
            "def paged_chunk(q, k_pages, qs, tables, block_q=128):",
            "def paged_chunk(q, k_pages, qs, tables, page_size, block_q=128):",
        ).replace("    page_size = {PAGE_EXPR}\n", "")
        fs = lint_rule(src, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "`page_size`" in fs[0].message


# ------------------------------------------------------- prefetch-ref-unused


class TestPrefetchRefUnused:
    RULE = "prefetch-ref-unused"

    # The ISSUE's motivating bug: a block table passed as scalar prefetch but
    # read by NOTHING — every sequence silently reads page 0.
    SNIPPET = """
import functools
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _kern(tables_ref, x_ref, o_ref):
    o_ref[...] = x_ref[...]

def run(x, tables):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, 128), lambda i, tables: (i, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i, tables: (i, 0)),
    )
    return pl.pallas_call(_kern, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(tables, x)
"""

    def test_ignored_block_table_is_flagged(self):
        fs = lint_rule(self.SNIPPET, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "`tables_ref`" in fs[0].message

    def test_index_map_read_counts_as_used(self):
        src = self.SNIPPET.replace(
            "in_specs=[pl.BlockSpec((1, 128), lambda i, tables: (i, 0))],",
            "in_specs=[pl.BlockSpec((1, 128),"
            " lambda i, tables: (tables[i], 0))],",
        )
        assert lint_rule(src, self.RULE) == []

    def test_kernel_body_read_counts_as_used(self):
        src = self.SNIPPET.replace(
            "o_ref[...] = x_ref[...]",
            "o_ref[...] = x_ref[...] * tables_ref[0]",
        )
        assert lint_rule(src, self.RULE) == []

    def test_partial_wrapped_kernel_resolves(self):
        # The ops/pallas idiom: the kernel rides functools.partial with
        # keyword-only static knobs; the body ignores the prefetch ref.
        src = self.SNIPPET.replace(
            "pl.pallas_call(_kern, grid_spec=grid_spec,",
            "pl.pallas_call(functools.partial(_kern, ), grid_spec=grid_spec,",
        )
        fs = lint_rule(src, self.RULE)
        assert rules_of(fs) == [self.RULE]

    def test_unresolvable_index_map_stays_silent(self):
        # An index map whose arity cannot line up with the prefetch args
        # might read anything — no finding, by design.
        src = self.SNIPPET.replace(
            "lambda i, tables: (i, 0))],\n", "make_imap())],\n", 1
        )
        assert lint_rule(src, self.RULE) == []

    def test_second_of_two_refs_flagged(self):
        src = """
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _kern(lens_ref, starts_ref, x_ref, o_ref):
    o_ref[...] = x_ref[...] * lens_ref[0]

def run(x, lens, starts):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, 128), lambda i, lens, starts: (lens[i], 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i, lens, starts: (i, 0)),
    )
    return pl.pallas_call(_kern, grid_spec=grid_spec,
                          out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(lens, starts, x)
"""
        fs = lint_rule(src, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "#1" in fs[0].message and "`starts_ref`" in fs[0].message

    # The ops/pallas/paged_prefill.py family shape (ISSUE 9 convention): a
    # 4-D grid with FIVE scalar-prefetch operands and a NAMED page-resolving
    # index map shared by K and V. Negative: the real pattern — the block
    # table is read inside `_kv_index`, everything else inside the kernel.
    # Positive: an index map that clamps the logical page but never consults
    # the table — every sequence silently streams page `ki` as physical.
    PAGED_SHAPE = """
import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _kern(qs_ref, lens_ref, ks_ref, tables_ref, flag_ref, q_ref, k_ref, o_ref):
    o_ref[...] = q_ref[...] * qs_ref[0] * lens_ref[0] * ks_ref[0] * flag_ref[0]

def _kv_index(bi, hi, qi, ki, qs, lens, ks, tables, fl):
    last = jnp.maximum(lens[bi] // 128 - 1, 0)
    phys = tables[bi, jnp.clip(ki, 0, last)]
    return (jnp.maximum(phys, 0), hi, 0, 0)

def run(q, k_pages, qs, lens, ks, tables, flag):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(2, 2, 2, 4),
        in_specs=[
            pl.BlockSpec(
                (1, 1, 128, 64),
                lambda bi, hi, qi, ki, qs, lens, ks, tables, fl: (bi, hi, qi, 0),
            ),
            pl.BlockSpec((1, 1, 128, 64), _kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 128, 64),
            lambda bi, hi, qi, ki, qs, lens, ks, tables, fl: (bi, hi, qi, 0),
        ),
    )
    return pl.pallas_call(
        functools.partial(_kern), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(qs, lens, ks, tables, flag, q, k_pages)
"""

    def test_paged_chunk_family_shape_is_clean(self):
        assert lint_rule(self.PAGED_SHAPE, self.RULE) == []

    def test_paged_chunk_index_map_ignoring_table_is_flagged(self):
        src = self.PAGED_SHAPE.replace(
            "    phys = tables[bi, jnp.clip(ki, 0, last)]\n"
            "    return (jnp.maximum(phys, 0), hi, 0, 0)",
            "    return (jnp.clip(ki, 0, last), hi, 0, 0)",
        )
        fs = lint_rule(src, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "#3" in fs[0].message and "`tables_ref`" in fs[0].message


# ------------------------------------------------------------ unblocked-timing


class TestUnblockedTiming:
    RULE = "unblocked-timing"

    def test_delta_around_jit_call_without_block(self):
        fs = lint_rule(
            """
import time
import jax

step = jax.jit(lambda x: x + 1)

def measure(x):
    t0 = time.perf_counter()
    y = step(x)
    return time.perf_counter() - t0
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "async dispatch" in fs[0].message

    def test_block_until_ready_closes_the_window(self):
        fs = lint_rule(
            """
import time
import jax

step = jax.jit(lambda x: x + 1)

def measure(x):
    t0 = time.perf_counter()
    y = step(x)
    jax.block_until_ready(y)
    return time.perf_counter() - t0
""",
            self.RULE,
        )
        assert fs == []

    def test_np_asarray_readback_closes_the_window(self):
        fs = lint_rule(
            """
import time
import jax
import numpy as np

step = jax.jit(lambda x: x + 1)

def measure(x):
    t0 = time.perf_counter()
    y = step(x)
    out = np.asarray(y)
    return time.perf_counter() - t0
""",
            self.RULE,
        )
        assert fs == []

    def test_wrapper_from_local_jit_factory(self):
        # The lru-cached builder idiom: fn = _decode_fn(...); fn(...) —
        # the factory's return jax.jit(...) marks its products as wrappers.
        fs = lint_rule(
            """
import time
import jax

def _build(n):
    def run(x):
        return x * n
    return jax.jit(run)

def measure(x):
    g = _build(2)
    t0 = time.perf_counter()
    y = g(x)
    dt = time.perf_counter() - t0
    return dt
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_tracked_jit_counts_as_a_jit_wrapper(self):
        fs = lint_rule(
            """
import time
from cake_tpu.obs.jitwatch import tracked_jit

step = tracked_jit(lambda x: x + 1, name="s")

def measure(x):
    t0 = time.perf_counter()
    y = step(x)
    return time.perf_counter() - t0
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_timed_non_jit_call_is_fine(self):
        fs = lint_rule(
            """
import time

def measure(sock):
    t0 = time.perf_counter()
    sock.send(b"x")
    return time.perf_counter() - t0
""",
            self.RULE,
        )
        assert fs == []

    def test_timer_reuse_checks_each_window_against_its_own_binding(self):
        # The same t0 name reused for a second (blocked) window must not
        # mask the FIRST window's missing sync.
        fs = lint_rule(
            """
import time
import jax

step = jax.jit(lambda x: x + 1)

def measure(x):
    t0 = time.perf_counter()
    y = step(x)
    bad = time.perf_counter() - t0
    t0 = time.perf_counter()
    z = step(x)
    jax.block_until_ready(z)
    good = time.perf_counter() - t0
    return bad, good
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert fs[0].line == 10  # the FIRST delta, not the blocked second

    def test_delta_before_the_jit_call_is_fine(self):
        # The window is positional: a call AFTER the clock is read again
        # is not inside the measurement.
        fs = lint_rule(
            """
import time
import jax

step = jax.jit(lambda x: x + 1)

def measure(x):
    t0 = time.perf_counter()
    dt = time.perf_counter() - t0
    y = step(x)
    return dt
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------ unbounded-socket-op


class TestUnboundedSocketOp:
    RULE = "unbounded-socket-op"
    PATH = "cake_tpu/runtime/snippet.py"

    def test_recv_with_no_timeout_in_scope(self):
        fs = lint_rule(
            """
def pump(sock):
    return sock.recv(4096)
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]
        assert "sock.recv" in fs[0].message

    def test_sendall_on_untimed_created_socket(self):
        fs = lint_rule(
            """
import socket

def push(data):
    s = socket.create_connection(("h", 1))
    s.sendall(data)
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_settimeout_in_scope_is_fine(self):
        fs = lint_rule(
            """
def pump(sock):
    sock.settimeout(5.0)
    return sock.recv(4096)
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_settimeout_none_does_not_count(self):
        fs = lint_rule(
            """
def pump(sock):
    sock.settimeout(None)
    return sock.recv(4096)
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_create_connection_timeout_kwarg_is_fine(self):
        fs = lint_rule(
            """
import socket

def push(data):
    s = socket.create_connection(("h", 1), timeout=3.0)
    s.sendall(data)
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_class_scope_covers_handed_around_connections(self):
        # The accept loop configures the conn; another method uses it —
        # the whole class is the configuring scope for parameters/self attrs.
        fs = lint_rule(
            """
class Server:
    def accept_loop(self, conn):
        conn.settimeout(30.0)
        self._serve(conn)

    def _serve(self, conn):
        conn.sendall(b"hi")
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_self_sock_untimed_across_methods(self):
        fs = lint_rule(
            """
import socket

class Client:
    def __init__(self):
        self._sock = socket.create_connection(("h", 1))

    def push(self, data):
        self._sock.sendall(data)
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_non_socket_connect_is_ignored(self):
        fs = lint_rule(
            """
def run(db):
    db.connect()
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_outside_runtime_is_ignored(self):
        fs = lint_rule(
            """
def pump(sock):
    return sock.recv(4096)
""",
            self.RULE,
            path="cake_tpu/utils/snippet.py",
        )
        assert fs == []


# ------------------------------------------------------------------- the tree


def test_every_shipped_rule_is_registered():
    names = {r["name"] for r in engine.rule_table()}
    assert names == {
        "host-sync-in-jit",
        "jit-in-hot-loop",
        "unhashable-static-arg",
        "unblocked-timing",
        "donation-after-use",
        "unlocked-shared-mutation",
        "frame-field-drift",
        "unknown-mesh-axis",
        "spec-arity-mismatch",
        "blockspec-indexmap-arity",
        "grid-block-rank-mismatch",
        "traced-block-dim",
        "traced-sampling-knob",
        "prefetch-ref-unused",
        "mutable-default-arg",
        "bare-except-swallow",
        "unbounded-socket-op",
        "naked-retry-loop",
        "stale-block-table",
        "unbounded-wait",
        "unbounded-metric-label",
        "span-leak",
        "step-state-unlocked",
        "taxonomy-drift",
        "requestlog-field-drift",
        "lock-order-cycle",
        "blocking-call-under-lock",
        "callback-under-lock",
        "notify-outside-lock",
        "leak-on-error-path",
        "double-release",
        "release-outside-choke-point",
        "refund-missing-on-shed",
    }


def test_readme_documents_every_rule():
    """The README rule catalog is pinned against the registry: adding a
    rule without a README row (or renaming one) fails here, so the docs
    cannot drift from the code."""
    repo = __import__("pathlib").Path(__file__).resolve().parent.parent
    readme = (repo / "README.md").read_text()
    missing = [
        r["name"]
        for r in engine.rule_table()
        if f"`{r['name']}`" not in readme
    ]
    assert missing == [], f"rules missing from README.md: {missing}"


# ------------------------------------------------------------ naked-retry-loop


class TestNakedRetryLoop:
    RULE = "naked-retry-loop"
    PATH = "cake_tpu/runtime/snippet.py"

    def test_unbounded_retry_without_backoff(self):
        fs = lint_rule(
            """
def pump(sock):
    while True:
        try:
            return sock.recv(4096)
        except ConnectionError:
            continue
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]
        assert "while True" in fs[0].message

    def test_hop_call_retry_flagged(self):
        fs = lint_rule(
            """
def round_trip(client, frame):
    while True:
        try:
            return client.forward(frame)
        except (TimeoutError, OSError):
            client.reconnect()
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_bounded_for_loop_is_fine(self):
        fs = lint_rule(
            """
def pump(sock):
    for attempt in range(3):
        try:
            return sock.recv(4096)
        except ConnectionError:
            continue
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_backoff_in_scope_is_fine(self):
        fs = lint_rule(
            """
import time

def pump(sock):
    while True:
        try:
            return sock.recv(4096)
        except ConnectionError:
            time.sleep(0.5)
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_event_wait_counts_as_backoff(self):
        fs = lint_rule(
            """
def probe(self, sock):
    while True:
        try:
            sock.sendall(b"ping")
        except ConnectionError:
            pass
        self._stop.wait(1.0)
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_handler_that_raises_is_fine(self):
        fs = lint_rule(
            """
def pump(sock):
    while True:
        try:
            return sock.recv(4096)
        except ConnectionError:
            raise
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_stop_flag_loop_is_fine(self):
        fs = lint_rule(
            """
def serve(self, conn):
    while not self._stop.is_set():
        try:
            conn.recv(1)
        except ConnectionError:
            continue
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_non_connection_except_is_fine(self):
        fs = lint_rule(
            """
def pump(sock):
    while True:
        try:
            return sock.recv(4096)
        except ValueError:
            continue
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_outside_runtime_is_fine(self):
        fs = lint_rule(
            """
def pump(sock):
    while True:
        try:
            return sock.recv(4096)
        except ConnectionError:
            continue
""",
            self.RULE,
            path="cake_tpu/ops/snippet.py",
        )
        assert fs == []


# ----------------------------------------------------------- stale-block-table


class TestStaleBlockTable:
    RULE = "stale-block-table"

    def test_row_used_after_make_private(self):
        # The detached-row bug class: the captured row still names the
        # SHARED page after the CoW split remapped the lane.
        fs = lint_rule(
            """
def write(self, lane, lp):
    row = self.allocator.block_tables[lane]
    self.allocator.make_private(lane, lp)
    return row[lp]
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "`row`" in fs[0].message

    def test_table_snapshot_used_after_fork_chain(self):
        # Whole-table snapshots (the jnp.asarray operand idiom) go stale
        # the same way — copies are snapshots of the same dead mapping.
        fs = lint_rule(
            """
def dispatch(self, lane, pages):
    tables = jnp.asarray(self.allocator.block_tables)
    self.allocator.fork_chain(lane, pages, 0)
    return run(tables)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_generic_mutator_needs_allocatorish_receiver(self):
        # `lease.release()` is not an allocator mutation; `alloc.release`
        # and `self._prefix.fork` are.
        fs = lint_rule(
            """
def ok(self, lane, lease):
    row = self.allocator.block_tables[lane]
    lease.release()
    return row[0]

def bad(self, lane, alloc):
    row = alloc.block_tables[lane]
    alloc.release(lane)
    return row[0]

def bad2(self, lane, ids, pad):
    row = self.allocator.block_tables[lane]
    self._prefix.fork(lane, ids, pad)
    return row[0]
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE, self.RULE]
        assert [f.line for f in fs] == [10, 15]

    def test_reread_after_mutation_is_fine(self):
        # Rebinding from a fresh read AFTER the mutation is the fix.
        fs = lint_rule(
            """
def write(self, lane, lp):
    row = self.allocator.block_tables[lane]
    use(row)
    self.allocator.make_private(lane, lp)
    row = self.allocator.block_tables[lane]
    return row[lp]
""",
            self.RULE,
        )
        assert fs == []

    def test_inline_read_at_use_site_is_fine(self):
        fs = lint_rule(
            """
def write(self, lane, lp):
    self.allocator.make_private(lane, lp)
    return self.allocator.block_tables[lane][lp]
""",
            self.RULE,
        )
        assert fs == []

    def test_refcount_only_ops_do_not_invalidate(self):
        # retain/release_pages touch refcounts, never lane rows: the
        # prefix cache's insert path captures a lane's page and swaps
        # cache references around it legitimately.
        fs = lint_rule(
            """
def insert(self, lane, logical):
    phys = int(self.allocator.block_tables[lane][logical])
    self.allocator.retain_pages([phys])
    self.allocator.release_pages([phys])
    return phys
""",
            self.RULE,
        )
        assert fs == []

    def test_use_before_mutation_is_fine(self):
        fs = lint_rule(
            """
def release(self, lane):
    row = self.allocator.block_tables[lane]
    flush(row)
    self.allocator.release(lane)
""",
            self.RULE,
        )
        assert fs == []


# --------------------------------------------------------------- unbounded-wait


class TestUnboundedWait:
    RULE = "unbounded-wait"
    PATH = "cake_tpu/runtime/snippet.py"

    def test_condition_wait_without_timeout(self):
        fs = lint_rule(
            """
import threading

class Engine:
    def __init__(self):
        self._cv = threading.Condition()

    def run(self):
        with self._cv:
            self._cv.wait()
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]
        assert "self._cv.wait()" in fs[0].message

    def test_event_wait_without_timeout_as_parameter(self):
        # Name heuristic: a handed-around `*event` parameter counts.
        fs = lint_rule(
            """
def block(done_event):
    done_event.wait()
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_thread_join_without_timeout(self):
        fs = lint_rule(
            """
import threading

class Guard:
    def __init__(self):
        self._worker = threading.Thread(target=print)

    def stop(self):
        self._worker.join()
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]
        assert ".join()" in fs[0].message

    def test_bounded_waits_and_joins_are_fine(self):
        fs = lint_rule(
            """
import threading

class Engine:
    def __init__(self):
        self._cv = threading.Condition()
        self._worker = threading.Thread(target=print)

    def run(self):
        with self._cv:
            self._cv.wait(timeout=1.0)

    def stop(self):
        self._worker.join(5.0)
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []

    def test_timeout_none_is_still_unbounded(self):
        fs = lint_rule(
            """
import threading

class Engine:
    def __init__(self):
        self._cv = threading.Condition()

    def run(self):
        self._cv.wait(timeout=None)
""",
            self.RULE,
            path=self.PATH,
        )
        assert rules_of(fs) == [self.RULE]

    def test_obs_and_utils_are_in_scope(self):
        # ISSUE 17 widened the gate beyond runtime/: the telemetry locks
        # and flusher threads in obs/ and utils/ play by the same rules.
        src = """
import threading

class Engine:
    def __init__(self):
        self._cv = threading.Condition()

    def run(self):
        self._cv.wait()
"""
        for path in (
            "cake_tpu/obs/snippet.py",
            "cake_tpu/utils/snippet.py",
        ):
            fs = lint_rule(src, self.RULE, path=path)
            assert rules_of(fs) == [self.RULE], path

    def test_jit_side_trees_are_out_of_scope(self):
        # ops/ and models/ stay out: no thread coordination there, and a
        # `wait` is somebody's math helper.
        fs = lint_rule(
            """
import threading

class Engine:
    def __init__(self):
        self._cv = threading.Condition()

    def run(self):
        self._cv.wait()
""",
            self.RULE,
            path="cake_tpu/models/snippet.py",
        )
        assert fs == []

    def test_unrelated_wait_receivers_not_flagged(self):
        # A `.wait()` on something that is neither factory-assigned nor
        # name-matched (a subprocess handle, a future) is out of scope.
        fs = lint_rule(
            """
def reap(proc):
    proc.wait()
""",
            self.RULE,
            path=self.PATH,
        )
        assert fs == []


# ---------------------------------------------------- unbounded-metric-label


class TestUnboundedMetricLabel:
    RULE = "unbounded-metric-label"

    def test_request_id_label_flagged(self):
        fs = lint_rule(
            """
from cake_tpu.utils import metrics

def record(rid):
    metrics.registry.counter("cake_ops_total", "ops").inc(rid=rid)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "rid" in fs[0].message

    def test_raw_header_label_flagged(self):
        fs = lint_rule(
            """
from cake_tpu.utils import metrics

def record(handler):
    metrics.registry.gauge("cake_client_info", "x").set(
        1, client=handler.headers.get("User-Agent")
    )
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_fresh_uuid_and_prompt_flagged_on_local_metric(self):
        fs = lint_rule(
            """
import uuid
from cake_tpu.utils import metrics

def record(prompt):
    h = metrics.registry.histogram("cake_x_seconds", "x")
    h.observe(0.5, req=str(uuid.uuid4()))
    h.observe(0.5, text=prompt)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE, self.RULE]

    def test_bounded_labels_not_flagged(self):
        # The real tree's conventions: node names, capped tenant ids, enum
        # kinds, directions — all bounded sets, none flagged.
        fs = lint_rule(
            """
from cake_tpu.utils import metrics

def record(node, tenant, kind):
    metrics.registry.counter("cake_ops_total", "ops").inc(
        node=node, tenant=tenant, kind=kind, direction="rx"
    )
    metrics.registry.gauge("cake_level", "x").set(3.0, node=node)
""",
            self.RULE,
        )
        assert fs == []

    def test_value_kwargs_and_non_metric_calls_out_of_scope(self):
        # n=/v= are sample values, not labels; flight.record and arbitrary
        # .set() receivers are not metric record calls.
        fs = lint_rule(
            """
from cake_tpu.utils import metrics

def record(rid, cost):
    metrics.registry.counter("cake_tokens_total", "t").inc(n=cost)
    metrics.flight.record("submitted", rid, request_id=rid)
    some_dict = {}
    some_dict.setdefault("x", 1)

class Config:
    def set(self, **kw): ...

def configure(cfg, request_id):
    cfg.set(request_id=request_id)
""",
            self.RULE,
        )
        assert fs == []

    def test_inline_suppression_respected(self):
        fs = lint_rule(
            """
from cake_tpu.utils import metrics

def record(rid):
    metrics.registry.counter("cake_debug_total", "d").inc(
        rid=rid  # cake-lint: disable=unbounded-metric-label
    )
""",
            self.RULE,
        )
        assert fs == []


# ---------------------------------------------------- traced-sampling-knob


class TestTracedSamplingKnob:
    RULE = "traced-sampling-knob"

    # The fused decode family contract (ISSUE 13): sampling knobs are
    # static; a jitted wrapper that takes one traced either fails to trace
    # or recompiles per value.
    SNIPPET = """
import jax
from cake_tpu.ops.pallas.fused_sample_tail import fused_sample_tail

@jax.jit
def tail(logits, ring, noise, temperature):
    return fused_sample_tail(
        logits, ring, noise, temperature=temperature, top_k=None,
        top_p=None, repeat_penalty=1.0, impl="xla",
    )
"""

    def test_traced_temperature_is_flagged(self):
        fs = lint_rule(self.SNIPPET, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "`temperature`" in fs[0].message

    def test_static_argnames_knob_is_clean(self):
        src = self.SNIPPET.replace(
            "@jax.jit",
            '@functools.partial(jax.jit, static_argnames=("temperature",))',
        ).replace("import jax", "import functools\nimport jax")
        assert lint_rule(src, self.RULE) == []

    def test_closure_knobs_are_clean(self):
        # The repo idiom: knobs close over the jitted fn, never ride it.
        src = """
import jax
from cake_tpu.models.llama.fused import sampled_decode_scan

def build(temperature, top_k):
    def run(kv, tok, slot, keys, ring, ring_idx):
        return sampled_decode_scan(
            lambda t, kv, p: (t, kv), kv, tok, slot, keys, ring, ring_idx,
            n_steps=4, temperature=temperature, top_k=top_k, top_p=None,
            repeat_penalty=1.0,
        )
    return jax.jit(run, donate_argnums=(0,))
"""
        assert lint_rule(src, self.RULE) == []

    def test_non_fused_family_jit_with_knob_param_is_clean(self):
        # A jit that never calls into the fused family may do what it
        # likes with a parameter that happens to be named temperature.
        src = """
import jax

@jax.jit
def scale(x, temperature):
    return x / temperature
"""
        assert lint_rule(src, self.RULE) == []

    def test_call_form_jit_traced_knob_is_flagged(self):
        src = """
import jax
from cake_tpu.models.llama.fused import sample_step

def one(logits, keys, ring, ring_idx, top_k):
    return sample_step(
        logits, keys, ring, ring_idx, temperature=0.7, top_k=top_k,
        top_p=None, repeat_penalty=1.0,
    )

sampler = jax.jit(one)
"""
        fs = lint_rule(src, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "`top_k`" in fs[0].message


class TestFusedFamilyKernelShapes:
    """ISSUE 13 convention (mirrors the ISSUE 9 pins): the new fused-kernel
    family shapes keep traced-block-dim and prefetch-ref-unused ENGAGED —
    positive and negative for each, on snippets shaped like the real
    kernels (ops/pallas/fused_sample_tail.py / fused_ingest.py)."""

    # The fused sampling tail's shape: ring as ONE scalar-prefetch operand,
    # a (b, n_v) grid over vocab tiles, block_v as a static knob.
    TAIL_SHAPE = """
import functools
import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _kern(ring_ref, logits_ref, o_ref, scr):
    o_ref[0, 0] = ring_ref[0, 0] + logits_ref[0, 0].astype('int32')

def _tile(bi, vi, ring):
    return (bi, vi)

def _out(bi, vi, ring):
    return (bi, 0)

@functools.partial(jax.jit, static_argnames=("block_v",))
def tail(logits, ring, block_v=128):
    vocab = logits.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(4, 2),
        in_specs=[pl.BlockSpec((1, block_v), _tile)],
        out_specs=pl.BlockSpec((1, 1), _out),
        scratch_shapes=[pltpu.VMEM((1, 256), 'float32')],
    )
    return pl.pallas_call(
        functools.partial(_kern), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((4, 1), 'int32'),
    )(ring, logits)
"""

    def test_tail_shape_static_block_v_is_clean(self):
        assert lint_rule(self.TAIL_SHAPE, "traced-block-dim") == []

    def test_tail_shape_traced_block_v_is_flagged(self):
        src = self.TAIL_SHAPE.replace(
            '@functools.partial(jax.jit, static_argnames=("block_v",))',
            "@jax.jit",
        )
        fs = lint_rule(src, "traced-block-dim")
        assert rules_of(fs) == ["traced-block-dim"]
        assert "`block_v`" in fs[0].message

    def test_tail_shape_ring_read_in_kernel_is_clean(self):
        assert lint_rule(self.TAIL_SHAPE, "prefetch-ref-unused") == []

    def test_tail_shape_ignored_ring_is_flagged(self):
        # A penalty ring that is plumbed but never read: the fusion would
        # silently sample unpenalized logits.
        src = self.TAIL_SHAPE.replace(
            "o_ref[0, 0] = ring_ref[0, 0] + logits_ref[0, 0].astype('int32')",
            "o_ref[0, 0] = logits_ref[0, 0].astype('int32')",
        )
        fs = lint_rule(src, "prefetch-ref-unused")
        assert rules_of(fs) == ["prefetch-ref-unused"]
        assert "`ring_ref`" in fs[0].message

    # The paged ingest's shape: slot + block table as scalar prefetch, the
    # write resolved through the table inside the kernel body.
    INGEST_SHAPE = """
import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _kern(slot_ref, tab_ref, qkv_ref, q_ref):
    bi = pl.program_id(0)
    phys = tab_ref[bi, jnp.minimum(slot_ref[0] // 8, tab_ref.shape[1] - 1)]
    q_ref[...] = qkv_ref[...] * (phys >= 0) * slot_ref[0]

def _row(bi, slot, tab):
    return (bi, 0)

def ingest(qkv, slot, tables):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, 128), _row)],
        out_specs=pl.BlockSpec((1, 128), _row),
    )
    return pl.pallas_call(
        functools.partial(_kern), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qkv.shape, qkv.dtype),
    )(slot, tables, qkv)
"""

    def test_ingest_shape_table_read_in_body_is_clean(self):
        assert lint_rule(self.INGEST_SHAPE, "prefetch-ref-unused") == []

    def test_ingest_shape_ignored_table_is_flagged(self):
        # The paging bug class: a block table passed but ignored — every
        # lane writes wherever the clamp lands instead of its own pages.
        src = self.INGEST_SHAPE.replace(
            "    phys = tab_ref[bi, jnp.minimum(slot_ref[0] // 8, "
            "tab_ref.shape[1] - 1)]\n"
            "    q_ref[...] = qkv_ref[...] * (phys >= 0) * slot_ref[0]",
            "    q_ref[...] = qkv_ref[...] * slot_ref[0]",
        )
        fs = lint_rule(src, "prefetch-ref-unused")
        assert rules_of(fs) == ["prefetch-ref-unused"]
        assert "`tab_ref`" in fs[0].message


# --------------------------------------------------------------- span-leak


class TestSpanLeak:
    RULE = "span-leak"

    def test_begin_without_end_is_flagged(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(req):
    sid = timeline.begin("request", track="lane0")
    do_work(req)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "never" in fs[0].message

    def test_end_only_under_if_is_flagged(self):
        # The non-raising else path leaks the span.
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(req, ok):
    sid = timeline.begin("request")
    if ok:
        timeline.end(sid)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "some paths" in fs[0].message

    def test_end_only_in_except_is_flagged(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(req):
    sid = timeline.begin("request")
    try:
        work(req)
    except ValueError:
        timeline.end(sid)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_end_in_finally_is_clean(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(req):
    sid = timeline.begin("request")
    try:
        work(req)
    finally:
        timeline.end(sid)
""",
            self.RULE,
        )
        assert fs == []

    def test_straight_line_end_is_clean(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(req):
    sid = timeline.begin("request")
    work(req)
    timeline.end(sid, args={"n": 1})
""",
            self.RULE,
        )
        assert fs == []

    def test_handed_off_id_is_clean(self):
        # Stored on self / returned / passed on: the lifecycle is the
        # holder's (exactly the serving.py _RowState shape).
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

class Row:
    def open_span(self):
        self._span = timeline.begin("request")

def open_and_return():
    sid = timeline.begin("request")
    return sid

def open_and_register(reg):
    sid = timeline.begin("request")
    reg.track(sid)
""",
            self.RULE,
        )
        assert fs == []

    def test_request_scoped_track_name_is_flagged(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(rid):
    with timeline.span("request", track=f"req-{rid}"):
        pass
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "track" in fs[0].message

    def test_bounded_track_names_are_clean(self):
        fs = lint_rule(
            """
from cake_tpu.obs.timeline import timeline

def serve(lane, rid):
    sid = timeline.begin("request", rid=rid, track=f"lane{lane}")
    timeline.instant("first-token", rid=rid, track="engine")
    timeline.end(sid)
""",
            self.RULE,
        )
        assert fs == []


# ----------------------------------------------------------- step-state-unlocked


class TestStepStateUnlocked:
    RULE = "step-state-unlocked"

    POSITIVE = """
import threading

class Engine:
    _STEP_STATE = ("_spilled", "_lane_map")

    def __init__(self):
        self._cv = threading.Condition()
        self._spilled = {}
        self._lane_map = {}

    def preempt(self, rid, rec):
        self._spilled[rid] = rec
"""

    NEGATIVE = """
import threading

class Engine:
    _STEP_STATE = ("_spilled",)

    def __init__(self):
        self._cv = threading.Condition()
        self._spilled = {}

    def preempt(self, rid, rec):
        with self._cv:
            self._spilled[rid] = rec

    def depth(self):
        return len(self._spilled)  # reads stay lock-free

    def other_state(self):
        self._scratch = 1  # undeclared attrs are not step state
"""

    def test_declared_attr_mutated_without_cv(self):
        fs = lint_rule(self.POSITIVE, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "_spilled" in fs[0].message

    def test_first_ever_mutation_is_flagged(self):
        # The differentiator vs unlocked-shared-mutation: no guarded
        # sibling site exists anywhere, yet the declaration still fires.
        fs = lint_rule(self.POSITIVE, "unlocked-shared-mutation")
        assert fs == []  # the inference-based rule is blind here
        fs = lint_rule(self.POSITIVE, self.RULE)
        assert len(fs) == 1

    def test_guarded_mutations_and_reads_are_clean(self):
        assert lint_rule(self.NEGATIVE, self.RULE) == []

    def test_init_is_exempt_and_undeclared_classes_skipped(self):
        assert lint_rule(
            """
import threading

class Plain:
    def __init__(self):
        self._lock = threading.Lock()
        self._spilled = {}

    def mutate(self):
        self._spilled = {}
""",
            self.RULE,
        ) == []

    def test_pop_and_clear_count_as_mutations(self):
        fs = lint_rule(
            """
import threading

class Engine:
    _STEP_STATE = ("_spilled",)

    def __init__(self):
        self._cv = threading.Condition()
        self._spilled = {}

    def drain(self):
        self._spilled.clear()

    def drop(self, rid):
        self._spilled.pop(rid, None)
""",
            self.RULE,
        )
        assert len(fs) == 2


# ---------------------------------------------------------- taxonomy-drift


class TestTaxonomyDrift:
    RULE = "taxonomy-drift"

    def test_store_into_phase_accumulator_outside_registry(self):
        fs = lint_rule(
            """
class Row:
    def account(self, dt):
        self.phase["warmup"] += dt
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "'warmup'" in fs[0].message
        assert "PHASES" in fs[0].message

    def test_store_into_buckets_outside_registry(self):
        fs = lint_rule(
            """
class Ledger:
    def add(self, dt):
        self.buckets["padx"] = dt
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "BUCKETS" in fs[0].message

    def test_phase_kwarg_literal_outside_registry(self):
        fs = lint_rule(
            """
def observe(hist, v):
    hist.observe(v, phase="warmup")
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_phase_observe_positional_literal(self):
        fs = lint_rule(
            """
class Engine:
    def note(self, s):
        self._phase_observe("cooldown", s)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_decision_vocabulary_pinned(self):
        fs = lint_rule(
            """
def verdict(audit, rid):
    audit.record("admit", "because_reasons", rid=rid)
    audit.record("evaporate", "fair_order", rid=rid)
""",
            self.RULE,
        )
        assert len(fs) == 2
        assert any("DECISION_CAUSES" in f.message for f in fs)
        assert any("DECISION_ACTIONS" in f.message for f in fs)

    def test_registered_names_and_dynamic_values_pass(self):
        # Registry members, dynamic (non-literal) names, stats-dict READ
        # navigation, and unrelated receivers are all out of scope.
        fs = lint_rule(
            """
class Row:
    def account(self, dt, phase):
        self.phase["decode"] += dt
        self.phase[phase] += dt

def add(ledger, dt):
    ledger.buckets["host_gap"] += dt

def render(stats, hist, v):
    total = stats["phases"]["phases"]
    hist.observe(v, phase="prefill")
    other = {}
    other["warmup"] = 1.0

def verdict(audit, rid):
    audit.record("defer", "page_pressure", rid=rid)
""",
            self.RULE,
        )
        assert fs == []


# -------------------------------------------------- requestlog-field-drift


class TestRequestLogFieldDrift:
    RULE = "requestlog-field-drift"

    def test_unregistered_field_on_record(self):
        fs = lint_rule(
            """
def finish(engine, rid):
    engine.requestlog.record(
        request_id=rid, tenant="t", finish_reason="stop",
        latency_bucket="fast",
    )
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "'latency_bucket'" in fs[0].message
        assert "REQUEST_LOG_FIELDS" in fs[0].message

    def test_receiver_stem_variants_and_literal_vocabularies(self):
        # request_log / reqlog receivers are in scope; literal
        # finish_reason/slo values are pinned to their registries.
        fs = lint_rule(
            """
def a(request_log, rid):
    request_log.record(
        request_id=rid, tenant="t", finish_reason="evaporated",
    )

def b(reqlog, rid):
    reqlog.record(
        request_id=rid, tenant="t", finish_reason="stop", slo="fine",
    )
""",
            self.RULE,
        )
        assert len(fs) == 2
        assert any("REQUEST_OUTCOMES" in f.message for f in fs)
        assert any("REQUEST_SLO_VERDICTS" in f.message for f in fs)

    def test_registered_fields_and_other_receivers_pass(self):
        # Registered fields with dynamic values pass; record() on audit/
        # flight/metric receivers is someone else's vocabulary; **fields
        # fan-ins are the runtime check's job.
        fs = lint_rule(
            """
def finish(engine, rid, finish, verdict, fields):
    engine.requestlog.record(
        request_id=rid, tenant="t", priority=1, prompt_tokens=4,
        completion_tokens=2, ttft_s=0.1, finish_reason=finish,
        slo=verdict, phases={}, decisions=[], node="local",
    )
    engine.requestlog.record(**fields)
    engine.audit.record("admit", "fair_order", rid=rid)
    flight.record("submitted", rid, path="serialized")
""",
            self.RULE,
        )
        assert fs == []
