"""Per-rule regression tests for cake_tpu/analysis.

Every shipped rule gets at least one TRUE-POSITIVE snippet (the test fails if
the rule is deleted or stops firing) and negative snippets pinning the
false-positive boundaries the real tree depends on (static-arg casts, rebind
donation, guarded mutations, narrowed excepts).

The analysis package is stdlib-only; none of these tests need jax.
"""

from __future__ import annotations

from cake_tpu.analysis import engine, lint_source


def rules_of(findings):
    return [f.rule for f in findings]


def lint_rule(src: str, rule: str, path: str = "snippet.py"):
    """Run ONE rule over a snippet (select= raises if the rule was deleted,
    so deleting a rule fails every test that names it)."""
    return lint_source(src, path=path, select=[rule])


# ------------------------------------------------------------ host-sync-in-jit


class TestHostSyncInJit:
    RULE = "host-sync-in-jit"

    def test_item_in_decorated_jit(self):
        fs = lint_rule(
            """
import jax

@jax.jit
def step(x):
    return x.item()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert ".item()" in fs[0].message

    def test_np_asarray_in_reachable_helper(self):
        # The sync hides one call deep: step -> helper -> np.asarray.
        fs = lint_rule(
            """
import jax
import numpy as np

def helper(y):
    return np.asarray(y)

def step(x):
    return helper(x) + 1

run = jax.jit(step)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_cast_of_traced_param(self):
        fs = lint_rule(
            """
import jax

def step(x, n):
    return x * int(n)

run = jax.jit(step)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_static_arg_cast_is_exempt(self):
        # int(n) on a static arg is concrete Python — the idiom every Pallas
        # kernel wrapper in ops/pallas/ uses.
        fs = lint_rule(
            """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("n",))
def step(x, n):
    return x * int(n)
""",
            self.RULE,
        )
        assert fs == []

    def test_jitted_bound_method(self):
        fs = lint_rule(
            """
import jax

class Backend:
    def __init__(self):
        self._step = jax.jit(self._impl)

    def _impl(self, x):
        return float(x)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_sync_outside_jit_is_fine(self):
        fs = lint_rule(
            """
import numpy as np

def host_side(x):
    return np.asarray(x).item()
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------- jit-in-hot-loop


class TestJitInHotLoop:
    RULE = "jit-in-hot-loop"

    def test_jit_constructed_in_loop(self):
        fs = lint_rule(
            """
import jax

def drive(f, steps):
    for s in steps:
        y = jax.jit(f)(s)
    return y
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_partial_jit_in_while(self):
        fs = lint_rule(
            """
import functools
import jax

def drive(f, xs):
    while xs:
        g = functools.partial(jax.jit, static_argnums=(1,))(f)
        xs = g(xs, 1)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_jit_hoisted_before_loop_is_fine(self):
        fs = lint_rule(
            """
import jax

def drive(f, steps):
    g = jax.jit(f)
    for s in steps:
        y = g(s)
    return y
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------- unhashable-static-arg


class TestUnhashableStaticArg:
    RULE = "unhashable-static-arg"

    def test_list_annotated_static_argnum(self):
        fs = lint_rule(
            """
import jax

def step(x, shape: list):
    return x

run = jax.jit(step, static_argnums=(1,))
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_dict_default_static_argname(self):
        fs = lint_rule(
            """
import jax

def step(x, opts={"a": 1}):
    return x

run = jax.jit(step, static_argnames=("opts",))
""",
            self.RULE,
            # The snippet also trips mutable-default-arg; selecting one rule
            # keeps the assertion precise.
        )
        assert rules_of(fs) == [self.RULE]

    def test_static_name_matching_no_param(self):
        fs = lint_rule(
            """
import jax

def step(x):
    return x

run = jax.jit(step, static_argnames=("block_q",))
""",
            self.RULE,
        )
        assert "matches no parameter" in fs[0].message

    def test_hashable_static_is_fine(self):
        fs = lint_rule(
            """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("block_q", "interpret"))
def kernel(x, block_q: int = 128, interpret: bool = False):
    return x
""",
            self.RULE,
        )
        assert fs == []


# ---------------------------------------------------------- donation-after-use


class TestDonationAfterUse:
    RULE = "donation-after-use"

    def test_read_after_donating_call(self):
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv

step = jax.jit(impl, donate_argnums=(1,))

def drive(params, kv):
    out = step(params, kv)
    return out, kv.sum()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]
        assert "donated" in fs[0].message

    def test_donate_argnames_resolved_through_signature(self):
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv

step = jax.jit(impl, donate_argnames=("kv",))

def drive(params, kv):
    out = step(params, kv)
    log(kv)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_loop_reuse_without_rebind(self):
        # The donated buffer is read at the TOP of the next iteration.
        fs = lint_rule(
            """
import jax

def impl(kv):
    return kv

step = jax.jit(impl, donate_argnums=(0,))

def drive(kv, n):
    for _ in range(n):
        check(kv)
        out = step(kv)
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_rebind_is_the_blessed_pattern(self):
        # `logits, kv = step(kv)` — what the whole tree does.
        fs = lint_rule(
            """
import jax

def impl(params, kv):
    return kv, kv

step = jax.jit(impl, donate_argnums=(1,))

def drive(params, kv):
    for _ in range(8):
        logits, kv = step(params, kv)
    return logits
""",
            self.RULE,
        )
        assert fs == []

    def test_read_before_call_is_fine(self):
        fs = lint_rule(
            """
import jax

def impl(kv):
    return kv

step = jax.jit(impl, donate_argnums=(0,))

def drive(kv):
    check(kv)
    return step(kv)
""",
            self.RULE,
        )
        assert fs == []


# ----------------------------------------------------- unlocked-shared-mutation


class TestUnlockedSharedMutation:
    RULE = "unlocked-shared-mutation"

    POSITIVE = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def clear(self):
        self._items = []
"""

    def test_unlocked_mutation_of_guarded_attr(self):
        fs = lint_rule(self.POSITIVE, self.RULE)
        assert rules_of(fs) == [self.RULE]
        assert "_items" in fs[0].message

    def test_condition_counts_as_lock(self):
        fs = lint_rule(
            """
import threading

class Queue:
    def __init__(self):
        self._cv = threading.Condition()
        self._q = []

    def put(self, x):
        with self._cv:
            self._q.append(x)
            self._cv.notify()

    def drop_all(self):
        self._q.clear()
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_all_mutations_guarded_is_fine(self):
        fs = lint_rule(
            """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def clear(self):
        with self._lock:
            self._items = []
""",
            self.RULE,
        )
        assert fs == []

    def test_init_and_unguarded_attrs_exempt(self):
        # _threads is never lock-guarded anywhere -> single-owner state, not
        # flagged (the worker accept-loop pattern).
        fs = lint_rule(
            """
import threading

class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self._conns = set()
        self._threads = []

    def accept(self, c, t):
        with self._lock:
            self._conns.add(c)
        self._threads.append(t)
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------ frame-field-drift


class TestFrameFieldDrift:
    RULE = "frame-field-drift"

    PROTO = """
def forward_frame(x, ranges, pos):
    header = {"ranges": ranges, "pos": pos}
    header["ghost"] = 1
    return Frame(3, header, payload=x)


def error_frame(msg):
    return Frame(6, {"error": msg})
"""

    CLIENT = """
def unpack(frame):
    if "error" in frame.header:
        raise RuntimeError(frame.header["error"])
    h = frame.header
    return h["ranges"], h.get("pos"), h.get("phantom")
"""

    def _run(self, srcs):
        return engine.run_lint(
            list(srcs), select=[self.RULE], reader=lambda p: srcs[str(p)]
        )

    def test_pack_only_and_read_only_fields_flagged(self):
        res = self._run({"proto.py": self.PROTO, "client.py": self.CLIENT})
        flagged = {f.message.split("'")[1] for f in res.findings}
        assert flagged == {"ghost", "phantom"}

    def test_symmetric_contract_is_clean(self):
        res = self._run(
            {
                "proto.py": """
def forward_frame(x, pos):
    return Frame(3, {"pos": pos}, payload=x)
""",
                "client.py": """
def unpack(frame):
    return frame.header["pos"]
""",
            }
        )
        assert res.findings == []

    def test_rule_needs_a_proto_file(self):
        res = self._run({"client.py": self.CLIENT})
        assert res.findings == []

    def test_real_tree_contract_is_symmetric(self):
        repo = __import__("pathlib").Path(__file__).resolve().parent.parent
        res = engine.run_lint([repo / "cake_tpu"], select=[self.RULE])
        assert res.findings == [], [f.render() for f in res.findings]


# ---------------------------------------------------------- mutable-default-arg


class TestMutableDefaultArg:
    RULE = "mutable-default-arg"

    def test_list_default(self):
        fs = lint_rule("def f(x, acc=[]):\n    return acc\n", self.RULE)
        assert rules_of(fs) == [self.RULE]

    def test_dict_call_kwonly_default(self):
        fs = lint_rule(
            "def f(x, *, opts=dict()):\n    return opts\n", self.RULE
        )
        assert rules_of(fs) == [self.RULE]

    def test_none_default_is_fine(self):
        fs = lint_rule(
            """
def f(x, acc=None):
    acc = [] if acc is None else acc
    return acc
""",
            self.RULE,
        )
        assert fs == []

    def test_call_with_list_arg_is_not_a_default(self):
        # BatchResult(text="", token_ids=[]) at a CALL site is fine.
        fs = lint_rule("r = Result(text='', token_ids=[])\n", self.RULE)
        assert fs == []


# ---------------------------------------------------------- bare-except-swallow


class TestBareExceptSwallow:
    RULE = "bare-except-swallow"

    def test_except_exception_pass(self):
        fs = lint_rule(
            """
try:
    probe()
except Exception:
    pass
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_bare_except_continue(self):
        fs = lint_rule(
            """
while True:
    try:
        step()
    except:
        continue
""",
            self.RULE,
        )
        assert rules_of(fs) == [self.RULE]

    def test_narrow_except_pass_is_fine(self):
        # `except OSError: pass` around socket close is the tree's idiom.
        fs = lint_rule(
            """
try:
    sock.close()
except OSError:
    pass
""",
            self.RULE,
        )
        assert fs == []

    def test_logged_broad_except_is_fine(self):
        fs = lint_rule(
            """
try:
    step()
except Exception as e:
    log.debug("step failed: %s", e)
""",
            self.RULE,
        )
        assert fs == []


# ------------------------------------------------------------------- the tree


def test_every_shipped_rule_is_registered():
    names = {r["name"] for r in engine.rule_table()}
    assert names == {
        "host-sync-in-jit",
        "jit-in-hot-loop",
        "unhashable-static-arg",
        "donation-after-use",
        "unlocked-shared-mutation",
        "frame-field-drift",
        "mutable-default-arg",
        "bare-except-swallow",
    }
