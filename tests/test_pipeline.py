"""Pipeline-parallel tests on the 8-device virtual CPU mesh.

The contract under test is the reference's implicit oracle (SURVEY.md §4): a
topology-sharded run must produce EXACTLY the tokens of the single-host run. Here
the sharded run is the shard_map + ppermute stage pipeline instead of TCP workers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.pipeline import PipelineRunner, pad_stages
from cake_tpu.parallel.topology import Topology

MAX_SEQ = 96


@pytest.fixture(scope="module")
def cfg():
    return LlamaConfig.tiny(num_hidden_layers=6)


@pytest.fixture(scope="module")
def params(cfg):
    return M.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)


def greedy_tokens(cfg, step, n=6):
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    gen.add_message(Message.user("pipeline oracle test"))
    gen.generate(n)
    return gen.generated_token_ids


@pytest.fixture(scope="module")
def oracle_ids(cfg, params):
    return greedy_tokens(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32),
    )


def test_pad_stages_shapes_and_mask(params):
    stacked, valid = pad_stages(params["layers"], [(0, 2), (2, 5), (5, 6)])
    assert valid.shape == (3, 3)
    assert valid.tolist() == [
        [True, True, False],
        [True, True, True],
        [True, False, False],
    ]
    assert stacked["wq"].shape[0] == 3 and stacked["wq"].shape[1] == 3
    # Padded slots are zero.
    assert float(jnp.abs(stacked["wq"][0, 2]).max()) == 0.0
    np.testing.assert_array_equal(
        np.asarray(stacked["wq"][1, 0]), np.asarray(params["layers"]["wq"][2])
    )


@pytest.mark.parametrize(
    "boundaries",
    [
        [(0, 3), (3, 6)],               # equal 2-stage
        [(0, 2), (2, 5), (5, 6)],       # ragged 3-stage
        [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5), (5, 6)],  # 1 layer/stage, 6 devices
    ],
)
def test_pipeline_matches_local_oracle(cfg, params, oracle_ids, boundaries):
    runner = PipelineRunner(
        cfg, params, boundaries, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    assert greedy_tokens(cfg, runner) == oracle_ids


def test_pipeline_from_topology_stage_plan(cfg, params, oracle_ids):
    topo = Topology.from_dict(
        {
            "w1": {"host": "a:1", "layers": ["model.layers.0-1"]},
            "w2": {"host": "b:1", "layers": ["model.layers.3-4"]},
        }
    )
    stages = topo.stage_plan(cfg.num_hidden_layers)
    runner = PipelineRunner(
        cfg,
        params,
        [(s.lo, s.hi) for s in stages],
        max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    assert greedy_tokens(cfg, runner) == oracle_ids


def test_pipeline_logits_match_local_forward(cfg, params):
    """Bit-level check at the logits (not just argmax) for one prefill+decode."""
    runner = PipelineRunner(
        cfg, params, [(0, 2), (2, 6)], max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    tokens = np.array([[5, 9, 100, 7]], np.int32)
    got_p = runner(tokens, 0, 4)
    got_d = runner(np.array([[42]], np.int32), 4, 1)

    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    want_p, kv = M.forward(
        params, jnp.asarray(tokens), kv, jnp.int32(0), jnp.int32(4), cfg
    )
    want_d, _ = M.forward(
        params, jnp.asarray([[42]]), kv, jnp.int32(4), jnp.int32(1), cfg
    )
    np.testing.assert_allclose(got_p, np.asarray(want_p), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(got_d, np.asarray(want_d), rtol=1e-5, atol=1e-5)


def test_pipeline_rejects_bad_boundaries(cfg, params):
    with pytest.raises(ValueError, match="cover"):
        PipelineRunner(cfg, params, [(0, 3)], max_seq_len=MAX_SEQ)
    with pytest.raises(ValueError, match="contiguous"):
        PipelineRunner(
            cfg, params, [(0, 2), (3, 6)], max_seq_len=MAX_SEQ
        )
    cfg12 = LlamaConfig.tiny(num_hidden_layers=12)
    params12 = M.init_params(cfg12, jax.random.PRNGKey(0), jnp.float32)
    with pytest.raises(ValueError, match="devices"):
        PipelineRunner(
            cfg12,
            params12,
            [(i, i + 1) for i in range(12)],  # 12 stages > 8 virtual devices
            max_seq_len=MAX_SEQ,
        )


def test_pipeline_reset_reproduces(cfg, params, oracle_ids):
    runner = PipelineRunner(
        cfg, params, [(0, 3), (3, 6)], max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    first = greedy_tokens(cfg, runner)
    second = greedy_tokens(cfg, runner)  # generator calls runner.reset()
    assert first == second == oracle_ids


def test_microbatched_prefill_matches_local_and_engages():
    """Long chunked prompt on the pipelined mesh: the GPipe-schedule prefill
    (all full chunks in one dispatch, overlapped across stages) must leave
    EXACTLY the KV the serial walk leaves — token streams equal the local
    oracle — and must actually be the path taken."""
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    prompt = "a long repetitive prompt " * 8  # >> 3 prefill chunks of 32
    max_seq = 384

    def run(step, spy=None):
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            prefill_chunk=32,
        )
        gen.add_message(Message.user(prompt))
        gen.generate(6)
        return gen.generated_token_ids

    local = run(
        LocalForwardStep(cfg, params, max_seq_len=max_seq, cache_dtype=jnp.float32)
    )
    runner = PipelineRunner(
        cfg, params, [(0, 2), (2, 4), (4, 6)],
        max_seq_len=max_seq, cache_dtype=jnp.float32,
    )
    calls = {"mb": 0}
    orig = runner.prefill_chunks

    def spy(tokens, pos0, chunk):
        calls["mb"] += 1
        return orig(tokens, pos0, chunk)

    runner.prefill_chunks = spy
    piped = run(runner)
    assert piped == local
    assert calls["mb"] == 1, "microbatched prefill path never engaged"


def test_microbatched_prefill_matches_on_stage_tp_mesh():
    """Microbatched prefill composed with tensor parallelism (stage x tp
    mesh): numerics still pinned to the local oracle."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=4, num_attention_heads=8, num_key_value_heads=4
    )
    params = M.init_params(cfg, jax.random.PRNGKey(6), jnp.float32)
    prompt = "tp stage mesh microbatch " * 8
    max_seq = 384

    def run(step):
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            prefill_chunk=32,
        )
        gen.add_message(Message.user(prompt))
        gen.generate(5)
        return gen.generated_token_ids

    local = run(
        LocalForwardStep(cfg, params, max_seq_len=max_seq, cache_dtype=jnp.float32)
    )
    runner = PipelineRunner(
        cfg, params, [(0, 2), (2, 4)], tp=2,
        max_seq_len=max_seq, cache_dtype=jnp.float32,
    )
    assert run(runner) == local
