"""Multi-file checkpoint IO: resolve -> mmap -> split -> serve, structurally
faithful to real multi-GB checkpoints (VERDICT r2 missing #1).

Real checkpoints ship as HF sharded indexes whose file boundaries cut across
layers, in bf16, sometimes with fused projections (Phi-3). The tiny fixtures
elsewhere write one file; these tests force the REAL layouts at reduced scale
(the full-size multi-GB run is cake_tpu/io/checkpoint_smoke.py, executed on
the build machine — see SMOKE.md for its recorded output).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.io.safetensors_io import (
    INDEX_FILE,
    load_params,
    resolve_checkpoint_files,
    save_sharded_checkpoint,
    save_tiny_checkpoint,
)

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def _greedy(cfg, step, prompt="sharded checkpoint oracle", n=6):
    gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
    gen.add_message(Message.user(prompt))
    gen.generate(n)
    return gen.generated_token_ids


def test_sharded_index_spans_files_and_loads_identically(tmp_path):
    """A bf16 multi-file index (shards small enough that one LAYER's tensors
    span several files) must resolve, mmap, and load to the same params as
    the single-file layout."""
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(41), jnp.float32)

    single = tmp_path / "single"
    sharded = tmp_path / "sharded"
    save_tiny_checkpoint(single, params, cfg)
    paths = save_sharded_checkpoint(
        sharded, params, cfg, max_shard_bytes=64 * 1024, dtype=jnp.float32
    )
    assert len(paths) > 4, "shards too few to span layer boundaries"
    assert resolve_checkpoint_files(sharded) == sorted(paths)
    # The index must actually scatter one layer's tensors over several files.
    weight_map = json.loads((sharded / INDEX_FILE).read_text())["weight_map"]
    layer0_files = {
        f for name, f in weight_map.items() if ".layers.0." in name
    }
    assert len(layer0_files) > 1, "layer 0 fits one shard; shrink max_shard_bytes"

    a = load_params(single, cfg, jnp.float32)
    b = load_params(sharded, cfg, jnp.float32)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a,
        b,
    )


def test_sharded_bf16_checkpoint_split_and_tcp_serve(tmp_path):
    """The documented deployment flow against a sharded bf16 index: split
    into per-worker reduced checkpoints, serve over live TCP workers, and
    match the local single-process oracle token-for-token."""
    from cake_tpu.io.splitter import split_model
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(42), jnp.float32)
    model_dir = tmp_path / "model"
    save_sharded_checkpoint(
        model_dir, params, cfg, max_shard_bytes=128 * 1024, dtype=jnp.bfloat16
    )
    # bf16 storage: the oracle loads the SAME sharded files so rounding
    # matches between the local and distributed runs.
    local_params = load_params(model_dir, cfg, jnp.float32)
    oracle = _greedy(
        cfg,
        LocalForwardStep(cfg, local_params, max_seq_len=96, cache_dtype=jnp.float32),
    )

    topo_dict = {
        "w1": {"host": "placeholder", "layers": ["model.layers.0-1"]},
        "w2": {"host": "placeholder", "layers": ["model.layers.2-3"]},
    }
    topo_path = tmp_path / "topology.yml"
    import yaml

    topo_path.write_text(yaml.safe_dump(topo_dict))
    topo = Topology.from_dict(topo_dict)
    split_dir = tmp_path / "split"
    split_model(model_dir, topo_path, split_dir)
    bundles = {
        name: split_dir / f"{name}-node" / "model" for name in ("w1", "w2")
    }
    for worker_dir in bundles.values():
        assert (worker_dir / "config.json").exists()
        assert resolve_checkpoint_files(worker_dir)

    workers = []
    try:
        for name in ("w1", "w2"):
            w = Worker(
                name, bundles[name], topo, ("127.0.0.1", 0),
                dtype=jnp.float32, max_seq_len=96,
            )
            w.start()
            topo.nodes[name].host = f"127.0.0.1:{w.address[1]}"
            workers.append(w)
        # The master keeps the full (sharded) checkpoint for embed/head and
        # any locally-owned ranges; workers load their reduced bundles.
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=96
        )
        try:
            assert _greedy(cfg, step) == oracle
        finally:
            step.close()
    finally:
        for w in workers:
            w.stop()


def test_phi3_fused_sharded_index_matches_transformers(tmp_path):
    """A transformers-written SHARDED Phi-3 checkpoint (fused qkv/gate_up,
    real HF index produced by save_pretrained(max_shard_size=...)): the
    fused-split loader must cross file boundaries and match HF greedy."""
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")

    hf_cfg = transformers.Phi3Config(
        hidden_size=64, intermediate_size=128, vocab_size=512,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False, pad_token_id=0, bos_token_id=256,
        eos_token_id=260, attn_implementation="eager",
    )
    torch.manual_seed(7)
    hf_model = transformers.Phi3ForCausalLM(hf_cfg).eval().to(torch.float32)
    hf_model.save_pretrained(
        tmp_path, safe_serialization=True, max_shard_size="200KB"
    )
    assert (tmp_path / INDEX_FILE).exists(), "HF did not shard; shrink the cap"
    assert len(resolve_checkpoint_files(tmp_path)) > 1

    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv_init = __import__(
        "cake_tpu.models.llama.cache", fromlist=["init_cache"]
    ).init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    toks = list(prompt)
    kv = kv_init
    logits, kv = M.forward(
        params, jnp.asarray([toks], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(toks)), cfg,
    )
    ours = []
    pos = len(toks)
    for _ in range(12):
        nxt = int(jnp.argmax(logits, -1)[0])
        ours.append(nxt)
        logits, kv = M.forward(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1

    with torch.no_grad():
        out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=12, do_sample=False,
            pad_token_id=0,
        )
    want = out[0, len(prompt):].tolist()
    assert ours == want
