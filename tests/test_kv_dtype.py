"""Reduced-precision KV cache storage (--kv-dtype f8).

cache_dtype was always a first-class parameter on every backend; these tests
pin that float8_e4m3fn storage works as a drop-in — attention computes in
the activation dtype after an on-read upcast — and that the quality cost is
the expected e4m3 rounding of keys/values, nothing structural.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
F8 = jnp.float8_e4m3fn


def run_stream(cfg, params, cache_dtype, prompt="kv dtype", n=10, **gen_kw):
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=cache_dtype),
        ByteTokenizer(),
        GREEDY,
        **gen_kw,
    )
    gen.add_message(Message.user(prompt))
    gen.generate(n)
    return list(gen.generated_token_ids)


def test_f8_cache_generation_deterministic():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(100), jnp.float32)
    a = run_stream(cfg, params, F8)
    b = run_stream(cfg, params, F8)
    assert a == b
    assert all(0 <= t < cfg.vocab_size for t in a)


def test_f8_cache_quality_vs_f32_cache():
    """Prefill logits with an f8 cache must track the f32-cache model: the
    only error source is e4m3 rounding of stored K/V (~3 mantissa bits)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(101), jnp.float32)
    prompt = np.random.default_rng(2).integers(0, 256, (1, 48)).astype(np.int32)

    def all_logits(cache_dtype):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads,
            cfg.head_dim, cache_dtype,
        )
        lg, _ = M.forward_all_logits(
            params, jnp.asarray(prompt), kv, jnp.int32(0), cfg,
            cached_prefill=True,
        )
        return np.asarray(lg[0])

    lf, l8 = all_logits(jnp.float32), all_logits(F8)
    agreement = float((lf.argmax(-1) == l8.argmax(-1)).mean())
    assert agreement >= 0.7, agreement
    # Logit perturbation stays small relative to the logit scale.
    assert float(np.abs(lf - l8).mean()) <= 0.5 * float(np.abs(lf).mean())


def test_f8_cache_fused_matches_stepwise():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(102), jnp.float32)
    a = run_stream(cfg, params, F8, decode_chunk_size=1)
    b = run_stream(cfg, params, F8, decode_chunk_size=4)
    assert a == b


def test_f8_cache_tp_and_pipeline_match_local():
    from cake_tpu.parallel.pipeline import PipelineRunner
    from cake_tpu.parallel.tensor import TensorParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(103), jnp.float32)

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("f8 parallel"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=F8))
    got_tp = run(
        TensorParallelRunner(cfg, params, tp=2, max_seq_len=128, cache_dtype=F8)
    )
    got_pp = run(
        PipelineRunner(
            cfg, params, [(0, 2), (2, 4)], max_seq_len=128, cache_dtype=F8
        )
    )
    assert got_tp == want
    assert got_pp == want


def test_f8_cache_sp_matches_local():
    from cake_tpu.parallel.sequence import SequenceParallelRunner

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(104), jnp.float32)

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
        gen.add_message(Message.user("f8 sequence parallel run"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=F8))
    got = run(
        SequenceParallelRunner(
            cfg, params, sp=4, max_seq_len=256, cache_dtype=F8
        )
    )
    assert got == want


def test_f8_cache_pallas_kernels_match_xla(monkeypatch):
    """decode_attention and the chunk-prefill kernel upcast f8 cache blocks
    on-VREG; interpret-mode results must match the XLA path on the SAME f8
    cache contents."""
    from cake_tpu.ops.attention import gqa_attention_hm
    from cake_tpu.ops.pallas.decode_attention import decode_attention

    rng = np.random.default_rng(3)
    b, n_kv, seq, d, n_q = 1, 2, 256, 32, 4
    kc = jnp.asarray(rng.standard_normal((b, n_kv, seq, d)), jnp.float32).astype(F8)
    vc = jnp.asarray(rng.standard_normal((b, n_kv, seq, d)), jnp.float32).astype(F8)
    q = jnp.asarray(rng.standard_normal((b, 1, n_q, d)), jnp.bfloat16)
    lens = jnp.asarray([197], jnp.int32)
    got = np.asarray(
        decode_attention(q, kc, vc, lens, interpret=True), np.float32
    )
    qpos = jnp.broadcast_to(lens[:, None] - 1, (b, 1))
    kpos = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    kpos = jnp.where(kpos < lens[:, None], kpos, jnp.int32(2**30))
    want = np.asarray(gqa_attention_hm(q, kc, vc, qpos, kpos), np.float32)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=2e-2)


def test_f8_cache_engine_rows_match_serialized():
    """--kv-dtype f8 composes with --api-batch: engine rows equal the
    serialized generator over the same f8 cache dtype."""
    from cake_tpu.runtime.serving import BatchEngine

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(105), jnp.float32)
    want = run_stream(cfg, params, F8, prompt="engine f8", n=6)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=128, cache_dtype=F8,
        decode_chunk_size=4, admission_window=0.0,
    )
    eng.start()
    try:
        h = eng.submit([Message.user("engine f8")], 6, GREEDY)
        got = [t.id for t in h.tokens()]
    finally:
        eng.stop()
    assert got == want


def test_wider_kv_cache_upgrades_compute():
    """--kv-dtype f32 under bf16 activations must actually USE the extra
    precision: attention with an f32 cache differs from a bf16 cache run
    (the read path upgrades q instead of truncating the cache)."""
    from cake_tpu.ops.attention import gqa_attention_hm

    rng = np.random.default_rng(4)
    b, n_kv, seq, d, n_q = 1, 2, 64, 32, 4
    kf = jnp.asarray(rng.standard_normal((b, n_kv, seq, d)), jnp.float32)
    vf = jnp.asarray(rng.standard_normal((b, n_kv, seq, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, 1, n_q, d)), jnp.bfloat16)
    pos = jnp.broadcast_to(jnp.arange(seq)[None, :], (b, seq))
    qpos = jnp.full((b, 1), seq - 1, jnp.int32)
    full = gqa_attention_hm(q, kf, vf, qpos, pos)
    assert full.dtype == q.dtype  # contract: returns in q's dtype
    truncated = gqa_attention_hm(
        q, kf.astype(jnp.bfloat16), vf.astype(jnp.bfloat16), qpos, pos
    )
    # If the wide cache were truncated on read these would be identical.
    assert not np.array_equal(np.asarray(full), np.asarray(truncated))


def test_qwen3_head_dim_class_default():
    """A qwen3 config.json omitting head_dim gets the HF class default of
    128, not hidden_size // heads."""
    from cake_tpu.models.llama.config import LlamaConfig

    cfg = LlamaConfig.from_hf_dict(
        {"model_type": "qwen3", "hidden_size": 1024,
         "num_attention_heads": 16, "num_key_value_heads": 8}
    )
    assert cfg.head_dim == 128


def test_triple_composition_int4_f8_speculative_engine():
    """int4 weights + f8 KV cache + batched speculative decoding compose in
    the serving engine: the stream is byte-equal to the serialized generator
    under the SAME settings (each pair is pinned elsewhere; this pins the
    triple)."""
    from cake_tpu.ops.quant import quantize_params
    from cake_tpu.runtime.serving import BatchEngine

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = quantize_params(
        M.init_params(cfg, jax.random.PRNGKey(106), jnp.float32), "int4"
    )
    # Repetitive prompt: prompt-lookup drafts actually fire.
    prompt = "ab ab ab ab ab ab"
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=F8),
        ByteTokenizer(),
        GREEDY,
        speculative_k=3,
    )
    gen.add_message(Message.user(prompt))
    gen.generate(10)
    want = list(gen.generated_token_ids)

    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=128, cache_dtype=F8,
        decode_chunk_size=4, admission_window=0.0, speculative_k=3,
    )
    eng.start()
    try:
        h = eng.submit([Message.user(prompt)], 10, GREEDY)
        got = [t.id for t in h.tokens()]
    finally:
        eng.stop()
    assert got == want
