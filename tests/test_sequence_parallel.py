"""Sequence-parallel serving (parallel/sequence.py): oracle vs local step.

Ring-attention prefill + sharded-KV distributed decode must reproduce the
single-device greedy token stream exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.sequence import SequenceParallelRunner

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def make(cfg, params, step):
    return LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)


@pytest.mark.parametrize("sp", [2, 8])
def test_sp_matches_local_oracle(sp):
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(9), jnp.float32)
    prompt = "sequence parallel oracle prompt with enough tokens to shard"

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(10)

    sp_step = SequenceParallelRunner(
        cfg, params, sp=sp, max_seq_len=256, cache_dtype=jnp.float32
    )
    gen = make(cfg, params, sp_step)
    gen.add_message(Message.user(prompt))
    gen.generate(10)
    assert gen.generated_token_ids == ref.generated_token_ids


def test_sp_decode_crosses_shard_boundary():
    """Generate enough tokens that decode writes cross a cache-shard boundary.

    max_seq 256 -> 8 shards x 32 slots: a ~40-token prompt + 30 generated
    tokens spans shards 0-2, exercising owner-only writes and the partial
    softmax combine with multiple populated shards.
    """
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(10), jnp.float32)
    prompt = "cross shard boundary generation test"

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(30)

    gen = make(
        cfg,
        params,
        SequenceParallelRunner(cfg, params, sp=8, max_seq_len=256, cache_dtype=jnp.float32),
    )
    gen.add_message(Message.user(prompt))
    gen.generate(30)
    assert gen.generated_token_ids == ref.generated_token_ids
    # Sanity: the run genuinely crossed shard 0's 32-slot window.
    assert len(gen._tokens) > 64


def test_sp_reset_reuses_runner():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(11), jnp.float32)
    step = SequenceParallelRunner(
        cfg, params, sp=4, max_seq_len=256, cache_dtype=jnp.float32
    )
    gen = make(cfg, params, step)
    gen.add_message(Message.user("first"))
    first = gen.generate(6)
    gen.reset()
    gen.add_message(Message.user("first"))
    assert gen.generate(6) == first


def test_sp_chunked_prefill_matches_one_shot():
    """prefill_chunk under sp: cache-prefix ring continuation chunks must
    reproduce the one-shot sp prefill AND the local oracle exactly."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(12), jnp.float32)
    prompt = "a deliberately long prompt so several continuation chunks run " * 2

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(8)

    for chunk in (16, 40):  # 40: chunk boundaries straddle shard windows
        step = SequenceParallelRunner(
            cfg, params, sp=4, max_seq_len=256, cache_dtype=jnp.float32
        )
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(), GREEDY, prefill_chunk=chunk
        )
        gen.add_message(Message.user(prompt))
        gen.generate(8)
        assert gen.generated_token_ids == ref.generated_token_ids, chunk


def test_sp_prefix_cache_multi_turn():
    """Prefix KV reuse over the sp runner: turn 2 prefills only the suffix via
    the chunk-continuation path, token stream unchanged."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(15), jnp.float32)

    def two_turns(prefix_cache):
        step = SequenceParallelRunner(
            cfg, params, sp=4, max_seq_len=256, cache_dtype=jnp.float32
        )
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(), GREEDY, prefix_cache=prefix_cache
        )
        user1 = Message.user("sequence parallel prefix reuse")
        gen.add_message(user1)
        gen.generate(6)
        reply = ByteTokenizer().decode(
            [t for t in gen.generated_token_ids if t not in cfg.eos_token_ids]
        )
        gen.reset()
        for m in (user1, Message.assistant(reply), Message.user("turn two")):
            gen.add_message(m)
        gen.generate(6)
        return list(gen.generated_token_ids), gen.last_prefill_tokens

    got, prefilled = two_turns(True)
    want, full = two_turns(False)
    assert got == want
    assert prefilled < full


@pytest.mark.parametrize("sp,tp", [(2, 2), (4, 2)])
def test_sp_tp_composition_matches_local_oracle(sp, tp):
    """2-D (sp, tp) mesh: sequence-sharded cache + head-sharded weights."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(16), jnp.float32)
    prompt = "two dimensional sp tp mesh oracle"

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(10)

    step = SequenceParallelRunner(
        cfg, params, sp=sp, tp=tp, max_seq_len=256, cache_dtype=jnp.float32
    )
    gen = make(cfg, params, step)
    gen.add_message(Message.user(prompt))
    gen.generate(10)
    assert gen.generated_token_ids == ref.generated_token_ids


def test_sp_tp_chunked_prefill_and_fused_decode():
    """sp x tp with prefill chunking and fused decode together."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(17), jnp.float32)
    prompt = "all the modes at once: chunked prefill, fused decode, sp x tp " * 2

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(8)

    step = SequenceParallelRunner(
        cfg, params, sp=2, tp=2, max_seq_len=256, cache_dtype=jnp.float32
    )
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), GREEDY, prefill_chunk=24, decode_chunk_size=4
    )
    gen.add_message(Message.user(prompt))
    gen.generate(8)
    assert gen.generated_token_ids == ref.generated_token_ids


def test_sp_pads_nondivisible_prefill_width():
    """sp=3: pow2 prompt buckets aren't divisible by 3 — the runner must pad
    the chunk internally and still match the oracle."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(13), jnp.float32)
    prompt = "non divisible width"

    ref = make(cfg, params, LocalForwardStep(cfg, params, max_seq_len=384, cache_dtype=jnp.float32))
    ref.add_message(Message.user(prompt))
    ref.generate(8)

    gen = make(
        cfg,
        params,
        SequenceParallelRunner(cfg, params, sp=3, max_seq_len=384, cache_dtype=jnp.float32),
    )
    gen.add_message(Message.user(prompt))
    gen.generate(8)
    assert gen.generated_token_ids == ref.generated_token_ids


def test_sp_fused_decode_matches_per_step():
    """decode_chunk on the sp runner: fused scan over the distributed step."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(14), jnp.float32)
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.1, repeat_last_n=8)
    outs = []
    for chunk in (1, 4):
        step = SequenceParallelRunner(
            cfg, params, sp=4, max_seq_len=256, cache_dtype=jnp.float32
        )
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(), s, decode_chunk_size=chunk
        )
        gen.add_message(Message.user("fused sp decode"))
        outs.append((gen.generate(9), list(gen.generated_token_ids)))
    assert outs[0] == outs[1]
