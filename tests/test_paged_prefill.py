"""Paged chunk-prefill kernel: interpret-mode kernel vs the gather twin vs
the dense chunk kernel, across the three shapes one arithmetic serves —
cold chunked prefill (q_starts = 0), cached-chunk suffix windows
(q_starts = start), and speculative-verify chunks at the shared slot.

Like tests/test_paged_attention.py, the load-bearing property is INDIRECTION
correctness: physical pages are deliberately scattered (LIFO free list hands
out high pages first), so a kernel that ignores its block table and reads
page 0 everywhere fails loudly here (the `prefetch-ref-unused` failure mode).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama.batch import prefill_positions, verify_positions
from cake_tpu.models.llama.paged_cache import PageAllocator
from cake_tpu.ops.pallas.chunk_prefill import chunk_prefill_attention
from cake_tpu.ops.pallas.paged_prefill import (
    paged_chunk_attention,
    paged_chunk_attention_xla,
    paged_kernel_supported,
)

B, N_Q, N_KV, HD = 3, 4, 2, 64
PS = 128  # kernel page size: the 128-lane tile
PER_SEQ = 3  # up to 3 pages per sequence -> 384 slots


def make_pool(alloc, seed=0, n_pages=12):
    rng = np.random.default_rng(seed)
    kp = jnp.asarray(rng.normal(size=(n_pages, N_KV, PS, HD)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_pages, N_KV, PS, HD)), jnp.float32)
    return kp, vp, rng


def cold_setup(seed=0, lengths=(160, 257, 40), pads=(3, 0, 10), n_pages=12):
    """A cold prefill shape: queries cover slots [0, W); every row's live
    window [pad, length) is mapped to deliberately out-of-order pages."""
    lengths = np.asarray(lengths, np.int32)
    pads = np.asarray(pads, np.int32)
    alloc = PageAllocator(n_pages, PS, B, PER_SEQ)
    for r in range(B):
        alloc.map_range(r, int(pads[r]), int(lengths[r]))
    kp, vp, rng = make_pool(alloc, seed, n_pages)
    w = int(lengths.max())
    q = jnp.asarray(rng.normal(size=(B, w, N_Q, HD)), jnp.float32)
    bt = jnp.asarray(alloc.block_tables)
    return q, kp, vp, bt, jnp.asarray(lengths), jnp.asarray(pads), w


def assert_live_close(got, want, lengths, pads, atol=2e-5):
    """Compare the VALID query rows only: slots outside [pad, length) are
    garbage nobody reads (the kernel zeroes them, the XLA twin computes
    clamped-position garbage — both contracts are 'finite, unread')."""
    got, want = np.asarray(got), np.asarray(want)
    lengths, pads = np.asarray(lengths), np.asarray(pads)
    for r in range(got.shape[0]):
        lo, hi = int(pads[r]), min(int(lengths[r]), got.shape[1])
        np.testing.assert_allclose(got[r, lo:hi], want[r, lo:hi], atol=atol)


def test_cold_chunk_matches_gather_twin():
    q, kp, vp, bt, lengths, pads, w = cold_setup()
    got = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        interpret=True,
    )
    q_pos, k_pos = prefill_positions(PER_SEQ * PS, pads, ends=lengths)
    want = paged_chunk_attention_xla(
        q, kp, vp, q_pos[:, :w], k_pos, bt
    )
    assert_live_close(got, want, lengths, pads)


def test_cold_chunk_matches_dense_chunk_kernel():
    # Three-way: paged kernel == dense chunk kernel fed the gathered view.
    from cake_tpu.models.llama.paged_cache import gather_pages

    q, kp, vp, bt, lengths, pads, w = cold_setup(seed=1)
    got = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        interpret=True,
    )
    dense_k = gather_pages(kp, bt)
    dense_v = gather_pages(vp, bt)
    want = chunk_prefill_attention(
        q, dense_k, dense_v, jnp.zeros((B,), jnp.int32), lengths,
        None, pads, interpret=True,
    )
    assert_live_close(got, want, lengths, pads)


def test_cached_chunk_matches_gather_twin():
    """Suffix/verify shape: a 16-wide window at absolute slot ``start``
    attends the whole live prefix, queries roped at their own slots."""
    lengths = np.asarray((200, 273, 216), np.int32)
    pads = np.asarray((3, 0, 10), np.int32)
    start = 200 - 16
    alloc = PageAllocator(12, PS, B, PER_SEQ)
    for r in range(B):
        alloc.map_range(r, int(pads[r]), int(lengths[r]))
    kp, vp, rng = make_pool(alloc, seed=2)
    w = 16
    q = jnp.asarray(rng.normal(size=(B, w, N_Q, HD)), jnp.float32)
    bt = jnp.asarray(alloc.block_tables)
    starts = jnp.full((B,), start, jnp.int32)
    lens = jnp.full((B,), start + w, jnp.int32)
    got = paged_chunk_attention(
        q, kp, vp, starts, lens, jnp.asarray(pads), bt, interpret=True
    )
    q_pos, k_pos, _ = verify_positions(
        w, jnp.asarray(pads), jnp.int32(start), PER_SEQ * PS
    )
    want = paged_chunk_attention_xla(q, kp, vp, q_pos, k_pos, bt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_physical_permutation_invariance():
    """The same logical tokens scattered across DIFFERENT physical pages
    must attend identically — the indirection is real."""
    q, kp, vp, bt, lengths, pads, w = cold_setup(seed=3)
    base = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        interpret=True,
    )
    # Permute physical pages and rewrite the tables to match.
    n_pages = kp.shape[0]
    perm = np.random.default_rng(7).permutation(n_pages)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(n_pages)
    kp2 = jnp.asarray(np.asarray(kp)[perm])
    vp2 = jnp.asarray(np.asarray(vp)[perm])
    bt2 = np.asarray(bt).copy()
    bt2[bt2 >= 0] = inv[bt2[bt2 >= 0]]
    moved = paged_chunk_attention(
        q, kp2, vp2, jnp.zeros((B,), jnp.int32), lengths, pads,
        jnp.asarray(bt2), interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(moved), atol=1e-6
    )


def test_window_prunes_and_masks_like_the_twin():
    q, kp, vp, bt, lengths, pads, w = cold_setup(seed=4)
    flag = jnp.ones((), bool)
    got = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        window_flag=flag, window=48, interpret=True,
    )
    q_pos, k_pos = prefill_positions(PER_SEQ * PS, pads, ends=lengths)
    want = paged_chunk_attention_xla(
        q, kp, vp, q_pos[:, :w], k_pos, bt, window=48, window_flag=flag
    )
    assert_live_close(got, want, lengths, pads)
    # Flag off = full causal, same knobs.
    off = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        window_flag=jnp.zeros((), bool), window=48, interpret=True,
    )
    full = paged_chunk_attention(
        q, kp, vp, jnp.zeros((B,), jnp.int32), lengths, pads, bt,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(off), np.asarray(full), atol=1e-6)


def test_dead_rows_and_unmapped_tails_are_finite_zero():
    """A row with length 0 (dead join lane) and unmapped tail pages must
    produce exact zeros for its masked queries — never NaN (0 * NaN would
    poison later layers)."""
    lengths = np.asarray((0, 257, 40), np.int32)
    pads = np.asarray((0, 0, 10), np.int32)
    alloc = PageAllocator(12, PS, B, PER_SEQ)
    for r in range(B):
        if lengths[r]:
            alloc.map_range(r, int(pads[r]), int(lengths[r]))
    kp, vp, rng = make_pool(alloc, seed=5)
    w = 64
    q = jnp.asarray(rng.normal(size=(B, w, N_Q, HD)), jnp.float32)
    bt = jnp.asarray(alloc.block_tables)
    out = np.asarray(
        paged_chunk_attention(
            q, kp, vp, jnp.zeros((B,), jnp.int32), jnp.asarray(lengths),
            jnp.asarray(pads), bt, interpret=True,
        )
    )
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out[0], 0.0)  # dead row: all-masked
    np.testing.assert_array_equal(out[2, :10], 0.0)  # pad queries


def test_untiled_page_size_is_refused_by_kernel():
    assert not paged_kernel_supported(96)
    assert paged_kernel_supported(256)
    kp = jnp.zeros((4, N_KV, 96, HD), jnp.float32)
    q = jnp.zeros((1, 8, N_Q, HD), jnp.float32)
    with pytest.raises(ValueError, match="128-lane"):
        paged_chunk_attention(
            q, kp, kp, jnp.zeros((1,), jnp.int32), jnp.full((1,), 8, jnp.int32),
            jnp.zeros((1,), jnp.int32), jnp.zeros((1, 2), jnp.int32),
            interpret=True,
        )


# --------------------------------------------------------------- integration
#
# The kernel family wired through the backend and engine: speculative verify
# under kv_mode="paged" (the capability gate is gone), the bounded epoch
# capacity (and the one-capacity trap it exists to avoid), and the pallas
# dispatch path end to end. Dense-vs-paged bit-identity for cold/warm/join/
# failover streams is pinned by tests/test_paged_serving.py,
# test_prefix_serving.py and test_chaos.py — all of which now run through
# these dispatches.

import time

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.batch_backend import PagedLocalBackend
from cake_tpu.runtime.serving import BatchEngine, ServeConfig
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 128
PAGE = 16  # small pages, NOT a lane-tile multiple: the XLA-twin path


@pytest.fixture(scope="module")
def model():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(43), jnp.float32)
    return cfg, params


def _engine(model, speculative_k=0, kv_mode="paged", max_seq=MAX_SEQ, **over):
    cfg, params = model
    kw = dict(
        max_batch=4, decode_chunk_size=4, admission_window=0.05,
        kv_mode=kv_mode,
    )
    if kv_mode == "paged":
        kw["page_size"] = over.pop("page_size", PAGE)
    kw.update(over)
    return BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=max_seq,
        cache_dtype=jnp.float32, speculative_k=speculative_k,
        serve=ServeConfig(**kw),
    )


def _run(eng, prompts, n, s=GREEDY):
    eng.start()
    try:
        handles = [eng.submit([Message.user(p)], n, s) for p in prompts]
        return [[t.id for t in h.tokens()] for h in handles]
    finally:
        eng.stop()


# Repetitive prompts: prompt lookup drafts verify at high rates on these.
SPEC_PROMPTS = ["abc abc abc abc abc abc", "q1 q1 q1 q1 q1 q1 q1"]


def test_paged_spec_greedy_identical_to_dense_spec_and_plain_paged(model):
    """Speculative verify RUNS under kv_mode="paged" (the capability gate
    is gone) and changes nothing: greedy paged-spec streams byte-match both
    the dense-spec streams (gather view ≡ dense arithmetic) and the plain
    paged streams (draft quality affects speed only)."""
    spec_eng = _engine(model, speculative_k=4)
    spec = _run(spec_eng, SPEC_PROMPTS, 16)
    assert spec_eng.stats["spec_rounds"] > 0
    assert spec == _run(_engine(model, speculative_k=4, kv_mode="dense"),
                        SPEC_PROMPTS, 16)
    assert spec == _run(_engine(model, speculative_k=0), SPEC_PROMPTS, 16)


def test_paged_spec_single_row_accepts_drafts(model):
    """One live row, chunk 1 (rounds attempted at every slot): paged verify
    must ACCEPT matching drafts — multi-token advances, not just byte-exact
    corrections."""
    eng = _engine(model, speculative_k=4, decode_chunk_size=1)
    spec = _run(eng, SPEC_PROMPTS[:1], 24)
    assert spec == _run(_engine(model, speculative_k=0), SPEC_PROMPTS[:1], 24)
    assert eng.stats["spec_rounds"] > 0
    assert eng.stats["spec_tokens"] > eng.stats["spec_rounds"]


def test_paged_spec_sampled_identical_to_dense_spec(model):
    """temperature > 0 through the paged verify: the vmapped rejection rule
    over the gather view is the dense arithmetic bit-for-bit, so per-seed
    streams match the dense speculative engine exactly."""
    s = SamplingConfig(temperature=0.9, top_k=12, repeat_penalty=1.0, seed=7)
    paged = _run(_engine(model, speculative_k=4), SPEC_PROMPTS, 12, s)
    dense = _run(_engine(model, speculative_k=4, kv_mode="dense"),
                 SPEC_PROMPTS, 12, s)
    assert paged == dense


def _wait_idle(eng, n_epochs, timeout=30.0):
    from cake_tpu.obs.timeline import timeline

    deadline = time.time() + timeout
    while time.time() < deadline:
        if sum(
            1 for e in timeline.snapshot() if e["name"] == "epoch"
        ) >= n_epochs:
            assert eng.quiesce(max(0.1, deadline - time.time()))
            return
        time.sleep(0.01)
    raise AssertionError("engine did not go idle")


def test_paged_spec_with_prefix_cache_warm_identical(model):
    """Spec + prefix cache + bounded capacity together: the warm round (every
    admission a chain hit, suffix-only prefill) speculates AND stays
    byte-identical to the cold round."""
    eng = _engine(model, speculative_k=4, prefix_cache=True)
    eng.start()
    try:
        rounds = []
        for r in range(2):
            handles = [
                eng.submit([Message.user(p)], 16, GREEDY)
                for p in SPEC_PROMPTS
            ]
            rounds.append([[t.id for t in h.tokens()] for h in handles])
            _wait_idle(eng, r + 1)
        cold, warm = rounds
    finally:
        eng.stop()
    assert warm == cold
    assert eng.stats["prefix_hits"] > 0
    assert eng.stats["spec_rounds"] > 0


def test_bounded_capacity_engages_and_streams_match_dense(model):
    """At max_seq 1024 a short-budget epoch must attend over the bucketed
    live capacity (256 slots), not the padded table width — and produce the
    exact dense streams while doing it."""
    cfg, params = model
    cfg_long = LlamaConfig.tiny(
        num_hidden_layers=2, max_position_embeddings=1024
    )
    eng = _engine((cfg_long, params), max_seq=1024)
    seen = []
    orig = eng.backend.set_epoch_capacity
    eng.backend.set_epoch_capacity = (
        lambda c: (seen.append(c), orig(c))[-1]
    )
    paged = _run(eng, ["a short prompt", "another short one"], 12)
    assert 256 in seen  # bucket + 12-token budget, 256-bucketed
    assert eng.backend._cap_pages is None  # reset at epoch end
    dense = _run(
        _engine((cfg_long, params), kv_mode="dense", max_seq=1024),
        ["a short prompt", "another short one"], 12,
    )
    assert paged == dense


def test_bounded_cap_epoch_refuses_join_it_would_truncate(model):
    """_take_joins prices waiting against what a SOLO epoch would deliver —
    min(max_tokens, max_seq - bucket), sized from the request's OWN budget —
    not this epoch's bounded cap. A high-budget request queued behind a
    short-budget epoch (cap 256 of max_seq 1024) must WAIT for its own
    epoch instead of joining and silently finishing "length" at the cap."""
    from cake_tpu.runtime.serving import StreamHandle, _Request

    cfg, params = model
    cfg_long = LlamaConfig.tiny(
        num_hidden_layers=2, max_position_embeddings=1024
    )
    eng = _engine((cfg_long, params), max_seq=1024)
    big = _Request(list(range(48)), 500, GREEDY, StreamHandle(48), rid="big")
    small = _Request(list(range(48)), 8, GREEDY, StreamHandle(48), rid="small")
    with eng._cv:
        eng._queue.extend([big, small])
    # A bounded short-budget epoch: cap 256, shared slot at 48, a free lane.
    taken = {
        r.rid
        for _, r in eng._take_joins(GREEDY.trace_knobs(), [object(), None],
                                    48, 256)
    }
    # Joining would cap big at ~208 tokens; waiting delivers all 500.
    assert "big" not in taken
    assert "small" in taken  # a small-budget joiner still fits this epoch
    assert [r.rid for r in eng._queue] == ["big"]


def test_one_capacity_mismatch_breaks_oracle(model):
    """THE documented trap: the same suffix window under a capacity that
    still covers the live prefix is bit-identical to the full table, but one
    page short of the live prefix silently TRUNCATES live keys — which is
    why the engine threads ONE capacity through suffix_prefill/suffix_join/
    migrate (a mismatch anywhere breaks the warm/cold identity chain)."""
    from cake_tpu.models.llama.batch import (
        paged_prefill,
        paged_suffix_prefill,
    )
    from cake_tpu.models.llama.paged_cache import init_paged_cache

    cfg, params = model
    alloc = PageAllocator(16, PAGE, batch=1, max_pages_per_seq=16)
    alloc.map_range(0, 0, 192)
    kv = init_paged_cache(
        cfg.num_hidden_layers, 16, cfg.num_key_value_heads, PAGE,
        cfg.head_dim, jnp.float32,
    )
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(1, 500, size=(1, 192)), jnp.int32)
    pads = jnp.zeros((1,), jnp.int32)
    tables = jnp.asarray(alloc.block_tables)
    _, kv = paged_prefill(params, tokens, kv, pads, tables, cfg)

    def suffix(tables_slice):
        # Re-score the last 16 prompt slots; write_starts=192 drops every
        # window write, so `kv` is reusable across calls.
        lg, _ = paged_suffix_prefill(
            params, tokens[:, 176:192], kv, pads,
            jnp.full((1,), 192, jnp.int32), tables_slice, cfg,
            jnp.int32(176),
        )
        return np.asarray(lg)

    full = suffix(tables)            # capacity 256 slots
    cover = suffix(tables[:, :12])   # capacity 192 — still covers the live prefix
    trunc = suffix(tables[:, :8])    # capacity 128 — truncates 64 live keys
    np.testing.assert_array_equal(full, cover)
    assert not np.allclose(full, trunc)


def test_write_past_epoch_capacity_fails_loudly(model):
    """A dispatch writing past the sliced table would DROP KV silently —
    the backend must refuse it instead."""
    cfg, params = model
    be = PagedLocalBackend(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32,
        page_size=PAGE,
    )
    kv = be.init_kv(2)
    be.set_epoch_capacity(64)
    assert be.capacity_slots() == 64
    with pytest.raises(ValueError, match="one-capacity"):
        be.prefill(np.zeros((2, 128), np.int32), kv, np.zeros((2,), np.int32))
    be.set_epoch_capacity(None)
    assert be.capacity_slots() == be.padded_seq


def test_kernel_fallback_flight_event_fires_once(model):
    """attention_impl=pallas over an untiled page size downgrades to the XLA
    twin — surfaced as ONE `kernel-fallback` flight event, not silence."""
    cfg, params = model
    cfg_p = LlamaConfig.tiny(num_hidden_layers=2, attention_impl="pallas")
    be = PagedLocalBackend(
        cfg_p, params, max_seq_len=128, cache_dtype=jnp.float32,
        page_size=PAGE,  # 16: not a 128-lane tile multiple
    )
    assert be.kernel_impl() == "fallback"
    kv = be.init_kv(1)
    be.allocator.map_range(0, 0, 32)
    tokens = np.zeros((1, 32), np.int32)
    for _ in range(2):
        _, kv = be.prefill(tokens, kv, np.zeros((1,), np.int32))
    events = [
        e for e in metrics.flight.snapshot()
        if e["event"] == "kernel-fallback"
    ]
    assert len(events) == 1
    # xla-by-choice is not a fallback: no event.
    metrics.flight.clear()
    be2 = PagedLocalBackend(
        cfg, params, max_seq_len=128, cache_dtype=jnp.float32, page_size=PAGE
    )
    assert be2.kernel_impl() == "xla"
    kv2 = be2.init_kv(1)
    be2.allocator.map_range(0, 0, 32)
    be2.prefill(tokens, kv2, np.zeros((1,), np.int32))
    assert not [
        e for e in metrics.flight.snapshot()
        if e["event"] == "kernel-fallback"
    ]


def test_pallas_paged_engine_cold_warm_identical(model):
    """The pallas dispatch end to end (interpret mode on CPU): a prefix-
    cache engine over 128-slot pages serves warm streams identical to cold
    ones — cold and warm walk the SAME paged chunk kernel, so the identity
    holds under pallas exactly as under the XLA twin."""
    cfg, params = model
    cfg_p = LlamaConfig.tiny(num_hidden_layers=2, attention_impl="pallas")
    eng = _engine(
        (cfg_p, params), max_seq=256, page_size=128, prefix_cache=True,
        max_batch=2,
    )
    assert eng.backend.kernel_impl() == "pallas"
    eng.start()
    try:
        rounds = []
        for r in range(2):
            h = eng.submit([Message.user("shared system prompt, again")],
                           8, GREEDY)
            rounds.append([t.id for t in h.tokens()])
            _wait_idle(eng, r + 1)
        cold, warm = rounds
    finally:
        eng.stop()
    assert warm == cold
    assert eng.stats["prefix_hits"] > 0
