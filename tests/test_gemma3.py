"""Gemma-3 (text) family: pinned against transformers.

Family deltas over Gemma-2 (HF modeling_gemma3): DUAL rope — sliding layers
rope at rope_local_base_freq (10k, unscaled), full-attention layers at
rope_theta (1M, with any linear rope_scaling) — selected per layer by the
``rope_sel`` layer metadata from stacked tables (ops/rope.model_rope_tables);
a 5:1 sliding:full layer_types pattern (win_flag from config, not parity);
per-head q/k RMSNorm in the Gemma (1+w) convention; no logit soft-caps.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.io.safetensors_io import load_params
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig

N_LAYERS = 7  # spans the 5:1 boundary: layers 0-4 sliding, 5 full, 6 sliding


def make_gemma3_checkpoint(tmp_path, seed=0, rope_scaling=None):
    hf_cfg = transformers.models.gemma3.Gemma3TextConfig(
        hidden_size=64,
        intermediate_size=128,
        vocab_size=512,
        num_hidden_layers=N_LAYERS,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        sliding_window=16,  # small: windowing visibly changes logits
        rope_theta=1000000.0,
        rope_local_base_freq=10000.0,
        rope_scaling=rope_scaling,
        max_position_embeddings=256,
        rms_norm_eps=1e-6,
        bos_token_id=256,
        eos_token_id=260,
        attention_bias=False,
        query_pre_attn_scalar=16,
    )
    torch.manual_seed(seed)
    model = (
        transformers.models.gemma3.Gemma3ForCausalLM(hf_cfg)
        .eval()
        .to(torch.float32)
    )
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


def ours_greedy(model_dir, prompt_ids, n_steps):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    return ours_greedy_params(cfg, params, prompt_ids, n_steps, max_seq=128)


def test_gemma3_config_parses(tmp_path):
    make_gemma3_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "gemma3_text"
    assert cfg.qk_norm and cfg.rmsnorm_offset
    assert cfg.rope_local_base_freq == 10000.0
    assert cfg.sliding_pattern is not None and len(cfg.sliding_pattern) == N_LAYERS
    assert cfg.sliding_pattern[5] is False  # every 6th layer full attention
    assert all(cfg.sliding_pattern[i] for i in (0, 1, 2, 3, 4, 6))
    assert cfg.post_block_norms and cfg.embedding_scale is not None
    assert cfg.attn_logit_softcap is None  # gemma3 dropped the soft-caps


def test_gemma3_layer_metadata_loaded(tmp_path):
    make_gemma3_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    lt = params["layers"]
    assert lt["q_norm"].shape == (N_LAYERS, 16)
    np.testing.assert_array_equal(
        np.asarray(lt["rope_sel"]), [1, 1, 1, 1, 1, 0, 1]
    )
    np.testing.assert_array_equal(
        np.asarray(lt["win_flag"]),
        [True, True, True, True, True, False, True],
    )
    # A worker's block range slices the pattern at ABSOLUTE layer indices.
    shard = load_params(tmp_path, cfg, jnp.float32, layer_range=(4, 7))
    np.testing.assert_array_equal(
        np.asarray(shard["layers"]["rope_sel"]), [1, 0, 1]
    )


def test_gemma3_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_gemma3_checkpoint(tmp_path, seed=21)
    # Prompt longer than the 16-token window so sliding layers truly window.
    prompt = [256] + [7, 301, 42, 9, 123, 77, 5, 88, 10, 400, 3, 64, 12, 205,
                      499, 31, 250, 17, 90, 110, 6, 45, 300, 2]
    want = hf_greedy(hf_model, prompt, 14)
    got = ours_greedy(tmp_path, prompt, 14)
    assert got == want


def test_gemma3_prefill_logits_match_transformers(tmp_path):
    hf_model = make_gemma3_checkpoint(tmp_path, seed=22)
    prompt = [256, 11, 205, 499, 3, 3, 64, 90, 17, 250, 31, 5, 77, 42, 301, 7,
              88, 10, 400, 12]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, 64, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )


def test_gemma3_linear_rope_scaling(tmp_path):
    """4B+-style linear rope_scaling on the GLOBAL rope only; the local rope
    stays unscaled (HF reassigns just the theta for the local embedding)."""
    hf_model = make_gemma3_checkpoint(
        tmp_path, seed=23, rope_scaling={"rope_type": "linear", "factor": 8.0}
    )
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.rope_scaling is not None and cfg.rope_scaling.rope_type == "linear"
    prompt = [256, 5, 77, 390, 12, 12, 9, 44, 71, 23, 150, 201, 33, 18, 6, 482,
              99, 3, 28, 55]
    want = hf_greedy(hf_model, prompt, 10)
    got = ours_greedy(tmp_path, prompt, 10)
    assert got == want


def test_gemma3_tp_and_pipeline_match_local(tmp_path):
    """Dual rope + pattern metadata ride the stacked layer trees: tp and the
    stage pipeline reproduce the local stream (rope_sel/win_flag replicate
    and stage-stack like any layer leaf)."""
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.generator import (
        LlamaGenerator,
        LocalForwardStep,
        SamplingConfig,
    )
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.pipeline import PipelineRunner
    from cake_tpu.parallel.tensor import TensorParallelRunner

    make_gemma3_checkpoint(tmp_path, seed=24)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
        gen.add_message(Message.user("gemma3 parallel backends"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    want = run(LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32))
    got_tp = run(
        TensorParallelRunner(cfg, params, tp=2, max_seq_len=128, cache_dtype=jnp.float32)
    )
    got_pp = run(
        PipelineRunner(
            cfg, params, [(0, 3), (3, 7)], max_seq_len=128, cache_dtype=jnp.float32
        )
    )
    assert got_tp == want
    assert got_pp == want


def test_gemma3_never_gets_rolling_cache(tmp_path):
    """--prefill-chunk on Gemma-3 must NOT enable the rolling ring cache:
    its every-6th full-attention layers need the whole key history, and a
    window-bounded ring would evict keys their (unwindowed) masks still
    admit — silently wrong long-prompt logits."""
    from cake_tpu.cli import build_parser, _build_master_step, _resolve_kv_dtype

    make_gemma3_checkpoint(tmp_path, seed=25)
    args = build_parser().parse_args(
        ["--model", str(tmp_path), "--prefill-chunk", "32", "--dtype", "f32"]
    )
    cfg = LlamaConfig.from_model_dir(tmp_path)
    step = _build_master_step(
        args, cfg, type("T", (), {"nodes": {}})(), jnp.float32, jnp.float32
    )
    from cake_tpu.models.llama.generator import LocalForwardStep

    assert isinstance(step, LocalForwardStep)
    assert step.rolling is False  # dense cache: full key history preserved


def test_gemma3_quantized_checkpoint_roundtrip(tmp_path):
    """Offline quantizer x Gemma-3: norms (incl. q/k norms) stay full
    precision, linears go int4, and the synthesized metadata (win_flag,
    rope_sel) regenerates from the config at load."""
    from cake_tpu.io.quantizer import quantize_checkpoint
    from cake_tpu.ops.quant import Quant4Weight, quantize_params

    make_gemma3_checkpoint(tmp_path / "src", seed=26)
    cfg = LlamaConfig.from_model_dir(tmp_path / "src")
    dst = quantize_checkpoint(
        tmp_path / "src", tmp_path / "q", "int4", dtype=jnp.float32
    )
    loaded = load_params(dst, cfg, jnp.float32)
    assert isinstance(loaded["layers"]["wq"], Quant4Weight)
    assert loaded["layers"]["q_norm"].dtype == jnp.float32  # unquantized
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"]["rope_sel"]), [1, 1, 1, 1, 1, 0, 1]
    )
    want = quantize_params(
        load_params(tmp_path / "src", cfg, jnp.float32), "int4"
    )
    got = ours_greedy_params(cfg, loaded, [256, 7, 301, 42], 8)
    ref = ours_greedy_params(cfg, want, [256, 7, 301, 42], 8)
    assert got == ref


def ours_greedy_params(cfg, params, prompt_ids, n_steps, max_seq=64):
    kv = init_cache(
        cfg.num_hidden_layers, 1, max_seq, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    logits, kv = fwd(
        params, jnp.asarray([prompt_ids], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(prompt_ids)), cfg,
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


def test_sliding_window_pattern_fallback():
    """A config.json with only sliding_window_pattern (no layer_types) — the
    real gemma-3-1b shape — derives the full-attention cadence from it."""
    cfg = LlamaConfig.from_hf_dict(
        {"model_type": "gemma3_text", "hidden_size": 64,
         "num_attention_heads": 4, "num_key_value_heads": 2,
         "num_hidden_layers": 8, "head_dim": 16,
         "sliding_window_pattern": 4}
    )
    assert cfg.sliding_pattern == (True, True, True, False) * 2
