"""1F1B interleaved pipelined decode (runtime/batch_backend.py).

Contract under test: with the batch split into S microbatch groups in
staggered flight, token streams are IDENTICAL to the serialized stage walk
(same per-row PRNG splits, penalty rings, slots), while the per-device
critical path per emitted token drops ~S-fold (each wall-step runs a
1/S-width group per stage instead of the whole batch on one stage).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import layout_prompts, seed_rings, first_sample
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.runtime.batch_backend import PipelineBatchBackend

S = 4  # stages
B = 8  # rows (2 per group)
MAX_SEQ = 64


@pytest.fixture(scope="module")
def setup():
    if jax.device_count() < S:
        pytest.skip(f"needs {S} devices")
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(21), jnp.float32)
    boundaries = [(i, i + 1) for i in range(4)]
    return cfg, params, boundaries


def _backend(setup, interleave):
    cfg, params, boundaries = setup
    return PipelineBatchBackend(
        cfg, params, boundaries, max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32, interleave=interleave,
    )


def _decode_both(setup, s: SamplingConfig, n: int = 5):
    """Prefill identically on both walks, decode n tokens, return streams."""
    cfg, params, boundaries = setup
    # Unequal prompt lengths exercise the per-row pads inside the groups.
    ids_list = [[7 + r, 3, 11 + r][: 2 + (r % 2)] for r in range(B)]
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    window = s.repeat_last_n
    keys0 = jax.random.split(jax.random.PRNGKey(5), B)

    outs = []
    for interleave in (False, True):
        be = _backend(setup, interleave)
        kv = be.init_kv(B)
        logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
        ring, ring_idx = seed_rings(ids_list, window)
        first, keys, ring, ring_idx = first_sample(
            logits, s, ring, ring_idx, keys0
        )
        toks, kv, keys, ring_j, ridx_j = be.decode(
            kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
            jnp.asarray(ring), jnp.asarray(ring_idx), n, s,
        )
        outs.append(
            (
                np.asarray(toks),
                np.asarray(ring_j),
                np.asarray(ridx_j),
                np.asarray(keys),
            )
        )
    return outs


def test_greedy_streams_identical(setup):
    (a, ra, ia, ka), (b, rb, ib, kb) = _decode_both(
        setup, SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    )
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(ka, kb)  # PRNG carries advance identically


def test_sampled_streams_identical(setup):
    """temperature > 0 + repeat penalty + rings: the full sampling arithmetic
    must walk the same per-row streams on both schedules."""
    (a, ra, ia, ka), (b, rb, ib, kb) = _decode_both(
        setup,
        SamplingConfig(
            temperature=0.8, top_k=20, top_p=0.9,
            repeat_penalty=1.15, repeat_last_n=16,
        ),
    )
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(ra, rb)
    np.testing.assert_array_equal(ia, ib)
    np.testing.assert_array_equal(ka, kb)


def test_interleaved_routing_and_fallback(setup):
    """B % S != 0 or single keys must fall back to the serialized walk."""
    be = _backend(setup, True)
    assert be.interleave
    # 6 rows over 4 stages: fallback (no crash, serialized path).
    cfg, params, boundaries = setup
    ids_list = [[5, 3]] * 6
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    kv = be.init_kv(6)
    logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    ring, ring_idx = seed_rings(ids_list, 0)
    keys0 = jax.random.split(jax.random.PRNGKey(1), 6)
    first, keys, ring, ring_idx = first_sample(logits, s, ring, ring_idx, keys0)
    toks, *_ = be.decode(
        kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
        jnp.asarray(ring), jnp.asarray(ring_idx), 3, s,
    )
    assert np.asarray(toks).shape == (6, 3)


def test_scalar_ring_idx_accepted(setup):
    """Equal-length prompts may pass a SCALAR ring_idx (valid on the
    serialized walk, fused.py sample_step); the interleaved dispatch must
    broadcast it, not crash on the group row slice."""
    be = _backend(setup, True)
    ids_list = [[5, 3]] * B
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    kv = be.init_kv(B)
    logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
    s = SamplingConfig(temperature=0.7, repeat_penalty=1.1, repeat_last_n=8)
    ring, _ = seed_rings(ids_list, 8)
    keys0 = jax.random.split(jax.random.PRNGKey(2), B)
    first, keys, ring, _ = first_sample(logits, s, ring, np.zeros(B, np.int32), keys0)
    toks, kv, *_ = be.decode(
        kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
        jnp.asarray(ring), jnp.int32(1), 3, s,  # scalar ring_idx
    )
    assert np.asarray(toks).shape == (B, 3)
    assert "1f1b" in str(next(iter(be._decode_cache)))


def test_per_device_critical_path_drops(setup):
    """The measured step-count win: per-DEVICE compiled FLOPs for n decoded
    tokens. Serialized: every device's program walks n*S full-batch stage
    steps (S-1 idle per wall-step but the critical path pays the full-batch
    stage each step). 1F1B: (n*S + S - 1) wall-steps of 1/S-width group work.
    The per-device program cost must drop by ~S/(1 + 1/n) — here ~3x of the
    ideal 4."""
    cfg, params, boundaries = setup
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    n = 8
    costs = {}
    for interleave in (False, True):
        be = _backend(setup, interleave)
        kv = be.init_kv(B)
        pads = jnp.zeros((B,), jnp.int32)
        tok = jnp.zeros((B,), jnp.int32)
        keys = jax.random.split(jax.random.PRNGKey(0), B)
        ring = jnp.full((B, 0), -1, jnp.int32)
        ridx = jnp.zeros((B,), jnp.int32)
        if interleave:
            window = 0
            mapped = be._interleaved_body(n, window, s)

            def run(kv, tok, slot, pads, keys, ring, ridx, mapped=mapped, be=be):
                out, kv, kf, rf, xf = mapped(
                    be.stage_params, be.valid, be.head_params, tok, kv,
                    slot, pads, keys, ring, ridx,
                )
                return out[be.n_stages - 1], kv
        else:
            from cake_tpu.models.llama.fused import sampled_decode_scan

            def run(kv, tok, slot, pads, keys, ring, ridx, be=be):
                return sampled_decode_scan(
                    be._forward_one(pads), kv, tok, slot, keys, ring, ridx,
                    n_steps=n, temperature=0.0, top_k=None, top_p=None,
                    repeat_penalty=1.0,
                )[:2]

        # One fresh jit per interleave variant IS the experiment (comparing
        # compiled FLOPs across configs).
        lowered = jax.jit(run).lower(  # cake-lint: disable=jit-in-hot-loop
            kv, tok, jnp.int32(8), pads, keys, ring, ridx
        )
        analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, list):  # older jax returns one dict per device
            analysis = analysis[0]
        costs[interleave] = float(analysis["flops"])
    # Ideal ratio S / (1 + (S-1)/(n*S)) ~ 3.7 at S=4, n=8; require a solid
    # margin over half the ideal so compiler noise cannot flake the test.
    assert costs[True] < costs[False] / 2.0, costs


def test_engine_over_interleaved_matches_local(setup):
    """End-to-end: the continuous-batching engine over the 1F1B pipeline
    backend emits the same per-request streams as over the local backend."""
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.runtime.batch_backend import LocalBatchBackend
    from cake_tpu.runtime.serving import BatchEngine

    cfg, params, boundaries = setup
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

    def run_engine(backend):
        eng = BatchEngine(
            cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=3, max_batch=S,
            admission_window=0.05, backend=backend,
        )
        eng.start()
        try:
            handles = [
                eng.submit([Message.user(f"req {i} body")], 6, s)
                for i in range(S)
            ]
            return [[t.id for t in h.tokens()] for h in handles]
        finally:
            eng.stop()

    local = run_engine(
        LocalBatchBackend(
            cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        )
    )
    pipe = run_engine(_backend(setup, True))
    assert pipe == local
