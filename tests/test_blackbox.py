"""Black-box anomaly capture (obs/blackbox.py) + the doctor report.

Pins: the on-disk ring bound, the capture rate limit, the p99 x K outlier
trigger, the diagnose() cause mapping, a GOLDEN doctor report (the exact
rendered text for a fixed bundle — deliberate formatting changes must edit
the snapshot consciously), and the engine-side SLO-breach trigger wiring.
No jax needed for the unit half; the engine half uses the tiny model.
"""

import json
import os

import pytest

from cake_tpu.obs import blackbox as bb
from cake_tpu.obs.blackbox import BlackBox


def test_ring_bound_keeps_newest(tmp_path):
    box = BlackBox(str(tmp_path), keep=3, min_interval_s=0.0)
    paths = [
        box.capture("manual", f"req-{i}", extra={"i": i}) for i in range(6)
    ]
    assert all(p is not None for p in paths)
    on_disk = box.bundles()
    assert len(on_disk) == 3
    # The newest three survive, oldest deleted.
    kept = [json.load(open(p))["request_id"] for p in on_disk]
    assert kept == ["req-3", "req-4", "req-5"]
    assert not os.path.exists(paths[0])


def test_rate_limit_suppresses_and_counts(tmp_path):
    box = BlackBox(str(tmp_path), keep=8, min_interval_s=3600.0)
    assert box.capture("stall", "req-a") is not None
    assert box.capture("epoch-error", "req-b") is None  # inside the window
    assert box.stats()["captured"] == 1
    assert box.stats()["suppressed"] == 1
    assert len(box.bundles()) == 1


def test_p99_outlier_trigger(tmp_path):
    box = BlackBox(str(tmp_path), keep=4, p99_mult=3.0)
    for _ in range(40):
        assert not box.observe_latency(0.1)
    assert not box.observe_latency(0.2)   # 2x: inside the multiplier
    assert box.observe_latency(1.0)       # 10x the rolling p99
    off = BlackBox(str(tmp_path), keep=4, p99_mult=0.0)
    for _ in range(40):
        assert not off.observe_latency(100.0)  # trigger disabled


def test_bad_knobs_refused(tmp_path):
    with pytest.raises(ValueError):
        BlackBox(str(tmp_path), keep=0)
    with pytest.raises(ValueError):
        BlackBox(str(tmp_path), min_interval_s=-1)


# ------------------------------------------------------------- diagnose


def _bundle(reason="latency-outlier", phases=None, **kw):
    exp = None
    if phases is not None:
        from cake_tpu.obs import critpath

        exp = {
            "wall_s": sum(phases.values()),
            "phases": phases,
            "dominant": critpath.dominant(phases),
            "convoy_frac": 0.0,
            "coverage": 1.0,
        }
    b = {"schema": 1, "reason": reason, "request_id": "req-x",
         "explain": exp}
    b.update(kw)
    return b


def test_diagnose_cause_mapping():
    assert bb.diagnose(_bundle("stall"))["cause"] == "stall"
    assert bb.diagnose(
        _bundle("latency-outlier", {"stall": 2.0, "decode": 1.0})
    )["cause"] == "stall"  # stall-dominated attribution
    assert bb.diagnose(
        _bundle("latency-outlier", {"convoy": 2.0, "stall": 0.005})
    )["cause"] == "convoy"  # a stall residue must not steal the blame
    assert bb.diagnose(
        _bundle("latency-outlier", {"queue": 2.0, "decode": 1.0})
    )["cause"] == "queue"
    assert bb.diagnose(
        _bundle("slo-ttft", {"convoy": 2.0, "decode": 1.0})
    )["cause"] == "convoy"
    assert bb.diagnose(
        _bundle("latency-outlier", {"wire": 2.0, "decode": 1.0})
    )["cause"] == "wire"
    assert bb.diagnose(
        _bundle("latency-outlier", {"decode": 3.0, "queue": 1.0})
    )["cause"] == "compute"
    assert bb.diagnose(_bundle("failover"))["cause"] == "failover"
    assert bb.diagnose(_bundle("shed"))["cause"] == "shed"
    assert bb.diagnose(_bundle("manual"))["cause"] == "unknown"


GOLDEN_BUNDLE = {
    "schema": 1,
    "captured_wall": 1700000000.0,
    "reason": "stall",
    "request_id": "chatcmpl-golden",
    "_path": "/ring/bundle-1700000000-0001-stall.json",
    "explain": {
        "wall_s": 1.25,
        "phases": {"queue": 0.25, "decode": 0.5, "stall": 0.5},
        "dominant": "decode",
        "convoy_frac": 0.0,
        "coverage": 1.0,
    },
    "engine": {"batches": 3, "rows": 5, "joins": 1, "shed": 0,
               "stream_errors": 1, "epoch_stalls": 1},
    "pool": {"pages_total": 64, "pages_free": 60},
}

GOLDEN_REPORT = """\
cake-tpu doctor report
  bundle:   /ring/bundle-1700000000-0001-stall.json
  reason:   stall
  request:  chatcmpl-golden
  cause:    stall
  dominant: decode
  wall:     1250.00 ms  (convoy_frac 0.000, coverage 1.000)

  phase                  ms
  queue              250.00
  decode             500.00
  stall              500.00

  engine: batches=3  rows=5  joins=1  shed=0  stream_errors=1  epoch_stalls=1
  pool:   60/64 pages free

  likely: a backend dispatch made no progress within the watchdog \
bound (--epoch-stall); check worker/device health and the \
cake_epoch_stalls_total trend"""


def test_doctor_golden_report():
    assert bb.render_report(GOLDEN_BUNDLE) == GOLDEN_REPORT


def test_load_bundle_file_and_dir(tmp_path):
    box = BlackBox(str(tmp_path), keep=4, min_interval_s=0.0)
    box.capture("manual", "req-old")
    newest = box.capture("stall", "req-new")
    by_dir = bb.load_bundle(str(tmp_path))
    assert by_dir["request_id"] == "req-new"  # newest wins
    by_file = bb.load_bundle(newest)
    assert by_file["request_id"] == "req-new"
    with pytest.raises(FileNotFoundError):
        bb.load_bundle(str(tmp_path / "empty-never-made"))


def test_doctor_cli(tmp_path, capsys):
    from cake_tpu.cli import _doctor_main

    path = tmp_path / "bundle-1-0001-stall.json"
    path.write_text(json.dumps(GOLDEN_BUNDLE))
    assert _doctor_main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "cause:    stall" in out
    assert _doctor_main(["--json", str(path)]) == 0
    assert json.loads(capsys.readouterr().out.strip())["cause"] == "stall"
    assert _doctor_main([str(tmp_path / "missing.json")]) == 2


# ------------------------------------------------------------- engine wiring


def test_engine_slo_breach_captures_bundle(tmp_path):
    """A declared-but-impossible TTFT objective makes every finished
    request an SLO breach: the engine captures a doctor-ready bundle."""
    import jax
    import jax.numpy as jnp

    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.runtime.serving import (
        BatchEngine,
        SamplingConfig,
        ServeConfig,
    )

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=256,
        cache_dtype=jnp.float32,
        serve=ServeConfig(
            max_batch=2, decode_chunk_size=4,
            slo_ttft_ms=0.001,  # unmeetable: every request breaches
            blackbox_dir=str(tmp_path), blackbox_keep=4,
            blackbox_min_interval_s=0.0,
        ),
    )
    eng.start()
    try:
        h = eng.submit(
            [Message.user("breach")], 4,
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        )
        h.text()
        bundles = eng.blackbox.bundles()
        assert len(bundles) >= 1
        bundle = bb.load_bundle(bundles[-1])
        assert bundle["reason"] == "slo-ttft"
        assert bundle["request_id"] == h.request_id
        # The bundle is self-contained: attribution + engine + timeline.
        assert bundle["explain"]["phases"]["decode"] >= 0.0
        assert bundle["engine"]["batches"] >= 1
        assert bundle["timeline"], "no timeline slice captured"
        assert bb.diagnose(bundle)["cause"] in (
            "compute", "queue", "convoy", "wire",
        )
    finally:
        eng.stop()
