"""Goodput & hardware-efficiency ledger (obs/efficiency.py, ISSUE 16).

The contracts under test:

  * Accounting oracle: a hand-built dispatch timeline on an injectable
    clock lands in the buckets with CLOSED-FORM splits (prefill/pad,
    decode/convoy/dead-lane, spec accepted/wasted, stall, failover,
    restore re-prefill, derived host_gap) and the buckets sum EXACTLY to
    the wall between the first dispatch's start and the last dispatch's
    end — the >= 95% smoke gate exists only to absorb rounding.
  * Roofline: per-dispatch FLOPs/HBM-bytes follow the analytic model;
    MFU/MBU appear exactly when a device peak is known (flag or table),
    and the CPU path degrades to absolute achieved numbers.
  * Decision audit: action/cause vocabulary pinned to obs/taxonomy.py
    (drift raises), consecutive-identical ring dedupe, per-request
    retrieval — and the LIVE engine records the right causes under both
    schedulers (admit/defer on epoch, preempt/restore on continuous).
  * Per-tenant goodput attribution, unit and end-to-end.
  * `cake-tpu top` renders from canned snapshots (pure function) and
    `top --once` round-trips a live HTTP server and exits 0.
"""

from __future__ import annotations

import http.server
import json
import threading

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.obs import efficiency as eff
from cake_tpu.obs.taxonomy import (
    BUCKETS,
    DECISION_ACTIONS,
    DECISION_CAUSES,
    GOODPUT_BUCKETS,
    PHASES,
    TOKEN_CLASSES,
)
from cake_tpu.runtime.serving import BatchEngine, ServeConfig

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
SAMPLED = SamplingConfig(temperature=0.8, top_k=20, repeat_penalty=1.0, seed=7)


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def make_ledger(clock, **kw):
    kw.setdefault("peak_tflops", 1.0)  # flags skip the jax device probe
    kw.setdefault("peak_hbm_gbps", 1.0)
    return eff.EfficiencyLedger(time_fn=clock, **kw)


def setup_engine(**serve_kw):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("decode_chunk_size", 4)
    serve_kw.setdefault("admission_window", 0.05)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32,
        serve=ServeConfig(**serve_kw),
    )
    eng.start()
    return eng


def collect(handle):
    return [tok.id for tok in handle.tokens()]


# ---------------------------------------------------------- taxonomy shape


def test_registries_are_disjoint_enough_and_complete():
    assert set(GOODPUT_BUCKETS) <= set(BUCKETS)
    assert "host_gap" in BUCKETS and "pad" in BUCKETS
    assert "completed" in TOKEN_CLASSES
    # critpath re-exports the shared PHASES registry (one source of truth).
    from cake_tpu.obs import critpath

    assert critpath.PHASES is PHASES


# ------------------------------------------------------- accounting oracle


def test_step_sequence_oracle_closed_form():
    clock = Clock(100.0)
    led = make_ledger(clock)

    clock.t = 101.0  # dispatch 1: prefill 4 lanes x 8 wide, 20 own tokens
    led.note_prefill(1.0, lanes=4, width=8, own_tokens=20)
    clock.t = 103.5  # 0.5s idle gap, then a 2.0s decode chunk
    led.note_decode(2.0, lanes=4, n=4, live=3, consumed=10, slot=8)
    clock.t = 104.5  # back-to-back 1.0s spec round, 2 lanes k=3, 5 used
    led.note_spec(1.0, lanes=2, k=3, live=2, used=5, slot=8)
    clock.t = 105.75  # 0.25s gap, then a 1.0s watchdog-abandoned stall
    led.note_stall(1.0)
    clock.t = 106.25  # 0.5s failover re-prefill
    led.note_failover(0.5)
    clock.t = 107.25  # restore prefill: 1 lane x 16, 8 live history
    led.note_prefill(1.0, lanes=1, width=16, own_tokens=8, restore=True)

    snap = led.snapshot()
    b = snap["buckets"]
    # Closed-form splits. prefill: 20/32 of 1.0s. decode: 10/16 of 2.0s
    # consumed, live 12/16, dead lane 4/16. spec: width 4, 5/8 accepted,
    # live remainder wasted. restore: 8/16 redone, 8/16 pad.
    assert b["prefill"] == pytest.approx(0.625)
    assert b["decode"] == pytest.approx(1.25)
    assert b["convoy"] == pytest.approx(0.25)
    assert b["spec_accepted"] == pytest.approx(0.625)
    assert b["spec_wasted"] == pytest.approx(0.375)
    assert b["stall"] == pytest.approx(1.0)
    assert b["failover"] == pytest.approx(0.5)
    assert b["restore_prefill"] == pytest.approx(0.5)
    assert b["pad"] == pytest.approx(0.375 + 0.5 + 0.5)
    assert b["host_gap"] == pytest.approx(0.75)

    # The invariant: buckets sum to the measured device wall (first
    # dispatch start -> last dispatch end) BY CONSTRUCTION; the smoke
    # gate's 95% bound absorbs rounding only.
    assert snap["wall_s"] == pytest.approx(7.25)
    assert snap["accounted_s"] == pytest.approx(snap["wall_s"], rel=1e-6)
    assert snap["accounted_s"] >= 0.95 * snap["wall_s"]
    assert snap["device_s"] == pytest.approx(6.5)
    assert snap["dispatches"] == 6
    useful = sum(b[x] for x in GOODPUT_BUCKETS)
    assert snap["goodput_frac"] == pytest.approx(useful / 7.25, abs=1e-3)
    assert set(b) == set(BUCKETS)


def test_reset_restarts_the_accounting_window():
    clock = Clock(100.0)
    led = make_ledger(clock)
    clock.t = 103.0  # a 3s "compile-contaminated" warmup dispatch
    led.note_prefill(3.0, lanes=1, width=4, own_tokens=4)
    led.note_finish("t", "stop", 5)
    led.reset()
    clock.t = 110.0
    led.note_decode(1.0, lanes=1, n=4, live=1, consumed=4)
    snap = led.snapshot()
    assert snap["wall_s"] == pytest.approx(1.0)  # no gap back to warmup
    assert snap["dispatches"] == 1
    assert snap["buckets"]["prefill"] == 0.0
    assert snap["goodput_tokens"] == 0
    assert snap["tenants"] == {}


def test_zero_and_overflow_dispatches_stay_bounded():
    clock = Clock()
    led = make_ledger(clock)
    led.note_prefill(0.0, lanes=2, width=4, own_tokens=4)  # dropped
    assert led.snapshot()["dispatches"] == 0
    clock.t = 101.0
    # own_tokens over the window clamps: no negative pad.
    led.note_prefill(1.0, lanes=1, width=4, own_tokens=99)
    b = led.snapshot()["buckets"]
    assert b["prefill"] == pytest.approx(1.0)
    assert b["pad"] == pytest.approx(0.0)


# ----------------------------------------------------------------- roofline


def test_dispatch_model_matches_analytic_forms():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    clock = Clock()
    led = make_ledger(clock, config=cfg, peak_tflops=100.0,
                      peak_hbm_gbps=100.0)
    clock.t = 101.0
    led.note_prefill(1.0, lanes=1, width=8, own_tokens=8)
    # note_prefill models lanes*width positions over a causal window
    # (ctx_sum ~ width^2/2) with one logit position per lane.
    assert led.flops_total == pytest.approx(
        eff.dispatch_flops(cfg, 8, 32, 1)
    )
    assert led.hbm_bytes_total == pytest.approx(
        eff.dispatch_hbm_bytes(cfg, 8, 32, 1)
    )
    snap = led.snapshot()
    assert snap["roofline"]["source"] == "flag"
    assert snap["roofline"]["mfu"] == pytest.approx(
        led.flops_total / 1.0 / (100.0 * 1e12), abs=1e-6
    )
    assert "achieved_tflops" in snap["model"]


def test_cpu_reports_absolute_numbers_only():
    # No flags and no TPU table entry for the CPU backend: the snapshot
    # carries achieved numbers but no mfu/mbu (nothing to divide by).
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    clock = Clock()
    led = eff.EfficiencyLedger(config=cfg, time_fn=clock)
    assert led.peak_source == "none"
    clock.t = 101.0
    led.note_decode(1.0, lanes=2, n=4, live=2, consumed=8, slot=4)
    roof = led.snapshot()["roofline"]
    assert roof["source"] == "none"
    assert "mfu" not in roof and "mbu" not in roof


# ----------------------------------------------------------- decision audit


def test_decision_audit_vocabulary_is_pinned():
    audit = eff.DecisionAudit()
    with pytest.raises(ValueError):
        # cake-lint: disable-next-line=taxonomy-drift (the point of the test)
        audit.record("evaporate", "fair_order")
    with pytest.raises(ValueError):
        # cake-lint: disable-next-line=taxonomy-drift (the point of the test)
        audit.record("admit", "because_reasons")
    audit.record("admit", "fair_order", rid="r1")
    assert audit.counts() == {"admit:fair_order": 1}
    assert set(DECISION_ACTIONS) >= {"admit", "defer", "preempt", "restore"}
    assert set(DECISION_CAUSES) >= {"page_pressure", "knob_incompatible"}


def test_decision_audit_dedupes_consecutive_but_counts_all():
    audit = eff.DecisionAudit(keep=8)
    for _ in range(5):  # a stuck verdict repeating every scheduler step
        audit.record("defer", "page_pressure", rid="r1")
    audit.record("defer", "page_pressure", rid="r2")
    audit.record("defer", "page_pressure", rid="r1")
    ring = audit.snapshot()
    assert [e["rid"] for e in ring] == ["r1", "r2", "r1"]
    assert audit.counts()["defer:page_pressure"] == 7
    assert [e["rid"] for e in audit.for_request("r1")] == ["r1", "r1"]


def test_decision_audit_ring_is_bounded():
    audit = eff.DecisionAudit(keep=4)
    for i in range(10):
        audit.record("admit", "fair_order", rid=f"r{i}")
    assert len(audit.snapshot()) == 4
    assert audit.snapshot(limit=2)[-1]["rid"] == "r9"


# ------------------------------------------------------------ token classes


def test_token_classes_and_tenant_attribution():
    led = make_ledger(Clock())
    led.note_finish("gold", "stop", 10)
    led.note_finish("gold", "length", 5)
    led.note_finish("gold", "cancelled", 3)
    led.note_finish("storm", "deadline", 2)
    led.note_finish("storm", "exploded", 1)  # unknown reason -> error
    led.note_finish("storm", "stop", 0)  # tokenless finish: no class
    snap = led.snapshot()
    assert snap["tokens"] == {
        "completed": 15, "cancelled": 3, "deadline": 2, "error": 1,
    }
    assert snap["goodput_tokens"] == 15
    assert snap["tenants"]["gold"] == {
        "goodput_tokens": 15, "wasted_tokens": 3,
    }
    assert snap["tenants"]["storm"] == {
        "goodput_tokens": 0, "wasted_tokens": 3,
    }


# ------------------------------------------------- live engine, both scheds


def test_epoch_engine_records_admit_and_knob_defer():
    eng = setup_engine(scheduler="epoch", admission_window=0.3)
    try:
        h1 = eng.submit([Message.user("first knobs")], 6, GREEDY)
        h2 = eng.submit([Message.user("other knobs")], 6, SAMPLED)
        collect(h1), collect(h2)
        counts = eng.audit.counts()
        assert counts.get("admit:fair_order", 0) >= 2
        # Incompatible sampling knobs in one admission window: the
        # non-head request defers with the structured cause.
        assert counts.get("defer:knob_incompatible", 0) >= 1
        deferred = eng.audit.for_request(h2.request_id)
        assert any(
            e["action"] == "defer" and e["cause"] == "knob_incompatible"
            for e in deferred
        ) or any(
            e["action"] == "defer" for e in eng.audit.for_request(
                h1.request_id
            )
        )
        # The ledger accounted the serve: goodput work + finished tokens.
        snap = eng.efficiency.snapshot()
        assert snap["dispatches"] > 0
        assert snap["buckets"]["decode"] > 0
        assert snap["goodput_tokens"] > 0
        assert snap["accounted_s"] >= 0.95 * snap["wall_s"]
    finally:
        eng.stop()


def test_continuous_engine_records_preempt_and_restore_causes():
    eng = setup_engine(
        scheduler="continuous", kv_mode="paged", page_size=16,
        max_pages=14,
    )
    try:
        prompts = [
            "alpha prompt padded out to be long " * 2,
            "row two also made quite long here " * 2,
        ]
        handles = [eng.submit([Message.user(p)], 48, GREEDY)
                   for p in prompts]
        for h in handles:
            collect(h)
        assert eng.quiesce()
        assert eng.stats["preemptions"] >= 1
        counts = eng.audit.counts()
        preempts = sum(
            n for k, n in counts.items()
            if k in ("preempt:page_pressure", "spill:page_pressure")
        )
        assert preempts >= 1
        assert counts.get("restore:fair_order", 0) >= 1
        # "why was this request preempted" is answerable per request id
        # (what GET /explain attaches for cake-tpu explain).
        assert any(
            any(e["action"] in ("preempt", "spill")
                for e in eng.audit.for_request(h.request_id))
            for h in handles
        )
        # Restore re-prefill is booked as redone work, not goodput.
        assert eng.efficiency.snapshot()["buckets"]["restore_prefill"] > 0
    finally:
        eng.stop()


def test_engine_tenant_goodput_end_to_end():
    eng = setup_engine(scheduler="continuous")
    try:
        h1 = eng.submit([Message.user("tenant a work")], 6, GREEDY,
                        tenant="a")
        h2 = eng.submit([Message.user("tenant b work")], 6, GREEDY,
                        tenant="b")
        collect(h1), collect(h2)
        tenants = eng.efficiency.snapshot()["tenants"]
        assert tenants["a"]["goodput_tokens"] > 0
        assert tenants["b"]["goodput_tokens"] > 0
        assert tenants["a"]["wasted_tokens"] == 0
    finally:
        eng.stop()


# ------------------------------------------------------------- cake-tpu top

CANNED_STATS = {
    "model": "tiny", "uptime_s": 12.5,
    "engine": {"scheduler": "continuous", "rows": 4, "joins": 2},
    "memwatch": {
        "host_rss_bytes": 2 * 2**30,
        "devices": [{
            "device": "TPU_0", "bytes_in_use": 2**30,
            "peak_bytes_in_use": 2 * 2**30, "bytes_limit": 4 * 2**30,
        }],
    },
}
CANNED_EFF = {
    "wall_s": 10.0, "accounted_s": 10.0, "device_s": 8.0,
    "dispatches": 42, "goodput_frac": 0.62, "goodput_tokens": 120,
    "buckets": {"decode": 5.0, "pad": 2.0, "host_gap": 2.0,
                "prefill": 1.0},
    "bucket_frac": {"decode": 0.5, "pad": 0.2, "host_gap": 0.2,
                    "prefill": 0.1},
    "tokens": {"completed": 120, "cancelled": 4, "deadline": 0,
               "error": 0},
    "tenants": {"gold": {"goodput_tokens": 120, "wasted_tokens": 4}},
    "decisions": {"admit:fair_order": 9, "defer:page_pressure": 2},
    "model": {"achieved_tflops": 0.01},
    "roofline": {"source": "flag", "peak_tflops": 100.0,
                 "peak_hbm_gbps": 100.0, "mfu": 0.41, "mbu": 0.55},
}
CANNED_SLO = {
    "tenants": {"gold": {"burn_rate": 0.5,
                         "fast": {"ttft_p99_s": 0.125}}},
}


def test_render_top_dashboard():
    from cake_tpu.cli import _render_top

    out = _render_top(CANNED_STATS, CANNED_EFF, CANNED_SLO)
    assert "scheduler=continuous" in out
    assert "goodput  62.0%" in out
    assert "mfu 0.410" in out and "mbu 0.550" in out
    assert "decode" in out and "50.0%" in out
    assert "completed=120" in out
    assert "gold" in out and "0.50" in out
    assert "admit:fair_order=9" in out
    assert "host_rss=2.00GiB" in out
    # Bucket rows are sorted by share, biggest first.
    assert out.index("decode") < out.index("pad")


def test_render_top_degrades_without_engine_blocks():
    from cake_tpu.cli import _render_top

    out = _render_top({"model": "tiny", "uptime_s": 1.0}, {}, {})
    assert "goodput" in out  # headline always renders


def test_top_once_against_live_http_server(capsys):
    from cake_tpu import cli

    routes = {
        "/stats": CANNED_STATS, "/efficiency": CANNED_EFF,
        "/slo": CANNED_SLO,
    }

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?")[0]
            if path not in routes:
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps(routes[path]).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # keep pytest output clean
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        rc = cli.main([
            "top", "--once",
            "--url", f"http://127.0.0.1:{srv.server_address[1]}",
        ])
    finally:
        srv.shutdown()
    assert rc == 0
    out = capsys.readouterr().out
    assert "goodput" in out and "mfu 0.410" in out


def test_top_once_poll_failure_exits_nonzero(capsys):
    from cake_tpu import cli

    with socketless_port() as port:
        rc = cli.main(
            ["top", "--once", "--url", f"http://127.0.0.1:{port}"]
        )
    assert rc == 1
    assert "poll" in capsys.readouterr().err


class socketless_port:
    """A port with nothing listening (bind-then-close)."""

    def __enter__(self):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def __exit__(self, *a):
        return False
