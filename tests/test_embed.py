"""Embeddable worker surface (cake_tpu/embed.py): one-call start_worker."""

import jax
import jax.numpy as jnp
import yaml

from cake_tpu import embed
from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.runtime.client import StageClient


def test_start_worker_nonblocking_serves(tmp_path):
    model_dir = tmp_path / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)

    topo_path = tmp_path / "topology.yml"
    topo_path.write_text(
        yaml.safe_dump(
            {
                "phone": {
                    "host": "127.0.0.1:0",
                    "description": "embedded worker",
                    "layers": ["model.layers.0-3"],
                }
            }
        )
    )

    worker = embed.start_worker(
        "phone", str(model_dir), str(topo_path), address="127.0.0.1:0", block=False
    )
    try:
        host, port = worker.address
        client = StageClient(f"{host}:{port}", "phone")
        assert client.info.ranges == [[0, 4]]
        assert client.ping() >= 0.0
        client.close()
    finally:
        worker.stop()
