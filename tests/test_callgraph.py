"""Call-graph resolution tests (cake_tpu/analysis/callgraph.py).

Multi-file snippet trees are fed through ``run_lint(reader=...)`` (no disk),
exactly like the frame-field-drift tests. The edge cases here are the ones
the cross-module jit rules lean on: aliased imports, re-exports through
``__init__.py``, recursion/cycles, and ``self.`` bound-method calls — each
as a positive (the sync IS found through the indirection) and a negative
(the resolution does not over-reach).
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import callgraph as cg
from cake_tpu.analysis import engine


def run_rule(srcs: dict[str, str], rule: str):
    res = engine.run_lint(
        list(srcs), select=[rule], reader=lambda p: srcs[str(p)]
    )
    return res.findings


def build_index(srcs: dict[str, str]) -> cg.ProjectIndex:
    ctxs = [
        engine.FileContext.parse(path, src) for path, src in srcs.items()
    ]
    return cg.ProjectIndex(ctxs)


# --------------------------------------------------------------- resolution


class TestResolution:
    def test_plain_from_import(self):
        index = build_index(
            {
                "pkg/a.py": "def f():\n    return 1\n",
                "pkg/b.py": "from pkg.a import f\n",
            }
        )
        mod_b = index.find_module(("pkg", "b"))
        info = index.resolve(mod_b, "f")
        assert info is not None and info.module.parts == ("pkg", "a")

    def test_aliased_import_module_and_symbol(self):
        index = build_index(
            {
                "pkg/a.py": "def f():\n    return 1\n",
                "pkg/b.py": "import pkg.a as aa\nfrom pkg.a import f as g\n",
            }
        )
        mod_b = index.find_module(("pkg", "b"))
        assert index.resolve(mod_b, "aa.f").qualname == "f"
        assert index.resolve(mod_b, "g").qualname == "f"

    def test_reexport_through_init(self):
        index = build_index(
            {
                "pkg/__init__.py": "from pkg.impl import f\n",
                "pkg/impl.py": "def f():\n    return 1\n",
                "user.py": "from pkg import f\n",
            }
        )
        user = index.find_module(("user",))
        info = index.resolve(user, "f")
        assert info is not None and info.module.parts == ("pkg", "impl")

    def test_relative_import(self):
        index = build_index(
            {
                "pkg/a.py": "def f():\n    return 1\n",
                "pkg/b.py": "from .a import f\n",
            }
        )
        mod_b = index.find_module(("pkg", "b"))
        info = index.resolve(mod_b, "f")
        assert info is not None and info.module.parts == ("pkg", "a")

    def test_external_name_resolves_to_nothing(self):
        index = build_index({"a.py": "import numpy as np\n"})
        mod = index.find_module(("a",))
        assert index.resolve(mod, "np.asarray") is None

    def test_import_cycle_terminates(self):
        # a re-exports from b which re-exports from a: resolution must not
        # recurse forever, and the symbol (defined nowhere) stays unresolved.
        index = build_index(
            {
                "pkg/a.py": "from pkg.b import ghost\n",
                "pkg/b.py": "from pkg.a import ghost\n",
            }
        )
        mod_a = index.find_module(("pkg", "a"))
        assert index.resolve(mod_a, "ghost") is None
        assert index.resolve_constant(mod_a, "ghost") is None

    def test_constant_through_import_chain(self):
        index = build_index(
            {
                "pkg/tensor.py": 'TP_AXIS = "tp"\n',
                "pkg/__init__.py": "from pkg.tensor import TP_AXIS\n",
                "user.py": "from pkg import TP_AXIS as AX\n",
            }
        )
        user = index.find_module(("user",))
        assert index.resolve_constant(user, "AX") == "tp"

    def test_method_resolution_with_base_class(self):
        index = build_index(
            {
                "m.py": (
                    "class Base:\n"
                    "    def helper(self):\n"
                    "        return 1\n"
                    "class Impl(Base):\n"
                    "    def run(self):\n"
                    "        return self.helper()\n"
                )
            }
        )
        mod = index.find_module(("m",))
        run = mod.functions["Impl.run"]
        call = next(
            n for n in ast.walk(run.node) if isinstance(n, ast.Call)
        )
        info = index.resolve_call(mod, run.node, call)
        assert info is not None and info.qualname == "Base.helper"


# ------------------------------------------------------------- reachability


class TestReachability:
    def test_recursion_terminates_and_includes_cycle(self):
        index = build_index(
            {
                "m.py": (
                    "def a():\n    return b()\n"
                    "def b():\n    return a()\n"
                )
            }
        )
        mod = index.find_module(("m",))
        reach = index.reachable([mod.functions["a"]])
        assert {i.qualname for i in reach.values()} == {"a", "b"}

    def test_nested_def_shadows_module_def(self):
        index = build_index(
            {
                "m.py": (
                    "def helper():\n    return 'module'\n"
                    "def root():\n"
                    "    def helper():\n"
                    "        return 'nested'\n"
                    "    return helper()\n"
                )
            }
        )
        mod = index.find_module(("m",))
        reach = index.reachable([mod.functions["root"]])
        nodes = [i.node for i in reach.values() if i.qualname == "helper"]
        assert len(nodes) == 1
        assert nodes[0] is not mod.functions["helper"].node


# ---------------------------------------- the rules that ride the call graph


class TestCrossModuleHostSync:
    RULE = "host-sync-in-jit"

    def test_sync_reachable_only_via_cross_module_helper(self):
        # The ISSUE 3 acceptance case: jit root in one module, the host
        # sync two modules away through an aliased import.
        fs = run_rule(
            {
                "pkg/step.py": (
                    "import jax\n"
                    "from pkg.mid import relay\n"
                    "@jax.jit\n"
                    "def step(x):\n"
                    "    return relay(x)\n"
                ),
                "pkg/mid.py": (
                    "from pkg.low import finish as fin\n"
                    "def relay(y):\n"
                    "    return fin(y)\n"
                ),
                "pkg/low.py": (
                    "import numpy as np\n"
                    "def finish(z):\n"
                    "    return np.asarray(z)\n"
                ),
            },
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert fs[0].path == "pkg/low.py"
        assert "np.asarray" in fs[0].message

    def test_self_method_chain_into_other_module(self):
        fs = run_rule(
            {
                "pkg/backend.py": (
                    "import jax\n"
                    "from pkg.util import pull\n"
                    "class Backend:\n"
                    "    def __init__(self):\n"
                    "        self._step = jax.jit(self._impl)\n"
                    "    def _impl(self, x):\n"
                    "        return self._finish(x)\n"
                    "    def _finish(self, x):\n"
                    "        return pull(x)\n"
                ),
                "pkg/util.py": (
                    "def pull(y):\n    return y.item()\n"
                ),
            },
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert fs[0].path == "pkg/util.py"

    def test_unjitted_cross_module_call_is_clean(self):
        # Same helper, but nothing jit-compiles the caller.
        fs = run_rule(
            {
                "pkg/step.py": (
                    "from pkg.low import finish\n"
                    "def host_side(x):\n"
                    "    return finish(x)\n"
                ),
                "pkg/low.py": (
                    "import numpy as np\n"
                    "def finish(z):\n"
                    "    return np.asarray(z)\n"
                ),
            },
            self.RULE,
        )
        assert fs == []

    def test_same_name_in_unrelated_module_not_reached(self):
        # step calls LOCAL helper; an unrelated module's helper with the
        # same name contains the sync and must not be dragged in.
        fs = run_rule(
            {
                "pkg/step.py": (
                    "import jax\n"
                    "def helper(x):\n"
                    "    return x + 1\n"
                    "@jax.jit\n"
                    "def step(x):\n"
                    "    return helper(x)\n"
                ),
                "pkg/other.py": (
                    "import numpy as np\n"
                    "def helper(z):\n"
                    "    return np.asarray(z)\n"
                ),
            },
            self.RULE,
        )
        assert fs == []


class TestCrossModuleDonation:
    RULE = "donation-after-use"

    def test_imported_donating_wrapper(self):
        fs = run_rule(
            {
                "pkg/backend.py": (
                    "import jax\n"
                    "def impl(params, kv):\n"
                    "    return kv\n"
                    "step = jax.jit(impl, donate_argnums=(1,))\n"
                ),
                "pkg/drive.py": (
                    "from pkg.backend import step\n"
                    "def drive(params, kv):\n"
                    "    out = step(params, kv)\n"
                    "    return out, kv.sum()\n"
                ),
            },
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert fs[0].path == "pkg/drive.py"

    def test_reexported_aliased_wrapper(self):
        fs = run_rule(
            {
                "pkg/__init__.py": "from pkg.backend import step\n",
                "pkg/backend.py": (
                    "import jax\n"
                    "def impl(kv):\n"
                    "    return kv\n"
                    "step = jax.jit(impl, donate_argnums=(0,))\n"
                ),
                "drive.py": (
                    "from pkg import step as fwd\n"
                    "def drive(kv):\n"
                    "    out = fwd(kv)\n"
                    "    return out, kv.sum()\n"
                ),
            },
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert fs[0].path == "drive.py"

    def test_rebind_through_import_is_clean(self):
        fs = run_rule(
            {
                "pkg/backend.py": (
                    "import jax\n"
                    "def impl(kv):\n"
                    "    return kv, kv\n"
                    "step = jax.jit(impl, donate_argnums=(0,))\n"
                ),
                "pkg/drive.py": (
                    "from pkg.backend import step\n"
                    "def drive(kv, n):\n"
                    "    for _ in range(n):\n"
                    "        logits, kv = step(kv)\n"
                    "    return logits\n"
                ),
            },
            self.RULE,
        )
        assert fs == []

    def test_function_local_wrapper_is_not_importable(self):
        # A wrapper bound inside a function in another module must not make
        # an identically-named import donate.
        fs = run_rule(
            {
                "pkg/backend.py": (
                    "import jax\n"
                    "def build():\n"
                    "    def impl(kv):\n"
                    "        return kv\n"
                    "    step = jax.jit(impl, donate_argnums=(0,))\n"
                    "    return step\n"
                ),
                "pkg/drive.py": (
                    "from pkg.elsewhere import step\n"
                    "def drive(kv):\n"
                    "    out = step(kv)\n"
                    "    return out, kv.sum()\n"
                ),
            },
            self.RULE,
        )
        assert fs == []
