"""Checkpoint IO tests: safetensors write/read round trip, layer-range loading."""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.io.safetensors_io import (
    load_params,
    open_checkpoint,
    resolve_checkpoint_files,
    save_tiny_checkpoint,
)
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig


def _write_tiny(tmp_path):
    cfg = LlamaConfig.tiny(num_hidden_layers=3)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    save_tiny_checkpoint(tmp_path / "model", params, cfg)
    return cfg, params


def test_roundtrip_full_params(tmp_path):
    cfg, params = _write_tiny(tmp_path)
    loaded = load_params(tmp_path / "model", cfg, jnp.float32)
    for path, a in jax.tree_util.tree_leaves_with_path(params):
        b = loaded
        for p in path:
            b = b[p.key] if hasattr(p, "key") else b[p.idx]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6, err_msg=str(path))


def test_layer_range_loading_matches_slice(tmp_path):
    cfg, params = _write_tiny(tmp_path)
    shard = load_params(tmp_path / "model", cfg, jnp.float32, layer_range=(1, 3))
    assert set(shard) == {"layers"}
    for k, w in shard["layers"].items():
        np.testing.assert_allclose(
            np.asarray(w), np.asarray(params["layers"][k][1:3]), atol=1e-6
        )


def test_index_file_resolution(tmp_path):
    cfg, _ = _write_tiny(tmp_path)
    files = resolve_checkpoint_files(tmp_path / "model")
    assert len(files) == 1
    # Removing the index must fall back to the single-file path (utils/mod.rs:32-39).
    (tmp_path / "model" / "model.safetensors.index.json").unlink()
    files2 = resolve_checkpoint_files(tmp_path / "model")
    assert files == files2


def test_reader_shapes_and_names(tmp_path):
    cfg, params = _write_tiny(tmp_path)
    r = open_checkpoint(tmp_path / "model")
    assert "model.embed_tokens.weight" in r
    assert r.shape("model.layers.0.self_attn.q_proj.weight") == (
        cfg.num_attention_heads * cfg.head_dim,
        cfg.hidden_size,
    )
    assert "model.layers.2.mlp.down_proj.weight" in r
    assert "model.layers.3.mlp.down_proj.weight" not in r


def test_config_loads_from_checkpoint_dir(tmp_path):
    cfg, _ = _write_tiny(tmp_path)
    cfg2 = LlamaConfig.from_model_dir(tmp_path / "model")
    assert cfg2 == cfg
