"""Interprocedural lock-set analysis tests (cake_tpu/analysis/locks.py and
the rules/lockorder.py pack).

Three layers, mirroring the analyzer's structure:

  * identity model — attr/global lock naming, ``Condition(self._lock)``
    aliasing, base-class ownership;
  * engagement pins over the REAL tree — the engine ``_cv`` ->
    prefix-cache-lock edge must appear in the lock-order graph (the
    acceptance shape: if attribute-type inference or the walker regress,
    this edge vanishes before any synthetic test notices), and the real
    tree must stay cycle-free;
  * rule positives/negatives — every lockorder rule has a snippet that
    fails if the rule is deleted (``select=`` raises on unknown names),
    including the cross-module ABBA cycle reported with BOTH witness
    paths.

Multi-file snippet trees go through ``run_lint(reader=...)`` (no disk),
the frame-field-drift/callgraph-test idiom. Stdlib-only; no jax.
"""

from __future__ import annotations

import pathlib

from cake_tpu.analysis import engine, lint_source
from cake_tpu.analysis import locks as la


def run_rule(srcs: dict[str, str], rule: str):
    res = engine.run_lint(
        list(srcs), select=[rule], reader=lambda p: srcs[str(p)]
    )
    return res.findings


def analyze(srcs: dict[str, str]) -> la.LockAnalysis:
    ctxs = [
        engine.FileContext.parse(path, src) for path, src in srcs.items()
    ]
    return la.analyze(ctxs)


def lint_rule(src: str, rule: str, path: str = "snippet.py"):
    return lint_source(src, path=path, select=[rule])


def id_strs(analysis: la.LockAnalysis) -> set[str]:
    return {str(i) for i in analysis.model.all_ids()}


def edge_strs(analysis: la.LockAnalysis) -> set[tuple[str, str]]:
    return {(str(a), str(b)) for (a, b) in analysis.edges}


# ------------------------------------------------------------ identity model


class TestLockIdentity:
    def test_attr_global_and_kind(self):
        analysis = analyze(
            {
                "pkg/mod.py": """
import threading

FLUSH_LOCK = threading.Lock()

class Pool:
    def __init__(self):
        self._lock = threading.RLock()
"""
            }
        )
        ids = id_strs(analysis)
        assert "pkg.mod.FLUSH_LOCK" in ids
        assert "pkg.mod.Pool._lock" in ids
        kinds = analysis.model.kinds
        by_str = {str(i): kinds[i] for i in analysis.model.all_ids()}
        assert by_str["pkg.mod.FLUSH_LOCK"] == "Lock"
        assert by_str["pkg.mod.Pool._lock"] == "RLock"

    def test_condition_wrapping_a_lock_aliases_to_it(self):
        # `Condition(self._lock)` is the SAME mutex: acquiring via either
        # name must be one graph node, or every wrapped-condition class
        # would report a self-cycle.
        analysis = analyze(
            {
                "pkg/mod.py": """
import threading

class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def run(self):
        with self._lock:
            pass
        with self._cv:
            pass
"""
            }
        )
        ids = id_strs(analysis)
        assert "pkg.mod.Engine._lock" in ids
        assert "pkg.mod.Engine._cv" not in ids
        assert analysis.cycles() == []

    def test_base_class_owns_the_identity(self):
        # A subclass method acquiring the base's lock and the base's own
        # methods must agree on one identity (same-module base chain).
        analysis = analyze(
            {
                "pkg/mod.py": """
import threading

class Base:
    def __init__(self):
        self._lock = threading.Lock()

class Child(Base):
    def poke(self):
        with self._lock:
            pass
"""
            }
        )
        ids = id_strs(analysis)
        assert "pkg.mod.Base._lock" in ids
        assert "pkg.mod.Child._lock" not in ids

    def test_order_edge_with_witness_site(self):
        analysis = analyze(
            {
                "pkg/mod.py": """
import threading

class Outer:
    def __init__(self):
        self._lock = threading.Lock()
        self._inner = Inner()

    def step(self):
        with self._lock:
            self._inner.bump()

class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def bump(self):
        with self._lock:
            pass
"""
            }
        )
        assert (
            "pkg.mod.Outer._lock",
            "pkg.mod.Inner._lock",
        ) in edge_strs(analysis)
        (ev,) = [
            analysis.witness(a, b)
            for (a, b) in analysis.edges
            if str(b) == "pkg.mod.Inner._lock"
        ]
        # The witness stack names the interprocedural path to the acquire.
        assert "Outer.step" in la.render_witness(ev)


# --------------------------------------------------- real-tree engagement pins


class TestRealTreeShape:
    """Acceptance pins over the actual cake_tpu tree: the analyzer must
    engage with the real runtime, not just synthetic snippets."""

    @staticmethod
    def _analysis() -> la.LockAnalysis:
        repo = pathlib.Path(__file__).resolve().parent.parent
        files = engine.collect_files([str(repo / "cake_tpu")])
        ctxs = [
            engine.FileContext.parse(str(f), f.read_text()) for f in files
        ]
        return la.lock_analysis(ctxs)

    def test_engine_cv_to_prefix_cache_lock_edge(self):
        # THE hierarchy edge: the batch engine holds its Condition while
        # touching the prefix-cache/page-allocator guard. It appears only
        # if `self._prefix = PrefixCache(...)` attribute-type inference
        # and held-set propagation both work on real code.
        analysis = self._analysis()
        edges = edge_strs(analysis)
        assert (
            "cake_tpu.runtime.serving.BatchEngine._cv",
            "cake_tpu.runtime.prefix_cache.PrefixCache._lock",
        ) in edges

    def test_identity_coverage_and_no_cycles(self):
        analysis = self._analysis()
        ids = id_strs(analysis)
        assert len(ids) >= 10
        # Representative spread across the trees the model must cover.
        assert "cake_tpu.runtime.serving.BatchEngine._cv" in ids
        assert "cake_tpu.utils.metrics.MetricsRegistry._lock" in ids
        assert "cake_tpu.obs.jitwatch._listener_lock" in ids
        assert analysis.cycles() == []

    def test_render_tree_is_the_readme_source(self):
        out = la.render_tree(self._analysis())
        assert "BatchEngine._cv" in out
        assert "PrefixCache._lock" in out


# ------------------------------------------------------------ lock-order-cycle


class TestLockOrderCycle:
    RULE = "lock-order-cycle"

    CYCLE_SRCS = {
        "pkg/a.py": """
import threading
from pkg import b

ALOCK = threading.Lock()

def forward():
    with ALOCK:
        b.inner()
""",
        "pkg/b.py": """
import threading

BLOCK = threading.Lock()

def inner():
    with BLOCK:
        pass

def backward():
    with BLOCK:
        outer()

def outer():
    from pkg.a import ALOCK
    with ALOCK:
        pass
""",
    }

    def test_cross_module_abba_reported_with_both_witness_paths(self):
        fs = run_rule(self.CYCLE_SRCS, self.RULE)
        assert [f.rule for f in fs] == [self.RULE]
        msg = fs[0].message
        # Both directions of the embrace, each with its own call path.
        assert "`pkg.a.ALOCK` then `pkg.b.BLOCK`" in msg
        assert "`pkg.b.BLOCK` then `pkg.a.ALOCK`" in msg
        assert "pkg.a.forward" in msg and "pkg.b.inner" in msg
        assert "pkg.b.backward" in msg and "pkg.b.outer" in msg

    def test_consistent_order_is_clean(self):
        srcs = {
            "pkg/a.py": """
import threading
from pkg import b

ALOCK = threading.Lock()

def forward():
    with ALOCK:
        b.inner()

def forward_again():
    with ALOCK:
        b.inner()
""",
            "pkg/b.py": """
import threading

BLOCK = threading.Lock()

def inner():
    with BLOCK:
        pass
""",
        }
        assert run_rule(srcs, self.RULE) == []

    def test_cycle_reported_once(self):
        # Two forward call sites must not duplicate the cycle finding.
        srcs = dict(self.CYCLE_SRCS)
        srcs["pkg/c.py"] = """
from pkg import a, b

def go():
    a.forward()
    b.backward()
"""
        fs = run_rule(srcs, self.RULE)
        assert len(fs) == 1


# ----------------------------------------------------- blocking-call-under-lock


class TestBlockingCallUnderLock:
    RULE = "blocking-call-under-lock"

    def test_sleep_under_lock(self):
        fs = lint_rule(
            """
import threading, time

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def spin(self):
        with self._lock:
            time.sleep(0.5)
""",
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert "time.sleep" in fs[0].message
        assert "snippet.W._lock" in fs[0].message

    def test_sleep_reached_through_cross_module_call(self):
        # The blocking call hides one module away: the lock is held in
        # a.py, the sleep lives in b.py — only held-set propagation
        # through the callgraph finds it.
        fs = run_rule(
            {
                "pkg/a.py": """
import threading
from pkg import b

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def spin(self):
        with self._lock:
            b.backoff()
""",
                "pkg/b.py": """
import time

def backoff():
    time.sleep(0.5)
""",
            },
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert fs[0].path == "pkg/b.py"
        assert "pkg.a.W.spin" in fs[0].message  # the witness path

    def test_own_condition_wait_is_not_blocking(self):
        # cv.wait() releases the condition's own lock while parked — the
        # canonical pattern, never a finding on its own.
        fs = lint_rule(
            """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()

    def pop(self):
        with self._cv:
            self._cv.wait(timeout=1.0)
""",
            self.RULE,
        )
        assert fs == []

    def test_wait_keeping_another_lock_held(self):
        fs = lint_rule(
            """
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()

    def pop(self):
        with self._lock:
            with self._cv:
                self._cv.wait(timeout=1.0)
""",
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert "snippet.Q._lock" in fs[0].message

    def test_sleep_outside_lock_is_clean(self):
        fs = lint_rule(
            """
import threading, time

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def spin(self):
        with self._lock:
            n = 1
        time.sleep(n)
""",
            self.RULE,
        )
        assert fs == []


# --------------------------------------------------------- callback-under-lock


class TestCallbackUnderLock:
    RULE = "callback-under-lock"

    def test_stored_callback_fired_under_lock(self):
        fs = lint_rule(
            """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()
        self._on_done = None

    def fire(self):
        with self._lock:
            self._on_done()
""",
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert "self._on_done" in fs[0].message

    def test_listener_loop_under_lock(self):
        fs = lint_rule(
            """
import threading

class Bus:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners = []

    def publish(self, ev):
        with self._lock:
            for cb in self._listeners:
                cb(ev)
""",
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]

    def test_snapshot_then_fire_outside_is_the_blessed_pattern(self):
        # The StreamHandle._emit idiom: copy under the lock, invoke after
        # release. Must stay clean or the whole tree lights up.
        fs = lint_rule(
            """
import threading

class Bus:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners = []

    def publish(self, ev):
        with self._lock:
            snapshot = list(self._listeners)
        for cb in snapshot:
            cb(ev)
""",
            self.RULE,
        )
        assert fs == []

    def test_resolvable_in_tree_method_is_not_a_callback(self):
        # A callbackish NAME that resolves to in-tree code is analyzed
        # interprocedurally instead of flagged — only opaque stored
        # callables are the re-entrancy vector.
        fs = lint_rule(
            """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def fire(self):
        with self._lock:
            self.on_done()

    def on_done(self):
        return None
""",
            self.RULE,
        )
        assert fs == []


# --------------------------------------------------------- notify-outside-lock


class TestNotifyOutsideLock:
    RULE = "notify-outside-lock"

    def test_unheld_notify_flagged_once(self):
        fs = lint_rule(
            """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()

    def kick(self):
        self._cv.notify_all()
""",
            self.RULE,
        )
        assert [f.rule for f in fs] == [self.RULE]
        assert "snippet.Q._cv" in fs[0].message

    def test_locked_helper_called_under_lock_is_clean(self):
        # Root-based held-set propagation: `_kick_locked` has an in-tree
        # caller that holds the lock, so it is analyzed only in that
        # context — no annotation needed.
        fs = lint_rule(
            """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()

    def push(self):
        with self._cv:
            self._kick_locked()

    def _kick_locked(self):
        self._cv.notify_all()
""",
            self.RULE,
        )
        assert fs == []

    def test_mixed_paths_flag_only_the_unheld_one(self):
        fs = lint_rule(
            """
import threading

class Q:
    def __init__(self):
        self._cv = threading.Condition()

    def kick(self):
        self._cv.notify_all()

    def push(self):
        with self._cv:
            self._cv.notify_all()
""",
            self.RULE,
        )
        assert len(fs) == 1
        assert fs[0].line == 9  # kick's notify, not push's


# -------------------------------------------------------------------- timings


def test_run_lint_records_phase_and_rule_timings():
    srcs = {"pkg/a.py": "import threading\nLOCK = threading.Lock()\n"}
    res = engine.run_lint(
        list(srcs),
        select=["lock-order-cycle"],
        reader=lambda p: srcs[str(p)],
    )
    names = [n for n, _ in res.timings]
    assert "(parse)" in names
    assert "(lock-walk)" in names  # shared snapshot, built once
    assert "lock-order-cycle" in names
    assert all(t >= 0 for _, t in res.timings)
