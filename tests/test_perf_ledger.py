"""Perf ledger (obs/perf_ledger.py): the BENCH_HISTORY.jsonl trajectory and
the noise-aware `cake-tpu benchdiff` regression gate."""

import json
import os

import pytest

from cake_tpu.obs import perf_ledger as pl


def test_append_history_stamps_rev_and_ts(tmp_path):
    path = tmp_path / "BENCH_HISTORY.jsonl"
    line = pl.append_history({"tok_s": 100.0, "unit": "tok/s"}, str(path))
    assert line["ts"] > 0
    # Two runs -> two lines, parseable, newest last.
    pl.append_history({"tok_s": 101.0}, str(path))
    rows = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["record"]["tok_s"] == 100.0
    assert rows[1]["record"]["tok_s"] == 101.0
    # This repo IS a git checkout: the revision stamp must resolve.
    assert pl.git_rev(os.path.dirname(os.path.abspath(__file__))) is not None


def test_bench_emit_appends_history(tmp_path, monkeypatch, capsys):
    """The satellite contract: bench.py's _emit funnel writes the ledger
    line for top-level (non-section-child) emits."""
    import bench

    monkeypatch.setenv("BENCH_JSON_PATH", str(tmp_path / "bench.json"))
    monkeypatch.setenv("BENCH_HISTORY_PATH", str(tmp_path / "hist.jsonl"))
    monkeypatch.delenv("BENCH_SECTIONS", raising=False)
    bench._emit(42.0, {"batch8_tok_s": 800.0})
    capsys.readouterr()
    rows = (tmp_path / "hist.jsonl").read_text().splitlines()
    assert len(rows) == 1
    rec = json.loads(rows[0])["record"]
    assert rec["value"] == 42.0
    assert rec["batch8_tok_s"] == 800.0
    # A section child must NOT append (it rolls up into the orchestrator).
    monkeypatch.setenv("BENCH_SECTIONS", "main")
    bench._emit(1.0, {})
    capsys.readouterr()
    assert len((tmp_path / "hist.jsonl").read_text().splitlines()) == 1


def test_diff_flags_20pct_regression():
    old = {"tok_s": 100.0, "prefill_tok_s": 20000.0, "compile_s": 5.0}
    new = {"tok_s": 80.0, "prefill_tok_s": 20100.0, "compile_s": 5.0}
    diff = pl.diff_records(old, new, pct=0.10)
    keys = [e["key"] for e in diff["regressions"]]
    assert keys == ["tok_s"]
    assert diff["regressions"][0]["delta_pct"] == pytest.approx(-20.0)
    # The 0.5% prefill wobble stays inside noise.
    assert any(e["key"] == "prefill_tok_s" for e in diff["unchanged"])


def test_diff_directions_and_floors():
    # Lower-better: compile time growing 30% regresses.
    diff = pl.diff_records({"compile_s": 5.0}, {"compile_s": 6.5})
    assert [e["key"] for e in diff["regressions"]] == ["compile_s"]
    # Higher-better improvement is not a regression.
    diff = pl.diff_records({"tok_s": 100.0}, {"tok_s": 130.0})
    assert not diff["regressions"]
    assert [e["key"] for e in diff["improvements"]] == ["tok_s"]
    # Abs floor: a 50% swing on a 0.01s compile key is sub-noise.
    diff = pl.diff_records({"compile_s": 0.01}, {"compile_s": 0.015})
    assert not diff["regressions"]
    # Unknown-direction keys inform, never gate.
    diff = pl.diff_records({"seed": 1.0}, {"seed": 9.0})
    assert not diff["regressions"] and diff["info"]
    # Keys on one side only are reported, not gated.
    diff = pl.diff_records({"tok_s": 1.0}, {"tok_s": 1.0, "new_tok_s": 2.0})
    assert [e["key"] for e in diff["missing"]] == ["new_tok_s"]


def test_nested_records_flatten():
    flat = pl.flatten_numeric(
        {"a": 1, "b": {"c": 2.0, "d": {"e": 3}}, "s": "x", "f": True}
    )
    assert flat == {"a": 1.0, "b.c": 2.0, "b.d.e": 3.0}


def test_benchdiff_cli_exit_codes(tmp_path, capsys):
    from cake_tpu.cli import _benchdiff_main

    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"tok_s": 100.0}))
    new.write_text(json.dumps({"tok_s": 80.0}))
    assert _benchdiff_main([str(old), str(new)]) == 1  # 20% regression
    out = capsys.readouterr().out
    assert "REGRESSIONS" in out and "tok_s" in out
    new.write_text(json.dumps({"tok_s": 99.0}))
    assert _benchdiff_main([str(old), str(new)]) == 0  # inside noise
    capsys.readouterr()
    assert _benchdiff_main([str(old), str(tmp_path / "nope.json")]) == 2
    # Ledger JSONL input: the last line's record is the comparand.
    hist = tmp_path / "hist.jsonl"
    pl.append_history({"tok_s": 100.0}, str(hist))
    pl.append_history({"tok_s": 50.0}, str(hist))
    assert _benchdiff_main([str(old), str(hist)]) == 1
    capsys.readouterr()


def test_load_record_shapes(tmp_path):
    j = tmp_path / "r.json"
    j.write_text(json.dumps({"tok_s": 5.0}))
    assert pl.load_record(str(j)) == {"tok_s": 5.0}
    hist = tmp_path / "h.jsonl"
    pl.append_history({"tok_s": 1.0}, str(hist))
    pl.append_history({"tok_s": 2.0}, str(hist))
    assert pl.load_record(str(hist)) == {"tok_s": 2.0}
