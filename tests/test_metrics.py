"""Metrics subsystem (utils/metrics.py): histograms/counters/gauges, the
flight recorder, and the request-scoped telemetry the BatchEngine records.

The acceptance contract (ISSUE 1): drive a request through BatchEngine and the
registry must hold TTFT / inter-token / queue-wait histograms for it, with a
non-empty flight-recorder timeline under that request's id.
"""

import json
import threading

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.serving import BatchEngine
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


# ---------------------------------------------------------------- histogram


def test_histogram_counts_sum_and_percentiles():
    h = metrics.Histogram("t_seconds", "test", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5):
        h.observe(v)
    (snap,) = h.snapshot()
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(0.605)
    # Rank arithmetic: p50 falls in the (0.01, 0.1] bucket, p99 in (0.1, 1.0].
    assert 0.01 <= snap["p50"] <= 0.1
    assert 0.1 < snap["p99"] <= 1.0
    # Percentile estimates never exceed the observed max.
    assert snap["p99"] <= 0.5


def test_histogram_overflow_bucket_reports_observed_max():
    h = metrics.Histogram("t_seconds", "test", buckets=(0.01,))
    h.observe(5.0)
    h.observe(7.5)
    assert h.percentile(99) == 7.5  # finite, not +Inf


def test_histogram_labels_are_separate_series():
    h = metrics.Histogram("hop_seconds", "test")
    h.observe(0.01, node="w1")
    h.observe(0.02, node="w1")
    h.observe(5.0, node="w2")
    snaps = {tuple(s["labels"].items()): s for s in h.snapshot()}
    assert snaps[(("node", "w1"),)]["count"] == 2
    assert snaps[(("node", "w2"),)]["count"] == 1


def test_histogram_empty_percentile_is_zero():
    h = metrics.Histogram("t_seconds", "test")
    assert h.percentile(99) == 0.0


def test_counter_monotonic_and_labelled():
    c = metrics.Counter("ops_total", "test")
    c.inc()
    c.inc(2, node="w1")
    assert c.value() == 1
    assert c.value(node="w1") == 2
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_set_inc_dec():
    g = metrics.Gauge("level", "test")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value() == 6


def test_registry_get_or_create_and_kind_conflict():
    reg = metrics.MetricsRegistry()
    a = reg.counter("x_total", "first")
    b = reg.counter("x_total", "second help ignored")
    assert a is b
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    reg.clear()
    assert reg.snapshot() == {"counters": [], "gauges": [], "histograms": []}


def test_registry_concurrent_observes():
    reg = metrics.MetricsRegistry()

    def work():
        for _ in range(300):
            reg.counter("n_total").inc()
            reg.histogram("h_seconds").observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(8)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert reg.counter("n_total").value() == 2400
    (snap,) = reg.histogram("h_seconds").snapshot()
    assert snap["count"] == 2400


# ---------------------------------------------------------------- exposition


def _parse_series(text: str) -> dict[str, float]:
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        name, val = line.rsplit(" ", 1)
        out[name] = float(val)
    return out


def test_exposition_histogram_buckets_cumulative_and_terminated():
    reg = metrics.MetricsRegistry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 50.0):
        h.observe(v)
    text = reg.expose()
    assert "# HELP lat_seconds latency" in text
    assert "# TYPE lat_seconds histogram" in text
    series = _parse_series(text)
    buckets = [
        series[f'lat_seconds_bucket{{le="{le}"}}']
        for le in ("0.01", "0.1", "1", "+Inf")
    ]
    assert buckets == sorted(buckets)  # cumulative => monotone
    assert buckets == [1, 2, 3, 4]
    assert buckets[-1] == series["lat_seconds_count"]  # +Inf == count
    assert series["lat_seconds_sum"] == pytest.approx(50.555)


def test_exposition_escapes_label_values():
    reg = metrics.MetricsRegistry()
    nasty = 'a\\b"c\nd'
    reg.counter("evil_total", "t").inc(node=nasty)
    text = reg.expose()
    assert '\\\\b' in text and '\\"c' in text and "\\nd" in text
    # A raw newline inside a label value would split the series line in two.
    for line in text.splitlines():
        if line.startswith("evil_total"):
            assert line.endswith(" 1")


def test_exposition_kinds_and_help():
    reg = metrics.MetricsRegistry()
    reg.counter("c_total", "a counter").inc()
    reg.gauge("g", "a gauge").set(2)
    reg.histogram("h_seconds", "a histogram").observe(0.5)
    text = reg.expose()
    assert "# TYPE c_total counter" in text
    assert "# TYPE g gauge" in text
    assert "# TYPE h_seconds histogram" in text
    assert "# HELP c_total a counter" in text


# ---------------------------------------------------------------- flight ring


def test_flight_recorder_ring_and_filter():
    fr = metrics.FlightRecorder(capacity=4)
    for i in range(6):
        fr.record("submitted", f"req-{i % 2}", seq=i)
    events = fr.snapshot()
    assert len(events) == 4  # bounded: newest capacity events win
    assert [e["seq"] for e in events] == [2, 3, 4, 5]
    only_zero = fr.snapshot(request_id="req-0")
    assert {e["request_id"] for e in only_zero} == {"req-0"}
    fr.clear()
    assert fr.snapshot() == []


def test_flight_recorder_dump_and_stream_jsonl(tmp_path):
    fr = metrics.FlightRecorder(capacity=8)
    fr.record("submitted", "req-a")
    dump = tmp_path / "dump.jsonl"
    assert fr.dump_jsonl(str(dump)) == 1
    (line,) = dump.read_text().splitlines()
    assert json.loads(line)["event"] == "submitted"

    stream = tmp_path / "stream.jsonl"
    fr.attach_jsonl(str(stream))
    fr.record("first-token", "req-a", ttft_s=0.5)
    fr.record("finished", "req-a")
    fr.attach_jsonl(None)
    fr.record("not-streamed", "req-a")
    lines = [json.loads(l) for l in stream.read_text().splitlines()]
    assert [e["event"] for e in lines] == ["first-token", "finished"]
    assert lines[0]["ttft_s"] == 0.5


# ------------------------------------------------- engine lifecycle telemetry


def test_batch_engine_records_request_scoped_telemetry():
    """ISSUE 1 acceptance: one request through BatchEngine must produce
    queue-wait / TTFT / inter-token observations and a flight timeline."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32,
        decode_chunk_size=4, admission_window=0.01,
    )
    eng.start()
    try:
        h = eng.submit(
            [Message.user("telemetry probe")], 8, GREEDY,
            request_id="req-probe",
        )
        assert h.request_id == "req-probe"
        tokens = list(h.tokens())
        assert len(tokens) >= 2  # inter-token needs at least two

        reg = metrics.registry
        for name in (
            "cake_queue_wait_seconds",
            "cake_ttft_seconds",
            "cake_inter_token_seconds",
        ):
            (snap,) = reg.histogram(name).snapshot()
            assert snap["count"] >= 1, name
        (itl,) = reg.histogram("cake_inter_token_seconds").snapshot()
        assert itl["count"] == len(tokens) - 1
        assert reg.counter("cake_engine_submitted_total").value() == 1
        assert reg.counter("cake_engine_admitted_total").value() == 1
        assert reg.counter("cake_engine_completed_total").value() == 1
        # TTFT covers submit -> first token, so it bounds queue wait.
        (ttft,) = reg.histogram("cake_ttft_seconds").snapshot()
        (qw,) = reg.histogram("cake_queue_wait_seconds").snapshot()
        assert ttft["sum"] >= qw["sum"]

        events = metrics.flight.snapshot(request_id="req-probe")
        assert [e["event"] for e in events] == [
            "submitted", "admitted", "first-token", "finished",
        ]
        assert events[0]["prompt_tokens"] == h.prompt_tokens
        assert events[-1]["finish_reason"] == h.finish_reason
        assert events[-1]["completion_tokens"] == len(tokens)
    finally:
        eng.stop()


def test_batch_engine_generates_request_id_when_absent():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32, admission_window=0.0,
    )
    eng.start()
    try:
        h = eng.submit([Message.user("anon")], 3, GREEDY)
        list(h.tokens())
        assert h.request_id.startswith("req-")
        assert metrics.flight.snapshot(request_id=h.request_id)
    finally:
        eng.stop()


def test_join_records_lifecycle_event():
    """A continuous-batching joiner gets a 'joined' (not 'admitted') event,
    and the joins counter tracks engine.stats."""
    import time as _time

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32,
        decode_chunk_size=2, admission_window=0.0,
    )
    eng.start()
    try:
        first = eng.submit(
            [Message.user("long running row for join headroom")], 24, GREEDY,
            request_id="req-first",
        )
        # Wait for the epoch to be live, then submit the joiner.
        deadline = _time.time() + 30
        while eng.stats["batches"] == 0 and _time.time() < deadline:
            _time.sleep(0.005)
        second = eng.submit(
            [Message.user("joiner")], 4, GREEDY, request_id="req-join"
        )
        list(first.tokens())
        list(second.tokens())
        if eng.stats["joins"]:  # joined the running epoch (the common path)
            events = [
                e["event"]
                for e in metrics.flight.snapshot(request_id="req-join")
            ]
            assert "joined" in events
            assert metrics.registry.counter(
                "cake_engine_joins_total"
            ).value() == eng.stats["joins"]
        else:  # epoch drained first: the joiner ran as its own epoch
            events = [
                e["event"]
                for e in metrics.flight.snapshot(request_id="req-join")
            ]
            assert "admitted" in events
    finally:
        eng.stop()
