"""Concurrent batched serving (runtime/serving.py + the API engine path).

The contract under test (VERDICT r1 #4): N concurrent clients each receive
correct, per-request-sampled output; requests actually batch (lockstep decode,
not serialization); and a row's stream is bit-identical to a single-request
run with the same seed regardless of batch composition.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.api import CHAT_ROUTE, ApiServer
from cake_tpu.runtime.serving import BatchEngine

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def single_row(cfg, params, prompt, n, sampling):
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
        ByteTokenizer(),
        sampling,
    )
    gen.add_message(Message.user(prompt))
    gen.generate(n)
    return list(gen.generated_token_ids), gen.last_finish_reason


def make_engine(cfg, params, **kw):
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("decode_chunk_size", 4)
    kw.setdefault("admission_window", 0.05)
    eng = BatchEngine(cfg, params, ByteTokenizer(), **kw)
    eng.start()
    return eng


def collect(handle):
    ids, text = [], []
    for tok in handle.tokens():
        ids.append(tok.id)
        text.append(tok.text)
    return ids, "".join(text)


def test_concurrent_greedy_rows_match_single_runs_and_batch():
    cfg, params = setup()
    eng = make_engine(cfg, params)
    prompts = ["alpha prompt", "row two is longer than row one", "c"]
    handles = [
        eng.submit([Message.user(p)], 8, GREEDY) for p in prompts
    ]
    got = [collect(h) for h in handles]
    for p, (ids, _text) in zip(prompts, got):
        want, _ = single_row(cfg, params, p, 8, GREEDY)
        assert ids == want, p
    # All three submissions landed within the admission window -> one batch.
    assert eng.stats["max_rows"] == 3
    assert eng.stats["batches"] == 1
    eng.stop()


def test_per_row_seeds_reproduce_single_request_streams():
    """Sampled rows with DIFFERENT seeds share one lockstep batch yet each
    reproduces its own single-request stream exactly (per-row PRNG keys)."""
    cfg, params = setup(seed=32)
    eng = make_engine(cfg, params)
    seeds = [7, 1234, 999]
    sampling = [
        SamplingConfig(temperature=0.8, top_k=20, repeat_penalty=1.0, seed=s)
        for s in seeds
    ]
    handles = [
        eng.submit([Message.user("same prompt for everyone")], 10, s)
        for s in sampling
    ]
    got = [collect(h)[0] for h in handles]
    assert eng.stats["max_rows"] == 3  # they really shared a batch
    for s, ids in zip(sampling, got):
        want, _ = single_row(cfg, params, "same prompt for everyone", 10, s)
        assert ids == want, f"seed {s.seed}"
    # Different seeds must actually diverge (sanity that sampling is live).
    assert len({tuple(g) for g in got}) > 1
    eng.stop()


def test_incompatible_knobs_split_batches():
    cfg, params = setup(seed=33)
    eng = make_engine(cfg, params)
    a = eng.submit([Message.user("greedy row")], 6, GREEDY)
    b = eng.submit(
        [Message.user("sampled row")],
        6,
        SamplingConfig(temperature=0.7, repeat_penalty=1.0, seed=5),
    )
    ids_a = collect(a)[0]
    ids_b = collect(b)[0]
    assert eng.stats["batches"] == 2  # knobs differ -> separate batches
    want_a, _ = single_row(cfg, params, "greedy row", 6, GREEDY)
    want_b, _ = single_row(
        cfg,
        params,
        "sampled row",
        6,
        SamplingConfig(temperature=0.7, repeat_penalty=1.0, seed=5),
    )
    assert ids_a == want_a
    assert ids_b == want_b
    eng.stop()


def test_per_row_max_tokens_and_overlength_prompt():
    cfg, params = setup(seed=34)
    eng = make_engine(cfg, params)
    short = eng.submit([Message.user("tiny")], 2, GREEDY)
    long = eng.submit([Message.user("tiny")], 9, GREEDY)
    done_at = {}

    def drain(name, handle, out):
        out[name] = [t.id for t in handle.tokens()]
        done_at[name] = time.perf_counter()

    out: dict = {}
    ts = [
        threading.Thread(target=drain, args=("short", short, out)),
        threading.Thread(target=drain, args=("long", long, out)),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=300)
    assert len(out["short"]) == 2 and short.finish_reason == "length"
    assert out["long"][:2] == out["short"]  # same row prefix, bigger budget
    # A finished row's stream closes immediately — it must not wait for the
    # slower row's lockstep lanes to drain.
    assert done_at["short"] <= done_at["long"]
    with pytest.raises(ValueError):
        eng.submit([Message.user("x" * 400)], 4, GREEDY)  # > max_seq_len=256
    eng.stop()


# --------------------------------------------------------------------- HTTP


@pytest.fixture(scope="module")
def batched_server():
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(35), jnp.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
    engine = BatchEngine(
        cfg,
        params,
        ByteTokenizer(),
        max_seq_len=256,
        cache_dtype=jnp.float32,
        decode_chunk_size=4,
        max_batch=8,
        admission_window=0.1,
    )
    api = ApiServer(gen, model_name="tiny-batched", engine=engine)
    httpd = api.make_server("127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield cfg, params, port, engine
    httpd.shutdown()
    engine.stop()


def _post(port, body, stream=False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{CHAT_ROUTE}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        if not stream:
            return json.loads(resp.read())
        chunks = []
        for line in resp:
            line = line.strip()
            if line.startswith(b"data: ") and line != b"data: [DONE]":
                chunks.append(json.loads(line[6:]))
        return chunks


def test_http_concurrent_streaming_clients(batched_server):
    cfg, params, port, engine = batched_server
    prompts = ["one fish", "two fish and some", "red", "blue fish"]
    before = engine.stats["batches"]
    results: dict[int, list] = {}
    errors: list = []

    def client(i, p):
        try:
            results[i] = _post(
                port,
                {"messages": [{"role": "user", "content": p}],
                 "max_tokens": 8, "stream": True},
                stream=True,
            )
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i, p))
        for i, p in enumerate(prompts)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert not errors
    assert len(results) == len(prompts)
    # Correctness per client: streamed text equals the single-request oracle.
    for i, p in enumerate(prompts):
        chunks = results[i]
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
            ByteTokenizer(),
            GREEDY,
        )
        gen.add_message(Message.user(p))
        want = gen.generate(8)
        assert text == want, p
        assert chunks[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    # They really were served as lockstep batches, not one-by-one.
    ran = engine.stats["batches"] - before
    assert ran < len(prompts)
    # /stats surfaces the engine's admission counters.
    stats = json.loads(
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30
        ).read()
    )
    assert stats["engine"]["batches"] >= 1


def test_http_nonstream_usage_and_aggregate_speedup(batched_server):
    """Aggregate concurrent throughput must beat serialized throughput.

    Measured on the same warm server: 4 sequential requests vs the same 4
    issued concurrently (one lockstep batch). Uses wall-clock with a
    comfortable margin; decode dominates with max_tokens=24 on the tiny model.
    """
    cfg, params, port, engine = batched_server
    body = {
        "messages": [{"role": "user", "content": "throughput probe"}],
        "max_tokens": 24,
    }
    _post(port, body)  # warm serial shape (B=1 prefill+decode compile)

    def burst(concurrent: bool) -> float:
        t0 = time.perf_counter()
        if not concurrent:
            for _ in range(4):
                _post(port, body)
        else:
            ts = [
                threading.Thread(target=_post, args=(port, body))
                for _ in range(4)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
        return time.perf_counter() - t0

    burst(True)  # warm the B=4 shapes (compile excluded from timing)
    # Timing contract with a bounded retry: concurrent join patterns are
    # timing-dependent, so a measured burst can hit a join width (B=2/3)
    # the warmups never produced and pay its one-off compile mid-burst —
    # observed once in-suite as concurrent 1.7s vs serial 0.5s while the
    # standalone run passed. A retry measures on now-warm shapes; a real
    # batching regression fails all three attempts.
    for _ in range(3):
        serial = burst(False)
        concurrent = burst(True)
        if concurrent < serial:
            break
    assert concurrent < serial, (concurrent, serial)
    resp = _post(port, body)
    usage = resp["usage"]
    assert usage["completion_tokens"] == 24
    assert usage["total_tokens"] == usage["prompt_tokens"] + 24


def test_late_request_joins_running_epoch_bit_exact():
    """Continuous batching: a request submitted while a batch is decoding
    joins at a chunk boundary (no waiting for the batch to drain) and its
    stream is bit-identical to its solo run."""
    cfg, params = setup(seed=41)
    eng = make_engine(cfg, params, max_batch=4, decode_chunk_size=2)
    try:
        first = eng.submit([Message.user("a long-running early request")], 40, GREEDY)
        # Wait until the epoch is demonstrably decoding, then submit late.
        deadline = time.time() + 30
        while not first.completion_tokens and time.time() < deadline:
            time.sleep(0.01)
        assert first.completion_tokens > 0  # the epoch is really decoding
        late = eng.submit([Message.user("late joiner")], 8, GREEDY)
        late_ids, _ = collect(late)
        first_ids, _ = collect(first)

        want_late, _ = single_row(cfg, params, "late joiner", 8, GREEDY)
        want_first, _ = single_row(
            cfg, params, "a long-running early request", 40, GREEDY
        )
        assert late_ids == want_late
        assert first_ids == want_first
        assert eng.stats.get("joins", 0) >= 1  # it joined, not a new batch
        assert eng.stats["batches"] == 1
    finally:
        eng.stop()


def test_freed_lane_is_reused_by_later_requests():
    """Rows that finish free their lane for later joiners within one epoch."""
    cfg, params = setup(seed=42)
    eng = make_engine(cfg, params, max_batch=2, decode_chunk_size=2)
    try:
        # Fill both lanes; short requests finish fast and free lanes.
        a = eng.submit([Message.user("anchor request running long")], 48, GREEDY)
        b = eng.submit([Message.user("short one")], 2, GREEDY)
        collect(b)  # b finishes, freeing its lane while a still runs
        c = eng.submit([Message.user("takes the freed lane")], 6, GREEDY)
        c_ids, _ = collect(c)
        a_ids, _ = collect(a)

        want_c, _ = single_row(cfg, params, "takes the freed lane", 6, GREEDY)
        want_a, _ = single_row(cfg, params, "anchor request running long", 48, GREEDY)
        assert c_ids == want_c
        assert a_ids == want_a
        assert eng.stats.get("joins", 0) >= 1
        assert eng.stats["batches"] <= 2  # c joined a's epoch (or b's lane)
    finally:
        eng.stop()


def test_sampled_late_join_reproducible():
    """Per-row PRNG independence holds across joins: a SAMPLED late joiner's
    stream equals its solo sampled run."""
    s = SamplingConfig(temperature=0.8, top_k=40, repeat_penalty=1.1, seed=77)
    cfg, params = setup(seed=43)
    eng = make_engine(cfg, params, max_batch=3, decode_chunk_size=2)
    try:
        anchor = eng.submit([Message.user("anchor sampled epoch runs a while")], 32, s)
        deadline = time.time() + 30
        while not anchor.completion_tokens and time.time() < deadline:
            time.sleep(0.01)
        late = eng.submit([Message.user("sampled late joiner")], 8, s)
        late_ids, _ = collect(late)
        collect(anchor)
        want, _ = single_row(cfg, params, "sampled late joiner", 8, s)
        assert late_ids == want
    finally:
        eng.stop()


# -------------------------------------------------- model-parallel backends


def _engine_tokens(cfg, params, backend, prompts, n=8, sampling=GREEDY):
    """Submit prompts to an engine over ``backend``; return per-prompt ids.
    (The staggered/JOIN scenario has its own dedicated test below.)"""
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32, decode_chunk_size=4,
        admission_window=0.05, backend=backend,
    )
    eng.start()
    try:
        handles = [eng.submit([Message.user(p)], n, sampling) for p in prompts]
        return [[t.id for t in h.tokens()] for h in handles]
    finally:
        eng.stop()


@pytest.mark.parametrize("kind", ["tp", "pipeline", "pipeline_tp"])
def test_engine_over_model_parallel_backends_token_exact(kind):
    """Continuous batching over tensor-parallel and pipelined backends: the
    engine's streams must be token-exact vs the single-device engine AND vs
    serialized single-request runs (VERDICT r2 #3 — batching and model
    parallelism are no longer mutually exclusive)."""
    from cake_tpu.runtime.batch_backend import (
        PipelineBatchBackend,
        TPBatchBackend,
    )

    cfg, params = setup(n_layers=4, seed=37)
    prompts = ["alpha row", "the second row is longer", "c row"]
    if kind == "tp":
        backend = TPBatchBackend(
            cfg, params, tp=2, max_seq_len=256, cache_dtype=jnp.float32
        )
    elif kind == "pipeline":
        backend = PipelineBatchBackend(
            cfg, params, [(0, 2), (2, 4)],
            max_seq_len=256, cache_dtype=jnp.float32,
        )
    else:
        backend = PipelineBatchBackend(
            cfg, params, [(0, 2), (2, 4)], tp=2,
            max_seq_len=256, cache_dtype=jnp.float32,
        )
    got = _engine_tokens(cfg, params, backend, prompts)
    for p, ids in zip(prompts, got):
        want, _ = single_row(cfg, params, p, 8, GREEDY)
        assert ids == want, (kind, p)


def test_engine_tp_backend_continuous_join_token_exact():
    """A request that JOINs a running epoch on the tensor-parallel backend
    (single-row sharded prefill scattered into a free lane) must still match
    its solo run exactly."""
    from cake_tpu.runtime.batch_backend import TPBatchBackend

    cfg, params = setup(n_layers=2, seed=38)
    backend = TPBatchBackend(
        cfg, params, tp=2, max_seq_len=256, cache_dtype=jnp.float32
    )
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=256, cache_dtype=jnp.float32, decode_chunk_size=4,
        admission_window=0.0, backend=backend,
    )
    eng.start()
    try:
        h0 = eng.submit([Message.user("long anchor request runs first")], 24, GREEDY)
        it0 = h0.tokens()
        first0 = next(it0)  # epoch is live
        h1 = eng.submit([Message.user("joiner")], 6, GREEDY)
        ids1 = [t.id for t in h1.tokens()]
        ids0 = [first0.id] + [t.id for t in it0]
    finally:
        eng.stop()
    want0, _ = single_row(cfg, params, "long anchor request runs first", 24, GREEDY)
    want1, _ = single_row(cfg, params, "joiner", 6, GREEDY)
    assert ids0 == want0
    assert ids1 == want1
    assert eng.stats["joins"] >= 1, "the joiner never joined the epoch"


def test_engine_backends_from_runner_token_exact():
    """The CLI's --api-batch adoption path: backends built via from_runner
    (adopting a live runner's placed shards, no second device_put) must be
    token-exact vs solo runs — pins what `--tp N --api-batch M` and
    `--backend mesh --api-batch M` actually construct."""
    from cake_tpu.parallel.pipeline import PipelineRunner
    from cake_tpu.parallel.tensor import TensorParallelRunner
    from cake_tpu.runtime.batch_backend import (
        PipelineBatchBackend,
        TPBatchBackend,
    )

    cfg, params = setup(n_layers=4, seed=39)
    prompts = ["adopted one", "the adopted second row"]
    runner_tp = TensorParallelRunner(
        cfg, params, tp=2, max_seq_len=256, cache_dtype=jnp.float32
    )
    runner_pipe = PipelineRunner(
        cfg, params, [(0, 2), (2, 4)], max_seq_len=256, cache_dtype=jnp.float32
    )
    for backend in (
        TPBatchBackend.from_runner(
            runner_tp, max_seq_len=256, cache_dtype=jnp.float32
        ),
        PipelineBatchBackend.from_runner(
            runner_pipe, max_seq_len=256, cache_dtype=jnp.float32
        ),
    ):
        got = _engine_tokens(cfg, params, backend, prompts)
        for p, ids in zip(prompts, got):
            want, _ = single_row(cfg, params, p, 8, GREEDY)
            assert ids == want, (type(backend).__name__, p)


def test_engine_sliding_window_family_matches_serialized():
    """The batch engine over a Mistral-style sliding-window model: lockstep
    streams equal the serialized generator's greedy streams (the window /
    per-row mask knobs thread through the batched bodies)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=3, sliding_window=24)
    params = M.init_params(cfg, jax.random.PRNGKey(61), jnp.float32)
    prompts = ["window test one", "w2"]
    want = [single_row(cfg, params, p, 8, GREEDY)[0] for p in prompts]

    eng = make_engine(cfg, params, max_batch=2, decode_chunk_size=3)
    try:
        handles = [eng.submit([Message.user(p)], 8, GREEDY) for p in prompts]
        got = [collect(h)[0] for h in handles]
    finally:
        eng.stop()
    assert got == want
    assert eng.stats["max_rows"] == 2  # the rows really decoded in lockstep


def test_engine_gemma2_alt_window_matches_serialized():
    """Gemma-2's alternating local/global window (win_flag layer metadata) +
    softcaps through the batch engine."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=4, model_type="gemma2", sliding_window=24,
        alt_sliding_window=True, rmsnorm_offset=True, post_block_norms=True,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        tie_word_embeddings=True, embedding_scale=8.0,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(62), jnp.float32)
    want = single_row(cfg, params, "gemma window", 8, GREEDY)[0]

    eng = make_engine(cfg, params, max_batch=2, decode_chunk_size=3)
    try:
        got = collect(eng.submit([Message.user("gemma window")], 8, GREEDY))[0]
    finally:
        eng.stop()
    assert got == want


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_engine_quantized_rows_match_serialized(mode):
    """--quantize int8/int4 composes with --api-batch: each engine row's
    greedy stream is byte-identical to the serialized generator over the
    SAME quantized weights (quantization happens before the backend split,
    so the lockstep and serialized paths share one representation)."""
    from cake_tpu.ops.quant import quantize_params

    cfg, params = setup()
    qparams = quantize_params(params, mode)
    prompts = ["quantized engine row a", "engine row b"]
    want = [single_row(cfg, qparams, p, 6, GREEDY)[0] for p in prompts]
    eng = make_engine(cfg, qparams)
    try:
        handles = [eng.submit([Message.user(p)], 6, GREEDY) for p in prompts]
        got = [[t.id for t in h.tokens()] for h in handles]
    finally:
        eng.stop()
    assert got == want


def test_engine_qwen3_family_matches_serialized():
    """Qwen3's per-head q/k norms through the batch engine: lockstep streams
    equal the serialized generator's."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=3, model_type="qwen3", qk_norm=True,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(63), jnp.float32)
    prompts = ["qwen3 engine one", "q2"]
    want = [single_row(cfg, params, p, 8, GREEDY)[0] for p in prompts]
    eng = make_engine(cfg, params, max_batch=2, decode_chunk_size=3)
    try:
        handles = [eng.submit([Message.user(p)], 8, GREEDY) for p in prompts]
        got = [collect(h)[0] for h in handles]
    finally:
        eng.stop()
    assert got == want
    assert eng.stats["max_rows"] == 2


def test_engine_gemma3_dual_rope_matches_serialized():
    """Gemma-3's dual rope + 5:1 window pattern + qk-norms through the batch
    engine: the stacked rope tables and rope_sel/win_flag metadata thread
    through the pad-aware batched bodies."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=4, model_type="gemma3_text", qk_norm=True,
        rmsnorm_offset=True, post_block_norms=True,
        rope_local_base_freq=10000.0,
        sliding_pattern=(True, True, False, True), sliding_window=16,
        query_pre_attn_scalar=8, hidden_activation="gelu_tanh",
        tie_word_embeddings=True, embedding_scale=8.0,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(64), jnp.float32)
    prompts = ["gemma3 engine dual rope test prompt", "g2"]
    want = [single_row(cfg, params, p, 8, GREEDY)[0] for p in prompts]
    eng = make_engine(cfg, params, max_batch=2, decode_chunk_size=3)
    try:
        handles = [eng.submit([Message.user(p)], 8, GREEDY) for p in prompts]
        got = [collect(h)[0] for h in handles]
    finally:
        eng.stop()
    assert got == want
