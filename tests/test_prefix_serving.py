"""Persistent prefix cache (runtime/prefix_cache.py) + engine wiring.

Two layers under test:

  * The radix tree over page chains itself: insert adopts a finished lane's
    prompt-prefix pages (refcounted, zero-copy), fork splices the longest
    cached chain into a new lane (+1 ref, pinned by a lease), LRU eviction
    respects pins and the page budget, reclaim frees on demand, and clear
    drains every non-lane reference.
  * The BatchEngine wiring: a warm cache serves admissions a forked chain
    and prefills only the uncached suffix — with greedy AND sampled streams
    **bit-identical** to a cold run (fp32 CPU, the PR 4 proof pattern),
    because every cache-enabled prefill (cold epochs included) walks the one
    cached-chunk arithmetic. The pool drains back to fully free after the
    engine idles and the cache is cleared; the shed gate counts reclaimable
    cache pages as available (a full-but-cold cache is capacity, not
    pressure).
"""
# These tests PIN allocator-mutation semantics by holding pre-mutation
# snapshots of block-table rows and asserting what fork/make_private/
# release did to them — the exact pattern stale-block-table exists to
# flag in runtime code, deliberate here.
# cake-lint: disable-file=stale-block-table

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.paged_cache import PageAllocator
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.prefix_cache import PrefixCache
from cake_tpu.runtime.serving import BatchEngine, EngineOverloaded, ServeConfig
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 256
PAGE = 16


# ------------------------------------------------------------- radix unit


def make_cache(n_pages=32, ps=4, batch=4, pps=8, max_pages=16, min_tokens=0):
    alloc = PageAllocator(n_pages, ps, batch=batch, max_pages_per_seq=pps)
    cache = PrefixCache(alloc, max_pages=max_pages, min_tokens=min_tokens)
    return alloc, cache


class TestChainHelpers:
    """PageAllocator chain-level primitives the cache is built on."""

    def test_retain_release_keep_pages_alive_across_lane_release(self):
        alloc, _ = make_cache()
        alloc.map_range(0, 0, 8)  # 2 pages
        pages = [int(p) for p in alloc.block_tables[0][:2]]
        alloc.retain_pages(pages)
        assert all(alloc.refcount[p] == 2 for p in pages)
        alloc.release(0)
        assert all(alloc.refcount[p] == 1 for p in pages)
        assert alloc.pages_free == alloc.pages_total - 2
        alloc.release_pages(pages)
        assert alloc.pages_free == alloc.pages_total

    def test_fork_chain_maps_shared_and_rejects_mapped_targets(self):
        alloc, _ = make_cache()
        alloc.map_range(0, 0, 8)
        pages = [int(p) for p in alloc.block_tables[0][:2]]
        alloc.fork_chain(1, pages, 0)
        assert all(alloc.refcount[p] == 2 for p in pages)
        assert alloc.pages_shared == 2
        with pytest.raises(ValueError):
            alloc.fork_chain(1, pages, 0)  # target already mapped
        with pytest.raises(ValueError):
            alloc.fork_chain(2, pages, 7)  # overflows the table
        alloc.unmap_page(1, 0)
        assert alloc.refcount[pages[0]] == 1
        with pytest.raises(ValueError):
            alloc.unmap_page(1, 0)  # already unmapped

    def test_retain_free_page_is_an_error(self):
        alloc, _ = make_cache()
        with pytest.raises(ValueError):
            alloc.retain_pages([0])
        with pytest.raises(ValueError):
            alloc.release_pages([0])

    def test_release_lanes_keeps_cache_refs(self):
        alloc, _ = make_cache()
        alloc.map_range(0, 0, 8)
        pages = [int(p) for p in alloc.block_tables[0][:2]]
        alloc.retain_pages(pages)
        alloc.release_lanes(batch=4)
        assert all(alloc.refcount[p] == 1 for p in pages)
        assert not alloc.lane_mapped(0)
        assert alloc.pages_free == alloc.pages_total - 2


class TestRadixTree:
    def test_insert_then_fork_serves_page_aligned_prefix(self):
        alloc, cache = make_cache(ps=4)
        ids = list(range(100, 110))  # 10 tokens, pad 2 -> chunks 2,4,4
        alloc.map_range(0, 2, 12)
        assert cache.insert(0, ids, pad=2) == 3
        alloc.release(0)
        assert cache.stats()["pages"] == 3
        assert alloc.pages_free == alloc.pages_total - 3

        # A longer prompt sharing the 10-token prefix forks the full chain.
        ids2 = ids + [300, 301]
        plan = cache.fork(1, ids2, pad=2)
        assert plan is not None
        assert plan.served == 10
        assert plan.cow_logical is None  # (2 + 10) % 4 == 0: page-aligned
        assert alloc.pages_shared == 3
        alloc.map_range(1, 2 + 10, 16)  # uncached tail
        # Pinned: eviction cannot touch the forked chain.
        assert cache.reclaim(99) == 0
        cache.release(plan.lease)
        alloc.release(1)
        assert cache.reclaim(99) == 3
        assert alloc.pages_free == alloc.pages_total

    def test_partial_tail_fork_reports_cow_page(self):
        alloc, cache = make_cache(ps=4)
        ids = list(range(100, 109))  # 9 tokens, pad 2 -> chunks 2,4,3(partial)
        alloc.map_range(0, 2, 11)
        cache.insert(0, ids, pad=2)
        alloc.release(0)

        plan = cache.fork(1, ids, pad=2)  # same prompt again
        assert plan is not None
        # The last prompt token is always recomputed: served caps at 8, which
        # lands mid-page -> the third chain page needs a CoW split.
        assert plan.served == 8
        assert plan.cow_logical == 2
        pair = alloc.make_private(1, 2)
        assert pair is not None  # it WAS shared (cache ref + lane ref)
        src, dst = pair
        assert int(alloc.block_tables[1][2]) == dst != src
        cache.release(plan.lease)
        alloc.release(1)
        cache.clear()
        assert alloc.pages_free == alloc.pages_total

    def test_partial_node_extends_to_longer_coverage(self):
        alloc, cache = make_cache(ps=4)
        short = list(range(100, 109))  # 9 tokens: tail node holds 3 of 4
        alloc.map_range(0, 2, 11)
        cache.insert(0, short, pad=2)
        alloc.release(0)
        old_pages = cache.stats()["pages"]

        longer = short + [200, 201, 202]  # 12 tokens: fills the tail page +
        alloc.map_range(1, 2, 14)
        cache.insert(1, longer, pad=2)
        alloc.release(1)
        st = cache.stats()
        # The partial node was REPLACED by the longer lane's page (same node
        # count for that span, +1 node for the new tail span).
        assert st["nodes"] == old_pages + 1
        plan = cache.fork(2, longer, pad=2)
        assert plan is not None and plan.served == 11  # len - 1
        cache.release(plan.lease)
        alloc.release(2)
        cache.clear()
        assert alloc.pages_free == alloc.pages_total

    def test_divergent_insert_lands_as_sibling(self):
        alloc, cache = make_cache(ps=4)
        a = [1, 2, 3, 4, 5, 6, 7, 8]
        b = [1, 2, 3, 4, 9, 9, 9, 9]  # diverges inside the second chunk
        alloc.map_range(0, 0, 8)
        cache.insert(0, a, pad=0)
        alloc.release(0)
        alloc.map_range(1, 0, 8)
        cache.insert(1, b, pad=0)
        alloc.release(1)
        pa = cache.fork(2, a, pad=0)
        assert pa is not None and pa.served == 7
        pb = cache.fork(3, b, pad=0)
        assert pb is not None and pb.served == 7
        cache.release(pa.lease)
        cache.release(pb.lease)
        alloc.release(2)
        alloc.release(3)
        cache.clear()
        assert alloc.pages_free == alloc.pages_total

    def test_alignment_classes_do_not_cross(self):
        alloc, cache = make_cache(ps=4)
        ids = list(range(50, 62))
        alloc.map_range(0, 0, 12)
        cache.insert(0, ids, pad=0)
        alloc.release(0)
        assert cache.fork(1, ids, pad=1) is None  # align 1 != align 0
        assert cache.match_tokens(ids, 1) == 0
        assert cache.match_tokens(ids, 0) > 0

    def test_min_tokens_gates_fork_and_insert(self):
        alloc, cache = make_cache(ps=4, min_tokens=6)
        short = [1, 2, 3]
        alloc.map_range(0, 0, 4)
        assert cache.insert(0, short, pad=0) == 0  # below the churn guard
        alloc.release(0)
        ids = list(range(10, 22))
        alloc.map_range(0, 0, 12)
        cache.insert(0, ids, pad=0)
        alloc.release(0)
        # A 5-token shared prefix is below min_tokens: miss.
        assert cache.fork(1, ids[:5] + [99, 98, 97], pad=0) is None
        assert cache.counters["misses"] == 1

    def test_lru_eviction_respects_budget_and_pins(self):
        alloc, cache = make_cache(ps=4, max_pages=2)
        a, b = [1, 2, 3, 4], [5, 6, 7, 8]
        alloc.map_range(0, 0, 4)
        cache.insert(0, a, pad=0)
        alloc.release(0)
        plan = cache.fork(1, a + [9], pad=0)  # pin chain a
        assert plan is not None
        alloc.map_range(1, 1, 8)
        alloc.map_range(2, 0, 4)
        cache.insert(2, b, pad=0)
        alloc.release(2)
        alloc.map_range(2, 0, 4)
        cache.insert(2, [7, 7, 7, 7], pad=0)
        alloc.release(2)
        # Budget 2, three 1-page chains, chain a pinned: unpinned LRU leaves
        # evicted down to the budget, the pinned chain untouched.
        st = cache.stats()
        assert st["pages"] == 2 and st["evictions"] >= 1
        assert cache.match_tokens(a + [9], 0) > 0  # pinned chain survives
        cache.release(plan.lease)
        alloc.release(1)
        cache._evict_to_budget()
        cache.clear()
        assert alloc.pages_free == alloc.pages_total

    def test_reclaim_frees_lru_first(self):
        alloc, cache = make_cache(ps=4, max_pages=16)
        for base in (0, 20, 40):
            ids = list(range(base, base + 8))
            alloc.map_range(0, 0, 8)
            cache.insert(0, ids, pad=0)
            alloc.release(0)
        free0 = alloc.pages_free
        assert cache.reclaim(2) == 2
        assert alloc.pages_free == free0 + 2
        # The OLDEST chain lost its pages first.
        assert cache.fork(1, list(range(0, 8)), pad=0) is None or (
            cache.counters["evictions"] >= 2
        )

    def test_match_tokens_is_read_only(self):
        alloc, cache = make_cache(ps=4)
        ids = list(range(9, 21))
        alloc.map_range(0, 0, 12)
        cache.insert(0, ids, pad=0)
        alloc.release(0)
        before = dict(cache.counters)
        n = cache.match_tokens(ids, 0)
        assert 0 < n <= len(ids) - 1
        assert dict(cache.counters) == before  # advisory: no hit/miss count


# ---------------------------------------------------------- engine wiring


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def prefix_cfg(**over):
    kw = dict(
        max_batch=8, decode_chunk_size=4, admission_window=0.05,
        kv_mode="paged", page_size=PAGE, prefix_cache=True,
    )
    kw.update(over)
    return ServeConfig(**kw)


def make_engine(cfg, params, serve, **kw):
    kw.setdefault("max_seq_len", MAX_SEQ)
    kw.setdefault("cache_dtype", jnp.float32)
    eng = BatchEngine(cfg, params, ByteTokenizer(), serve=serve, **kw)
    eng.start()
    return eng


def collect(handle):
    return [t.id for t in handle.tokens()]


def wait_idle(eng, n_epochs, timeout=30.0):
    """Block until ``n_epochs`` epoch spans have CLOSED on the timeline —
    the engine fully drained them, lanes recycled, chains inserted. Without
    this the next submit would continuous-batching-JOIN the draining epoch
    (at a join-pad alignment: a legitimate but different code path) instead
    of starting a fresh warm epoch."""
    from cake_tpu.obs.timeline import timeline

    deadline = time.time() + timeout
    while time.time() < deadline:
        done = sum(1 for e in timeline.snapshot() if e["name"] == "epoch")
        if done >= n_epochs:
            # The epoch span closes BEFORE the finally path recycles lanes;
            # quiesce waits for the release/insert bookkeeping too.
            assert eng.quiesce(max(0.1, deadline - time.time()))
            return
        time.sleep(0.01)
    raise AssertionError("engine did not go idle")


SYS = (
    "You are a helpful, careful assistant serving a production workload."
    " Always answer concisely, cite no sources, and keep formatting plain."
)
PROMPTS = [SYS + f" Request {i}: summarize topic number {i}." for i in range(4)]


def run_rounds(eng, sampling, n_rounds=2, n_tokens=24):
    rounds = []
    for r in range(n_rounds):
        handles = [
            eng.submit([Message.user(p)], n_tokens, sampling)
            for p in PROMPTS
        ]
        rounds.append([collect(h) for h in handles])
        wait_idle(eng, r + 1)
    return rounds


@pytest.mark.parametrize(
    "sampling",
    [
        GREEDY,
        SamplingConfig(temperature=0.8, top_k=40, repeat_penalty=1.1, seed=11),
    ],
    ids=["greedy", "sampled"],
)
def test_warm_streams_bit_identical_to_cold(sampling):
    """Acceptance: the shared-system-prompt workload — round 2 runs against
    the chains round 1 left behind (every admission a hit), and its streams
    are bit-identical to the cold round's."""
    cfg, params = setup()
    eng = make_engine(cfg, params, prefix_cfg(prefix_cache_pages=48))
    alloc = eng._alloc
    cold, warm = run_rounds(eng, sampling)
    assert warm == cold  # bit-identical, token for token
    assert eng.stats["prefix_hits"] >= len(PROMPTS)  # round 2 hit
    px = eng._prefix.stats()
    assert px["inserts"] >= len(PROMPTS)
    assert px["hit_tokens"] > 0
    assert metrics.registry.counter("cake_prefix_hits_total").value() >= 4
    # Idle engine: only the cache still holds pages; clear() drains the pool
    # back to fully free — nothing leaked through fork/insert refcounts.
    assert alloc.pages_free == alloc.pages_total - px["pages"]
    eng._prefix.clear()
    assert alloc.pages_free == alloc.pages_total
    eng.stop()


def test_cache_off_engine_is_untouched():
    """With prefix_cache off (the default), the engine keeps the plain
    paged paths byte-for-byte: repeat runs are bit-identical, no cache
    object exists, no prefix counters record. (A cache-ENABLED engine's
    streams are pinned against each other — warm vs cold — not against the
    cache-off engine: the cached-chunk prefill is a different reduction
    order at the ulp level, which is exactly why the engine routes EVERY
    cache-enabled prefill through it.)"""
    cfg, params = setup()
    runs = []
    for _ in range(2):
        eng = make_engine(cfg, params, prefix_cfg(prefix_cache=False))
        runs.append(run_rounds(eng, GREEDY, n_rounds=1))
        assert eng._prefix is None
        assert eng.stats["prefix_hits"] == eng.stats["prefix_misses"] == 0
        eng.stop()
    assert runs[0] == runs[1]
    assert metrics.registry.counter("cake_prefix_hits_total").value() == 0


JOIN_SYS = "Shared join-test system preamble, byte-tokenized."
JOIN_P1 = JOIN_SYS + " Long-running primary request."
JOIN_P2 = JOIN_SYS + " Late joiner."


def test_warm_join_hits_and_matches_cold_join():
    """A request that JOINS a running epoch forks at its join pad. With
    page_size=1 every pad is alignment-compatible, so the joiner hits; its
    stream is bit-identical to the same join against a cold cache (one
    arithmetic for hit and miss)."""
    cfg, params = setup()
    serve = prefix_cfg(
        page_size=1, max_pages=420, max_batch=2, decode_chunk_size=2,
        admission_window=0.02,
    )

    def run(warmup):
        eng = make_engine(cfg, params, serve)
        epochs = 0
        if warmup:
            h = eng.submit([Message.user(JOIN_P2)], 4, GREEDY)
            collect(h)
            epochs += 1
            wait_idle(eng, epochs)
        hits0 = eng.stats["prefix_hits"]
        h1 = eng.submit([Message.user(JOIN_P1)], 40, GREEDY)
        it = h1.tokens()
        next(it)  # the epoch is decoding now
        h2 = eng.submit([Message.user(JOIN_P2)], 8, GREEDY)
        got2 = collect(h2)
        got1 = [t.id for t in it]
        joined = eng.stats["joins"] >= 1
        hit = eng.stats["prefix_hits"] - hits0
        wait_idle(eng, epochs + 1)
        eng._prefix.clear()
        ok_drain = eng._alloc.pages_free == eng._alloc.pages_total
        eng.stop()
        return got1, got2, joined, hit, ok_drain

    cold1, cold2, joined_c, _, drain_c = run(warmup=False)
    warm1, warm2, joined_w, hits_w, drain_w = run(warmup=True)
    assert joined_c and joined_w  # h2 joined the running epoch in both runs
    assert warm2 == cold2  # the joiner's stream is bit-identical
    assert warm1 == cold1
    assert hits_w >= 1  # ...and the warm run actually forked a chain
    assert drain_c and drain_w


def test_join_page_exhaustion_degrades_only_that_stream():
    """A PageExhausted out of the fork/map path (the admission price went
    stale against a concurrent reclaim) force-finishes just the one stream
    as "length" — never the epoch. Pinned by making _fork_lane itself
    raise: the primary stream must be untouched and the pool must drain."""
    from cake_tpu.models.llama.paged_cache import PageExhausted

    cfg, params = setup()
    serve = prefix_cfg(
        page_size=1, max_pages=420, max_batch=2, decode_chunk_size=2,
        admission_window=0.02,
    )

    def run(starve):
        eng = make_engine(cfg, params, serve)
        h1 = eng.submit([Message.user(JOIN_P1)], 40, GREEDY)
        it = h1.tokens()
        next(it)  # the epoch is decoding now
        orig = eng._fork_lane
        if starve:
            def boom(lane, req, pad, end):
                raise PageExhausted("synthetic stale-price exhaustion")
            eng._fork_lane = boom
        h2 = eng.submit([Message.user(JOIN_P2)], 8, GREEDY)
        got2 = collect(h2)
        eng._fork_lane = orig
        got1 = [t.id for t in it]
        wait_idle(eng, 1)
        eng._prefix.clear()
        drained = eng._alloc.pages_free == eng._alloc.pages_total
        truncations = eng.stats["page_truncations"]
        reason2 = h2.finish_reason
        eng.stop()
        return got1, got2, reason2, truncations, drained

    ref1, ref2, _, _, _ = run(starve=False)
    got1, got2, reason2, truncations, drained = run(starve=True)
    assert got2 == [] and reason2 == "length"  # the starved stream degraded
    assert truncations >= 1
    assert got1 == ref1  # the primary stream never noticed
    assert len(ref2) > 0  # control: un-starved, the same join streams fine
    assert drained  # no page leaked through the degrade path


def test_shed_gate_counts_reclaimable_cache_pages():
    """Satellite: a full-but-cold cache is capacity, not pressure. With the
    free list below the shed floor but (free + reclaimable) above it, the
    submission is admitted (eviction runs at admission); only when even
    reclaiming everything cannot reach the floor does the gate shed."""
    cfg, params = setup()
    serve = prefix_cfg(
        max_pages=32, prefix_cache_pages=24, shed_min_free_pages=26,
        max_batch=2,
    )
    eng = make_engine(cfg, params, serve)
    alloc = eng._alloc
    # Fill the cache: a long prompt's chain stays behind after it finishes.
    h = eng.submit([Message.user(SYS + " warm the cache up.")], 4, GREEDY)
    collect(h)
    wait_idle(eng, 1)
    held = eng._prefix.stats()["pages"]
    assert held > 0
    assert alloc.pages_free == alloc.pages_total - held
    if alloc.pages_free >= 26:
        pytest.skip("prompt too short to push the free list under the floor")
    # Below the floor on raw free pages, above it with reclaimable counted:
    # must NOT shed, and the request must complete (shed-after-evict order).
    h = eng.submit([Message.user("short")], 4, GREEDY)
    assert collect(h)
    assert eng.stats["shed"] == 0
    eng.stop()

    # Control: a floor no amount of eviction can reach still sheds.
    eng = make_engine(
        cfg, params,
        prefix_cfg(max_pages=32, shed_min_free_pages=33, max_batch=2),
    )
    with pytest.raises(EngineOverloaded):
        eng.submit([Message.user("hi")], 4, GREEDY)
    assert eng.stats["shed"] == 1
    eng.stop()


def test_prefix_cache_requires_paged_backend():
    with pytest.raises(ValueError):
        ServeConfig(kv_mode="dense", prefix_cache=True)
    cfg, params = setup()
    with pytest.raises(ValueError):
        BatchEngine(
            cfg, params, ByteTokenizer(),
            max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
            backend=object.__new__(
                __import__(
                    "cake_tpu.runtime.batch_backend", fromlist=["x"]
                ).LocalBatchBackend
            ),
            serve=ServeConfig(kv_mode="paged", prefix_cache=True),
        )


def test_pool_pressure_evicts_cache_before_truncating_decode():
    """The decode page-extend path reclaims cold cache pages on demand: a
    pool sized so decode would starve with the cache resident still serves
    the stream to its full budget."""
    cfg, params = setup()
    serve = prefix_cfg(max_pages=18, prefix_cache_pages=14, max_batch=2)
    eng = make_engine(cfg, params, serve)
    h = eng.submit([Message.user(SYS + " fill pages.")], 4, GREEDY)
    collect(h)
    wait_idle(eng, 1)
    held = eng._prefix.stats()["pages"]
    assert held >= 8  # the cache holds most of the 18-page pool
    # A long decode now needs more pages than the free list holds: its
    # history grows past (18 - held) * 16 slots, so the extend path MUST
    # reclaim cache pages or truncate.
    h = eng.submit([Message.user("go long")], 160, GREEDY)
    got = collect(h)
    assert len(got) == 160 and h.finish_reason == "length"
    assert eng.stats["page_truncations"] == 0
    assert eng._prefix.counters["evictions"] >= 1
    wait_idle(eng, 2)
    eng._prefix.clear()
    assert eng._alloc.pages_free == eng._alloc.pages_total
    eng.stop()


# ------------------------------------- cache-aware admission ordering (ISSUE 15)


def test_cache_aware_ordering_groups_same_chain_requests():
    """Draining the queue prefers candidates extending the SAME cached
    radix path as the fair-order head: [P-a, X, P-b] admits the two
    P-requests together, so both fork the chain while it is hot — with
    ordering OFF, X rides the first epoch and its insert evicts P before
    P-b runs (budget = one chain), halving the hits. Streams themselves
    are bit-identical either way (ordering moves admissions, never
    tokens)."""
    cfg, params = setup()
    p_a = SYS + " Request a: summarize topic number 1."
    p_b = SYS + " Request b: summarize topic number 2."
    x = (
        "A completely different prompt sharing no prefix with the system"
        " one, padded until it holds roughly as many pages as the chain."
    )

    def run(ordered):
        # Budget ~ one chain: X's insert must evict P when X lands first.
        serve = prefix_cfg(
            max_batch=2, max_pages=64, prefix_cache_pages=6,
            cache_aware_order=ordered, admission_window=0.1,
        )
        eng = make_engine(cfg, params, serve)
        # Warm the chain: one request whose prompt prefix IS the shared
        # system prompt.
        collect(eng.submit([Message.user(p_a)], 4, GREEDY))
        wait_idle(eng, 1)
        assert eng._prefix.stats()["pages"] >= 4
        hits0 = eng.stats["prefix_hits"]
        handles = [
            eng.submit([Message.user(p)], 4, GREEDY)
            for p in (p_a, x, p_b)
        ]
        out = [collect(h) for h in handles]
        # Epoch COUNT differs by ordering mode (that is the point) — wait
        # on pool idleness, not a span count.
        assert eng.quiesce(30)
        hits = eng.stats["prefix_hits"] - hits0
        eng._prefix.clear()
        eng.stop()
        return out, hits

    out_on, hits_on = run(True)
    out_off, hits_off = run(False)
    assert out_on == out_off  # ordering never changes tokens
    assert hits_on == 2       # P-a and P-b grouped, both hot
    assert hits_off < hits_on  # interleaved order thrashed the chain


def test_cache_aware_ordering_defers_not_starves():
    """A deferred candidate is admitted in the NEXT epoch (bounded
    deferral inside the DRR walk): everyone finishes."""
    cfg, params = setup()
    serve = prefix_cfg(
        max_batch=2, max_pages=64, prefix_cache_pages=6,
        cache_aware_order=True, admission_window=0.1,
    )
    eng = make_engine(cfg, params, serve)
    collect(eng.submit([Message.user(PROMPTS[0])], 4, GREEDY))
    wait_idle(eng, 1)
    handles = [
        eng.submit([Message.user(p)], 4, GREEDY)
        for p in (PROMPTS[1], "the odd one out", PROMPTS[2])
    ]
    for h in handles:
        collect(h)
        assert h.finish_reason in ("stop", "length")
    eng.stop()


# --------------------------------------- evict-then-retry (ISSUE 15 satellite)


def test_extend_retries_reclaim_until_no_progress():
    """The starved-stream fix: a reclaim pass that under-frees (here: one
    page per call, standing in for lane-shared pages and pin churn) no
    longer force-finishes the stream — the extend path evicts-then-retries
    until a pass frees nothing new. With the chunk spanning two pages the
    single-retry behavior this replaces would have truncated."""
    cfg, params = setup()
    # decode_chunk 20 > page 16: one extension can need TWO fresh pages.
    serve = prefix_cfg(
        max_pages=18, prefix_cache_pages=14, max_batch=2,
        decode_chunk_size=20,
    )
    eng = make_engine(cfg, params, serve)
    collect(eng.submit([Message.user(SYS + " fill pages.")], 4, GREEDY))
    wait_idle(eng, 1)
    assert eng._prefix.stats()["pages"] >= 8

    orig = eng._prefix.reclaim
    calls = []

    def stingy(n_pages, rid=""):
        calls.append(n_pages)
        return orig(1, rid=rid)  # a pass frees AT MOST one page

    eng._prefix.reclaim = stingy
    h = eng.submit([Message.user("go long")], 160, GREEDY)
    got = collect(h)
    assert len(got) == 160 and h.finish_reason == "length"
    assert eng.stats["page_truncations"] == 0
    # The retry loop really ran more than one pass for one extension.
    assert len(calls) >= 2
    eng._prefix.reclaim = orig
    wait_idle(eng, 2)
    eng._prefix.clear()
    assert eng._alloc.pages_free == eng._alloc.pages_total
    eng.stop()
