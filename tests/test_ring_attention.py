"""Ring attention vs the single-device oracle on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.ops.attention import gqa_attention
from cake_tpu.parallel.context import make_sp_mesh, ring_attention_sharded


def _oracle(q, k, v):
    b, s = q.shape[:2]
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    return gqa_attention(q, k, v, positions, positions)


@pytest.mark.parametrize("n_dev", [2, 4, 8])
@pytest.mark.parametrize(
    "b,s,n_q,n_kv,d",
    [
        (1, 128, 4, 2, 32),
        (2, 64, 8, 8, 16),   # MHA
        (1, 256, 8, 1, 32),  # MQA, long-ish
    ],
)
def test_ring_matches_oracle(n_dev, b, s, n_q, n_kv, d):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, s, n_q, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n_kv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n_kv, d), jnp.float32)

    mesh = make_sp_mesh(n_dev)
    out = ring_attention_sharded(q, k, v, mesh)
    ref = _oracle(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_chunk_isolation():
    """Each device's output depends only on causally-visible chunks: perturbing a
    late chunk's K/V must not change earlier chunks' outputs."""
    b, s, n_q, n_kv, d = 1, 64, 4, 2, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, s, n_q, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, n_kv, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, n_kv, d), jnp.float32)
    mesh = make_sp_mesh(4)

    base = np.asarray(ring_attention_sharded(q, k, v, mesh))
    k2 = k.at[:, 48:].set(jax.random.normal(jax.random.PRNGKey(2), (b, 16, n_kv, d)))
    pert = np.asarray(ring_attention_sharded(q, k2, v, mesh))
    np.testing.assert_allclose(pert[:, :48], base[:, :48], atol=1e-6)
    assert not np.allclose(pert[:, 48:], base[:, 48:])
