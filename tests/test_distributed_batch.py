"""Continuous batching over the TCP topology (DistributedBatchBackend).

The reference's defining deployment (heterogeneous hosts over TCP) serves one
request at a time behind the API lock (api/mod.rs:76). Contract under test:
the engine's init_kv/prefill/decode/join seam over LIVE StageClient spans
emits per-request token streams IDENTICAL to the local backend — batched
prefill/decode/join ride the FORWARD header's ``batch`` extension through
real worker processes' pad-aware jits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.batch import layout_prompts, seed_rings, first_sample
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.batch_backend import (
    DistributedBatchBackend,
    LocalBatchBackend,
)
from cake_tpu.runtime.master import DistributedForwardStep
from cake_tpu.runtime.serving import BatchEngine
from cake_tpu.runtime.worker import Worker

MAX_SEQ = 96


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """Two live workers + a master-owned middle range (0-1 w1, 2-3 master,
    4-5 w2) so the walk interleaves local jits with wire round trips."""
    model_dir = tmp_path_factory.mktemp("ckpt") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)

    topo = Topology.from_dict(
        {
            "w1": {"host": "placeholder", "layers": ["model.layers.0-1"]},
            "w2": {"host": "placeholder", "layers": ["model.layers.4-5"]},
        }
    )
    workers = []
    for name in ("w1", "w2"):
        w = Worker(
            name, model_dir, topo, ("127.0.0.1", 0),
            dtype=jnp.float32, max_seq_len=MAX_SEQ,
        )
        w.start()
        topo.nodes[name].host = f"127.0.0.1:{w.address[1]}"
        workers.append(w)
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
    )
    yield cfg, params, step
    step.close()
    for w in workers:
        w.stop()


def _backend(cluster):
    cfg, params, step = cluster
    return DistributedBatchBackend(
        step, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )


def _local(cluster):
    cfg, params, step = cluster
    return LocalBatchBackend(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )


@pytest.mark.parametrize(
    "s",
    [
        SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0),
        SamplingConfig(
            temperature=0.8, top_k=16, top_p=0.9,
            repeat_penalty=1.1, repeat_last_n=8,
        ),
    ],
    ids=["greedy", "sampled"],
)
def test_prefill_decode_matches_local(cluster, s):
    """Batched prefill + chunked decode over the live cluster: streams equal
    the single-process local backend row for row."""
    B, n = 3, 6
    ids_list = [[7, 3, 11, 2][: 2 + r] for r in range(B)]
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    window = s.repeat_last_n
    keys0 = jax.random.split(jax.random.PRNGKey(5), B)

    outs = []
    for be in (_local(cluster), _backend(cluster)):
        kv = be.init_kv(B)
        logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
        ring, ring_idx = seed_rings(ids_list, window)
        first, keys, ring, ring_idx = first_sample(
            logits, s, ring, ring_idx, keys0
        )
        toks, kv, keys, ring_j, ridx_j = be.decode(
            kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
            jnp.asarray(ring), jnp.asarray(ring_idx), n, s,
        )
        outs.append((list(first), np.asarray(toks)))
    (fa, a), (fb, b) = outs
    assert fa == fb
    np.testing.assert_array_equal(a, b)


def test_join_matches_local(cluster):
    """A continuous JOIN mid-epoch: the joined row's logits (and the whole
    batch's subsequent decode) must match the local backend."""
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0, repeat_last_n=0)
    B = 2
    ids_list = [[5, 9], [4, 8, 2]]
    tokens, pads, bucket = layout_prompts(ids_list, MAX_SEQ)
    join_ids = [6, 1]
    keys0 = jax.random.split(jax.random.PRNGKey(7), B)

    outs = []
    for be in (_local(cluster), _backend(cluster)):
        kv = be.init_kv(B)
        logits, kv = be.prefill(jnp.asarray(tokens), kv, jnp.asarray(pads))
        ring, ring_idx = seed_rings(ids_list, 0)
        first, keys, ring, ring_idx = first_sample(
            logits, s, ring, ring_idx, keys0
        )
        # Decode 2, then join a row into lane 1 ending at the shared slot.
        toks1, kv, keys, ring_j, ridx_j = be.decode(
            kv, jnp.asarray(first), bucket, jnp.asarray(pads), keys,
            jnp.asarray(ring), jnp.asarray(ring_idx), 2, s,
        )
        slot = bucket + 2
        W = 64
        row_tokens = np.zeros((1, W), np.int32)
        row_tokens[0, slot - len(join_ids) : slot] = join_ids
        jlogits, kv = be.join(
            kv, row_tokens,
            jnp.asarray([slot - len(join_ids)], jnp.int32),
            jnp.asarray([slot], jnp.int32), 1,
        )
        pads2 = np.asarray(pads).copy()
        pads2[1] = slot - len(join_ids)
        tok = np.asarray(toks1[:, -1]).copy()
        tok[1] = int(np.argmax(np.asarray(jlogits[0])))
        toks2, kv, keys, ring_j, ridx_j = be.decode(
            kv, jnp.asarray(tok), slot, jnp.asarray(pads2), keys,
            jnp.asarray(ring_j), jnp.asarray(ridx_j), 3, s,
        )
        outs.append(
            (np.asarray(toks1), np.asarray(jlogits), np.asarray(toks2))
        )
    (a1, aj, a2), (b1, bj, b2) = outs
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_allclose(aj, bj, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(a2, b2)


def test_old_worker_rejected(cluster):
    """A pre-batch worker's handshake omits batch_ops; the backend must
    refuse loudly instead of letting pads be silently ignored."""
    import dataclasses

    cfg, params, step = cluster
    client = next(iter(step.clients.values()))
    old = client.info
    client.info = dataclasses.replace(old, batch_ops=False)
    try:
        with pytest.raises(RuntimeError, match="does not support lockstep"):
            DistributedBatchBackend(
                step, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
            )
    finally:
        client.info = old


def test_engine_over_tcp_matches_local(cluster):
    """End-to-end: BatchEngine over the live TCP cluster — concurrent
    requests batch into one epoch (stats prove it) and emit the same streams
    as the engine over the local backend."""
    cfg, params, step = cluster
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

    def run_engine(backend):
        eng = BatchEngine(
            cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=3, max_batch=4,
            admission_window=0.05, backend=backend,
        )
        eng.start()
        try:
            handles = [
                eng.submit([Message.user(f"tcp req {i}")], 5, s)
                for i in range(3)
            ]
            streams = [[t.id for t in h.tokens()] for h in handles]
            return streams, dict(eng.stats)
        finally:
            eng.stop()

    local_streams, _ = run_engine(_local(cluster))
    tcp_streams, stats = run_engine(_backend(cluster))
    assert tcp_streams == local_streams
    assert stats["max_rows"] >= 2  # requests really batched over the wire

def test_engine_over_tcp_speculative_matches_local(cluster):
    """Speculative verify over the wire: the engine drafts per row, ONE
    batched verify round trip per span scores them all, and greedy streams
    stay byte-identical to the local engine's."""
    cfg, params, step = cluster
    s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    prompts = ["abc abc abc abc abc", "xy xy xy xy xy xy"]

    def run_engine(backend, k):
        eng = BatchEngine(
            cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=3, max_batch=4,
            admission_window=0.05, speculative_k=k, backend=backend,
        )
        eng.start()
        try:
            handles = [eng.submit([Message.user(p)], 10, s) for p in prompts]
            streams = [[t.id for t in h.tokens()] for h in handles]
            return streams, dict(eng.stats)
        finally:
            eng.stop()

    local, _ = run_engine(_local(cluster), 0)
    tcp, stats = run_engine(_backend(cluster), 4)
    assert tcp == local
    assert stats["spec_rounds"] > 0


def test_verify_incapable_worker_falls_back_to_plain_decode(cluster):
    """A worker whose handshake lacks verify_ops: the backend shadows its
    verify methods, so the engine silently falls back to plain decode
    instead of failing every epoch on an unknown batch kind."""
    import dataclasses

    cfg, params, step = cluster
    client = next(iter(step.clients.values()))
    old = client.info
    client.info = dataclasses.replace(old, verify_ops=False)
    try:
        be = DistributedBatchBackend(
            step, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        )
        assert be.verify_greedy is None and be.verify_sampled is None
        s = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
        eng = BatchEngine(
            cfg, None, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=3, max_batch=2,
            admission_window=0.0, speculative_k=4, backend=be,
        )
        eng.start()
        try:
            h = eng.submit([Message.user("abc abc abc abc")], 6, s)
            ids = [t.id for t in h.tokens()]
        finally:
            eng.stop()
        assert len(ids) == 6
        assert eng.stats["spec_rounds"] == 0  # fell back, no crash
    finally:
        client.info = old
