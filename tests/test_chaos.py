"""Chaos tests: seeded fault plans through the serving stack.

The contract under test (ISSUE 6 acceptance): a deterministic fault plan
(runtime/faults.py) produces the failure; the recovery machinery contains it.

  * worker crash mid-decode -> ONLY the affected streams finish with
    ``finish_reason="error"``; co-batched streams that already finished are
    bit-identical to a fault-free run; the page pool drains to fully free;
    the engine keeps serving.
  * a torn connection / lost reply mid-epoch -> the op REPLAYS idempotently
    (session sid/seq, runtime/{client,worker}.py) and every stream completes
    bit-identically — the fault costs a retry, not a request.
  * cancellation mid-epoch returns every page and stops the decode burn.
  * a stalled worker is marked unhealthy by the heartbeat within its
    deadline, and recovers when the stall clears.
  * admission load shedding refuses (EngineOverloaded -> 503) at the
    configured queue depth.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import faults
from cake_tpu.runtime.batch_backend import DistributedBatchBackend
from cake_tpu.runtime.client import HeartbeatMonitor
from cake_tpu.runtime.master import DistributedForwardStep
from cake_tpu.runtime.serving import BatchEngine, EngineOverloaded, ServeConfig
from cake_tpu.runtime.worker import Worker
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 96


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    faults.clear()
    yield
    faults.clear()


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def make_engine(cfg, params, **serve_kw):
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("decode_chunk_size", 4)
    serve_kw.setdefault("admission_window", 0.05)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(**serve_kw),
    )
    eng.start()
    return eng


def collect(handle):
    return [tok.id for tok in handle.tokens()]


# ------------------------------------------------------------ fault plan unit


class TestFaultPlan:
    def test_dsl_parse_and_fire_order(self):
        plan = faults.parse(
            "seed=7;kill@worker.op:node=w1:after=2:count=1;"
            "delay@client.send:delay_s=0.01:count=0"
        )
        assert plan.seed == 7
        # after=2: the first two matching checkpoints pass clean.
        assert plan.check("worker.op", "w1") is None
        assert plan.check("worker.op", "w2") is None  # node filter: no match,
        assert plan.check("worker.op", "w1") is None  # so w1 is only at 2 here
        spec = plan.check("worker.op", "w1")
        assert spec is not None and spec.kind == "kill"
        # count=1: exhausted.
        assert plan.check("worker.op", "w1") is None
        # unlimited count keeps firing.
        assert plan.check("client.send").kind == "delay"
        assert plan.check("client.send").kind == "delay"

    def test_seeded_probability_is_deterministic(self):
        def decisions():
            plan = faults.parse("seed=123;drop@site:p=0.5:count=0")
            return [plan.check("site") is not None for _ in range(64)]

        a, b = decisions(), decisions()
        assert a == b
        assert any(a) and not all(a)  # p=0.5 actually branches

    def test_malformed_plans_fail_loudly(self):
        with pytest.raises(ValueError):
            faults.parse("kill-without-site")
        with pytest.raises(ValueError):
            faults.parse("explode@site")  # unknown kind
        with pytest.raises(ValueError):
            faults.parse("kill@site:wat")  # option is not key=value

    def test_fired_fault_is_observable(self):
        faults.install(faults.parse("stall@x.y:delay_s=0.0"))
        assert faults.check("x.y", "n0") is not None
        assert metrics.registry.counter(
            "cake_faults_injected_total"
        ).value(kind="stall", site="x.y") == 1
        events = [
            e for e in metrics.flight.snapshot()
            if e["event"] == "fault-injected"
        ]
        assert events and events[0]["site"] == "x.y"


# -------------------------------------------- engine-level failure isolation


@pytest.mark.parametrize("scheduler", ["epoch", "continuous"])
def test_worker_crash_mid_decode_isolates_streams_and_drains_pool(scheduler):
    """Acceptance (a): a seeded crash mid-decode finishes only the affected
    stream as "error"; the co-batched stream that finished BEFORE the fault
    is bit-identical to a fault-free run; the page pool returns to fully
    free; the engine survives and serves the next request. Both scheduler
    shapes honor the contract (ISSUE 15: every failure path survives the
    continuous scheduler)."""
    cfg, params = setup()
    prompts = ["short survivor", "the long victim stream"]

    # Fault-free oracle run (same engine shape, no plan installed).
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16, scheduler=scheduler,
    )
    handles = [
        eng.submit([Message.user(prompts[0])], 3, GREEDY),
        eng.submit([Message.user(prompts[1])], 24, GREEDY),
    ]
    want_survivor = collect(handles[0])
    want_victim_full = collect(handles[1])
    eng.stop()

    # Chaos run: the 4th decode-chunk dispatch dies (prefill is a separate
    # site). The 3-token survivor finishes inside the first chunk.
    faults.install(faults.parse("crash@backend.decode:after=3:count=1"))
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16, scheduler=scheduler,
    )
    alloc = eng.backend.allocator
    handles = [
        eng.submit([Message.user(prompts[0])], 3, GREEDY),
        eng.submit([Message.user(prompts[1])], 24, GREEDY),
    ]
    got_survivor = collect(handles[0])
    got_victim = collect(handles[1])

    assert got_survivor == want_survivor  # bit-identical, untouched
    assert handles[0].finish_reason in ("stop", "length")
    # The victim got the fault-free PREFIX, then a clean "error" finish —
    # no exception raised into the consumer.
    assert handles[1].finish_reason == "error"
    assert len(got_victim) < 24
    assert got_victim == want_victim_full[: len(got_victim)]
    assert alloc.pages_free == alloc.pages_total  # pool fully drained

    # The engine is still alive: a follow-up request completes normally.
    h = eng.submit([Message.user(prompts[0])], 3, GREEDY)
    assert collect(h) == want_survivor
    assert eng.stats["stream_errors"] == 1
    assert metrics.registry.counter("cake_stream_errors_total").value() == 1
    eng.stop()


# --------------------------------------------------------------- cancellation


def test_cancel_mid_epoch_returns_every_page():
    """Acceptance: cancel(request_id) frees the lane's pages mid-epoch
    (pool-gauge assertion) and the stream stops burning decode steps."""
    cfg, params = setup()
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16, decode_chunk_size=2,
    )
    alloc = eng.backend.allocator
    h = eng.submit([Message.user("cancel me mid flight")], 64, GREEDY)
    deadline = time.time() + 30
    while h.completion_tokens < 1 and time.time() < deadline:
        time.sleep(0.005)  # wait until the request is decoding in an epoch
    assert h.completion_tokens >= 1
    assert eng.cancel(h.request_id) is True
    ids = collect(h)  # ends promptly at the next chunk boundary
    assert h.finish_reason == "cancelled"
    assert len(ids) < 64
    # The epoch is over (no live rows) and every page is back.
    deadline = time.time() + 30
    while alloc.pages_free != alloc.pages_total and time.time() < deadline:
        time.sleep(0.01)
    assert alloc.pages_free == alloc.pages_total
    assert metrics.registry.gauge("cake_kv_pages_free").value() == float(
        alloc.pages_total
    )
    # The mid-epoch path fired (not the queued-cancel path).
    wheres = [
        e.get("where")
        for e in metrics.flight.snapshot(request_id=h.request_id)
        if e["event"] == "cancelled"
    ]
    assert wheres == ["epoch"]
    assert eng.stats["cancelled"] == 1
    # cancel() is idempotent and honest: the request is gone now.
    assert eng.cancel(h.request_id) is False
    eng.stop()


def test_cancel_queued_request_never_runs():
    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(max_batch=2, admission_window=0.01),
    )
    # Engine NOT started: the queue holds everything deterministically.
    h = eng.submit([Message.user("queued")], 8, GREEDY)
    assert eng.cancel(h.request_id) is True
    assert collect(h) == []
    assert h.finish_reason == "cancelled"
    assert eng.cancel("chatcmpl-never-existed") is False


# ------------------------------------------------- live-TCP chaos (1 worker)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """One live worker owning every layer, master owning only the head —
    each decode step is one wire round trip, the sharpest replay surface."""
    model_dir = tmp_path_factory.mktemp("ckpt") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w0": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    w = Worker(
        "w0", model_dir, topo, ("127.0.0.1", 0),
        dtype=jnp.float32, max_seq_len=MAX_SEQ,
    )
    w.start()
    topo.nodes["w0"].host = f"127.0.0.1:{w.address[1]}"
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ,
        op_deadline_s=1.0, op_retries=2,
        reconnect_attempts=3, reconnect_backoff_s=0.05,
    )
    yield cfg, step, topo
    step.close()
    w.stop()


def tcp_engine(cluster):
    cfg, step, _ = cluster
    eng = BatchEngine(
        cfg, None, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        backend=DistributedBatchBackend(
            step, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        ),
        serve=ServeConfig(
            max_batch=4, decode_chunk_size=4, admission_window=0.05
        ),
    )
    eng.start()
    return eng


def _two_streams(eng):
    """The chaos workload: a short survivor + a long co-batched stream."""
    h_short = eng.submit([Message.user("survivor")], 2, GREEDY)
    h_long = eng.submit([Message.user("the long victim stream")], 16, GREEDY)
    return h_short, h_long


def test_tcp_connection_kill_replays_to_completion(cluster):
    """A torn connection mid-decode (worker PROCESS alive): the client
    re-dials and resends the same (sid, seq); the epoch completes and every
    stream is bit-identical to a fault-free run — the replay branch of the
    acceptance criterion."""
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    want = (collect(h_short), collect(h_long))
    eng.stop()

    faults.install(faults.parse("kill@worker.op:after=4:count=1"))
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    got = (collect(h_short), collect(h_long))
    assert got == want
    assert h_long.finish_reason in ("stop", "length")
    assert eng.stats["stream_errors"] == 0
    assert metrics.registry.counter(
        "cake_op_retries_total"
    ).value(node="w0") >= 1
    eng.stop()


def test_tcp_reply_drop_served_from_replay_cache(cluster):
    """The op APPLIED but its reply was lost: the resent (sid, seq) must be
    answered from the worker's replay cache, not re-executed (a double KV
    write would corrupt the stream)."""
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    want = (collect(h_short), collect(h_long))
    eng.stop()

    faults.install(faults.parse("drop@worker.reply:after=3:count=1"))
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    got = (collect(h_short), collect(h_long))
    assert got == want
    assert metrics.registry.counter(
        "cake_worker_replays_total"
    ).value(node="w0") >= 1
    eng.stop()


def test_tcp_worker_crash_errors_live_streams_only(cluster):
    """Worker process death mid-decode (session state gone): replay is
    impossible, so the LIVE streams finish "error"; the stream that finished
    before the crash is bit-identical; the engine serves the next request."""
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    want_short, want_long = collect(h_short), collect(h_long)
    eng.stop()

    # Ops: prefill(1) + 4 decode steps serve the first chunk — the 2-token
    # survivor is finished by then. Crash on the 6th op (chunk 2).
    faults.install(faults.parse("crash@worker.op:after=5:count=1"))
    eng = tcp_engine(cluster)
    h_short, h_long = _two_streams(eng)
    got_short, got_long = collect(h_short), collect(h_long)
    assert got_short == want_short  # untouched, bit-identical
    assert h_short.finish_reason in ("stop", "length")
    assert h_long.finish_reason == "error"
    assert got_long == want_long[: len(got_long)]
    assert len(got_long) < len(want_long)
    assert eng.stats["stream_errors"] == 1
    assert metrics.registry.counter(
        "cake_hop_failures_total"
    ).value(node="w0") >= 1

    # Next epoch = next session: the "restarted" worker serves it fine.
    h = eng.submit([Message.user("survivor")], 2, GREEDY)
    assert collect(h) == want_short
    eng.stop()


def test_heartbeat_marks_stalled_worker_unhealthy_within_deadline(cluster):
    """Acceptance (c): a stalled worker is unhealthy within the heartbeat
    deadline, and recovers once the stall clears."""
    _, _, topo = cluster
    mon = HeartbeatMonitor(
        {"w0": topo.nodes["w0"].host}, interval_s=0.05, deadline_s=0.3
    ).start()
    try:
        deadline = time.time() + 5
        while not mon.snapshot()["w0"] and time.time() < deadline:
            time.sleep(0.02)
        assert mon.healthy("w0") is True

        faults.install(
            faults.parse("stall@worker.ping:delay_s=0.6:count=3")
        )
        t0 = time.time()
        while mon.healthy("w0") and time.time() - t0 < 5:
            time.sleep(0.02)
        detect_s = time.time() - t0
        assert mon.healthy("w0") is False
        # Within the deadline (+ one probe interval + slack for CI jitter).
        assert detect_s < 0.3 + 0.05 + 1.0
        assert metrics.registry.counter(
            "cake_worker_unhealthy_total"
        ).value(node="w0") == 1
        assert metrics.registry.gauge(
            "cake_worker_healthy"
        ).value(node="w0") == 0

        # The stall budget (count=3) runs out -> healthy again.
        t0 = time.time()
        while not mon.healthy("w0") and time.time() - t0 < 10:
            time.sleep(0.02)
        assert mon.healthy("w0") is True
        assert any(
            e["event"] == "worker-healthy"
            for e in metrics.flight.snapshot()
        )
    finally:
        mon.stop()


# -------------------------------------------------------------- load shedding


def test_queue_depth_shedding_raises_overloaded():
    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(max_batch=2, shed_queue_depth=2, retry_after_s=3.0),
    )
    # Engine NOT started: submissions pile up deterministically.
    eng.submit([Message.user("a")], 4, GREEDY)
    eng.submit([Message.user("b")], 4, GREEDY)
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([Message.user("c")], 4, GREEDY)
    assert ei.value.retry_after_s == 3.0
    assert eng.stats["shed"] == 1
    assert metrics.registry.counter("cake_shed_total").value() == 1
    assert any(
        e["event"] == "shed" for e in metrics.flight.snapshot()
    )


# ------------------------------------------- replica failover (live TCP)


@pytest.fixture(scope="module")
def replica_cluster(tmp_path_factory):
    """Two live workers declaring the SAME layer range (a replica group)
    plus the master-owned head: the fleet the failover tentpole serves."""
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint

    model_dir = tmp_path_factory.mktemp("ckpt-replica") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {
            "w0": {"host": "placeholder", "layers": ["model.layers.0-1"]},
            "w0b": {"host": "placeholder", "layers": ["model.layers.0-1"]},
        }
    )
    workers = []
    for name in ("w0", "w0b"):
        w = Worker(
            name, model_dir, topo, ("127.0.0.1", 0),
            dtype=jnp.float32, max_seq_len=MAX_SEQ,
        )
        w.start()
        topo.nodes[name].host = f"127.0.0.1:{w.address[1]}"
        workers.append(w)
    yield cfg, model_dir, topo
    for w in workers:
        w.stop()


def replica_step(replica_cluster):
    cfg, model_dir, topo = replica_cluster
    return DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ,
        op_deadline_s=1.0, op_retries=1,
        reconnect_attempts=2, reconnect_backoff_s=0.05,
    )


def replica_engine(cfg, step, **serve_kw):
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("decode_chunk_size", 4)
    serve_kw.setdefault("admission_window", 0.05)
    # Deterministic chaos: the epoch under test routes the group primary.
    step.router.prefer("w0")
    eng = BatchEngine(
        cfg, None, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        backend=DistributedBatchBackend(
            step, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        ),
        serve=ServeConfig(**serve_kw),
    )
    eng.start()
    return eng


def test_failover_kill_primary_streams_bit_identical(replica_cluster):
    """Acceptance (tentpole): a seeded kill@client.send makes the primary
    unreachable mid-decode; with a replica present EVERY stream finishes
    stop/length, greedy outputs are bit-identical to a fault-free run,
    cake_failover_total >= 1, and zero streams finish "error"."""
    cfg, _, _ = replica_cluster
    step = replica_step(replica_cluster)
    eng = replica_engine(cfg, step)
    h_short, h_long = _two_streams(eng)
    want = (collect(h_short), collect(h_long))
    eng.stop()
    step.close()

    # Ops to w0: prefill(1) + decode steps; the 4th send dies and every
    # later send too (count=0) — the node is gone for good.
    faults.install(faults.parse("kill@client.send:node=w0:after=3:count=0"))
    step = replica_step(replica_cluster)
    eng = replica_engine(cfg, step)
    h_short, h_long = _two_streams(eng)
    got = (collect(h_short), collect(h_long))

    assert got == want  # bit-identical through the migration
    assert h_short.finish_reason in ("stop", "length")
    assert h_long.finish_reason in ("stop", "length")
    assert eng.stats["stream_errors"] == 0
    assert eng.stats["failovers"] >= 1
    assert eng.stats["recovered"] >= 1
    assert metrics.registry.counter(
        "cake_failover_total"
    ).value(node="w0") >= 1
    assert metrics.registry.counter(
        "cake_streams_recovered_total"
    ).value() >= 1
    snap = step.router.snapshot()
    assert snap["routes"]["w0"] == "w0b" and snap["ejected"] == ["w0"]
    events = [e["event"] for e in metrics.flight.snapshot()]
    assert "failover" in events and "failover-migrated" in events
    eng.stop()
    step.close()


def test_failover_budget_zero_matches_pr6_error_isolation(replica_cluster):
    """max_failovers=0: even with a healthy replica present the epoch takes
    PR 6's path — live streams finish "error", nothing migrates."""
    cfg, _, _ = replica_cluster
    faults.install(faults.parse("kill@client.send:node=w0:after=3:count=0"))
    step = replica_step(replica_cluster)
    eng = replica_engine(cfg, step, max_failovers=0)
    h_short, h_long = _two_streams(eng)
    collect(h_short), collect(h_long)
    assert h_long.finish_reason == "error"
    assert eng.stats["failovers"] == 0
    assert eng.stats["stream_errors"] >= 1
    eng.stop()
    step.close()


def test_failover_no_healthy_replica_degrades_to_error(replica_cluster):
    """Both members unreachable: the router has nowhere to route, so the
    behavior is PR 6's error isolation — a clean "error" finish, engine
    alive (bit-identical to the no-replica deployment)."""
    cfg, _, _ = replica_cluster
    faults.install(faults.parse("kill@client.send:after=3:count=0"))
    step = replica_step(replica_cluster)
    eng = replica_engine(cfg, step)
    h_short, h_long = _two_streams(eng)
    collect(h_short), collect(h_long)
    assert h_long.finish_reason == "error"
    assert eng.stats["stream_errors"] >= 1
    eng.stop()
    step.close()


def test_standby_rejoin_after_cooldown(replica_cluster):
    """Standby rejoin: once the fault clears and the cooldown passes, the
    ejected primary re-enters rotation (rejoin event) and serves again."""
    cfg, _, _ = replica_cluster
    faults.install(faults.parse("kill@client.send:node=w0:after=3:count=0"))
    step = replica_step(replica_cluster)
    eng = replica_engine(cfg, step, failover_cooldown_s=0.05)
    h_short, h_long = _two_streams(eng)
    want = (collect(h_short), collect(h_long))
    assert eng.stats["failovers"] >= 1
    assert step.router.snapshot()["ejected"] == ["w0"]

    faults.clear()  # the "restarted" worker is reachable again
    time.sleep(0.1)  # probation
    step.router.prefer("w0")
    h_short, h_long = _two_streams(eng)
    got = (collect(h_short), collect(h_long))
    assert got == want
    assert step.router.snapshot()["ejected"] == []
    assert step.router.route("w0") == "w0"  # the rejoined primary serves
    assert any(
        e["event"] == "rejoin" and e["node"] == "w0"
        for e in metrics.flight.snapshot()
    )
    eng.stop()
    step.close()


# ------------------------------------- local migration (paged + injected)


def test_paged_local_migration_recovers_bit_identical():
    """failover_local: a transient backend fault on the PAGED local engine
    migrates live streams in place — outputs bit-identical to a fault-free
    run, the pool drains back to fully free, zero "error" finishes."""
    cfg, params = setup()
    prompts = ["short survivor", "the long victim stream"]

    eng = make_engine(cfg, params, kv_mode="paged", page_size=16)
    handles = [
        eng.submit([Message.user(prompts[0])], 3, GREEDY),
        eng.submit([Message.user(prompts[1])], 24, GREEDY),
    ]
    want = [collect(h) for h in handles]
    eng.stop()

    faults.install(faults.parse("crash@backend.decode:after=3:count=1"))
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16, failover_local=True,
    )
    alloc = eng.backend.allocator
    handles = [
        eng.submit([Message.user(prompts[0])], 3, GREEDY),
        eng.submit([Message.user(prompts[1])], 24, GREEDY),
    ]
    got = [collect(h) for h in handles]
    assert got == want
    assert [h.finish_reason for h in handles] == ["length", "length"]
    assert eng.stats["failovers"] == 1
    assert eng.stats["recovered"] >= 1
    assert eng.stats["stream_errors"] == 0
    assert alloc.pages_free == alloc.pages_total
    eng.stop()


def test_worker_kill_while_lane_spilled_restores_bit_identical():
    """ISSUE 15 chaos: the backend dies while a preempted lane sits
    SPILLED host-side. The live stream rides the failover migration; the
    spilled lane's restore then walks the recovered route — both streams
    bit-identical to a fault-free run, zero "error" finishes, the pool
    drains, and no spilled chain leaks (quiesce-verified)."""
    cfg, params = setup()
    prompts = [
        "alpha prompt padded out to be long " * 2,
        "row two also made quite long here " * 2,
    ]

    def run():
        eng = BatchEngine(
            cfg, params, ByteTokenizer(),
            max_seq_len=256, cache_dtype=jnp.float32,
            serve=ServeConfig(
                max_batch=4, decode_chunk_size=4, admission_window=0.1,
                scheduler="continuous", kv_mode="paged", page_size=16,
                max_pages=14, failover_local=True,
            ),
        )
        eng.start()
        handles = [
            eng.submit([Message.user(p)], 48, GREEDY) for p in prompts
        ]
        out = [collect(h) for h in handles]
        stats = dict(eng.stats)
        assert eng.quiesce()
        with eng._cv:
            assert not eng._spilled  # no leaked spilled chains
        alloc = eng.backend.allocator
        assert alloc.pages_free == alloc.pages_total
        fins = [h.finish_reason for h in handles]
        eng.stop()
        return out, stats, fins

    want, st0, _ = run()
    assert st0["preemptions"] >= 1  # the pressure scenario is real

    # The 11th decode dispatch dies — empirically between the preemption
    # and the restore, so the kill lands while the lane sits spilled (the
    # event-order assertion below keeps the timing honest if shapes move).
    faults.install(faults.parse("crash@backend.decode:after=10:count=1"))
    got, st, fins = run()
    assert got == want  # restore rode the failover bit-identically
    assert fins == ["length", "length"] and st["stream_errors"] == 0
    assert st["failovers"] == 1 and st["preemptions"] >= 1
    assert st["restores"] >= 1
    order = [
        e["event"]
        for e in metrics.flight.snapshot()
        if e["event"] in ("preempted", "failover", "restored")
    ]
    # The flight ring also holds the oracle run's preempt/restore pair;
    # the chaos run's tail is what must read kill-while-spilled: the
    # preemption parked the lane, the failover fired, THEN the restore.
    assert order[-3:] == ["preempted", "failover", "restored"]


def test_local_backend_without_optin_keeps_error_isolation():
    """No failover_local: the PR 6 contract is untouched — an injected
    crash still finishes live streams with "error"."""
    cfg, params = setup()
    faults.install(faults.parse("crash@backend.decode:after=3:count=1"))
    eng = make_engine(cfg, params, kv_mode="paged", page_size=16)
    h = eng.submit([Message.user("the long victim stream")], 24, GREEDY)
    collect(h)
    assert h.finish_reason == "error"
    assert eng.stats["failovers"] == 0
    eng.stop()


# -------------------------------------------------- priority + backpressure


def test_priority_scales_shedding_gates_and_retry_after():
    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(max_batch=2, shed_queue_depth=2, retry_after_s=2.0),
    )
    # Engine NOT started: submissions pile up deterministically.
    eng.submit([Message.user("a")], 4, GREEDY)  # depth 1
    # Low priority sheds at depth >= 2 * 0.5 = 1, and waits twice as long.
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([Message.user("low")], 4, GREEDY, priority=0)
    assert ei.value.retry_after_s == 4.0
    eng.submit([Message.user("b")], 4, GREEDY)  # depth 2 (normal still fits)
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([Message.user("c")], 4, GREEDY)  # normal gate: depth >= 2
    assert ei.value.retry_after_s == 2.0
    # High priority tolerates twice the depth — and waits half as long when
    # it finally sheds.
    eng.submit([Message.user("hi")], 4, GREEDY, priority=2)  # depth 3: fits
    eng.submit([Message.user("hi2")], 4, GREEDY, priority=2)  # depth 4
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([Message.user("hi3")], 4, GREEDY, priority=2)
    assert ei.value.retry_after_s == 1.0
    assert eng.stats["shed"] == 3


def test_backpressure_cancels_unread_stream():
    """A consumer that never drains its handle hits the output-buffer
    watermark: the stream routes into the cancel path (pages freed, lane
    recycled) and the counter moves."""
    cfg, params = setup()
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16,
        decode_chunk_size=2, stream_buffer_tokens=4,
    )
    alloc = eng.backend.allocator
    h = eng.submit([Message.user("nobody is reading this")], 64, GREEDY)
    deadline = time.time() + 30
    while eng.stats["backpressured"] < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert eng.stats["backpressured"] == 1
    ids = collect(h)  # buffered tokens drain, then the cancelled finish
    assert h.finish_reason == "cancelled"
    assert len(ids) < 64
    assert metrics.registry.counter(
        "cake_stream_backpressure_total"
    ).value() == 1
    assert any(
        e["event"] == "stream-backpressure"
        for e in metrics.flight.snapshot(request_id=h.request_id)
    )
    deadline = time.time() + 30
    while alloc.pages_free != alloc.pages_total and time.time() < deadline:
        time.sleep(0.01)
    assert alloc.pages_free == alloc.pages_total
    eng.stop()


# ------------------------------------- prefix cache under faults (ISSUE 8)
# A stream holding FORKED shared pages (runtime/prefix_cache.py) dies in
# every way a stream can die — cancel, backpressure-cancel, failover-migrate
# — and the cache invariants must hold: refcounts return consistent (once
# idle, the pool holds exactly the cache's pages; clear() drains it to fully
# free), no shared page is scribbled (survivor/rerun streams bit-identical),
# no page leaks.

# Short enough that prompt + template fits the 96-slot window with decode
# room, long enough that the cached chain spans several 16-token pages.
# Every suffix below is EXACTLY 8 bytes: equal prompt lengths mean equal
# pads, so all requests land in one cache alignment class (pad % page_size)
# and warm lookups hit — the shared-system-prompt traffic shape.
PREFIX_SHARED = "A shared system preamble on pages."


def prefix_engine(cfg, params, **over):
    over.setdefault("kv_mode", "paged")
    over.setdefault("page_size", 16)
    over.setdefault("prefix_cache", True)
    over.setdefault("decode_chunk_size", 2)
    return make_engine(cfg, params, **over)


def warm_prefix(eng, timeout=30.0):
    """One warmup request leaves the shared chain cached; returns once the
    engine idles with ONLY the cache holding pages (inserts visible)."""
    h = eng.submit([Message.user(PREFIX_SHARED + " warmup.")], 2, GREEDY)
    collect(h)
    wait_cache_idle(eng, timeout)
    assert eng._prefix.stats()["pages"] > 0


def wait_cache_idle(eng, timeout=30.0):
    assert eng.quiesce(timeout), "pool never settled to cache-only pages"


def test_cancel_stream_holding_forked_shared_pages():
    """Cancel a stream whose lane forked cached shared pages mid-decode:
    the co-batched survivor (also forked from the SAME chain) stays
    bit-identical — the cancelled lane never scribbled the shared pages —
    and after the epoch the pool holds exactly the cache's pages; clear()
    drains it fully."""
    cfg, params = setup()
    prompts = [
        PREFIX_SHARED + " victim1",
        PREFIX_SHARED + " surviv1",
    ]
    eng = prefix_engine(cfg, params)
    warm_prefix(eng)
    handles = [eng.submit([Message.user(p)], 24, GREEDY) for p in prompts]
    want = [collect(h) for h in handles]
    assert eng.stats["prefix_hits"] >= 2  # both rows forked the chain
    eng.stop()

    eng = prefix_engine(cfg, params)
    alloc = eng.backend.allocator
    warm_prefix(eng)
    h0 = eng.submit([Message.user(prompts[0])], 24, GREEDY)
    h1 = eng.submit([Message.user(prompts[1])], 24, GREEDY)
    deadline = time.time() + 30
    while h0.completion_tokens < 2 and time.time() < deadline:
        time.sleep(0.005)
    assert eng.cancel(h0.request_id) is True
    got0, got1 = collect(h0), collect(h1)
    assert h0.finish_reason == "cancelled" and len(got0) < 24
    assert got0 == want[0][: len(got0)]  # clean prefix up to the cancel
    assert got1 == want[1]  # survivor bit-identical: no shared-page scribble
    wait_cache_idle(eng)  # refcounts consistent: cache-only pages remain
    eng._prefix.clear()
    assert alloc.pages_free == alloc.pages_total  # zero leaked pages
    eng.stop()


def test_backpressure_cancel_releases_forked_shared_pages():
    """An unread stream holding forked shared pages hits the output-buffer
    watermark and routes into the cancel path: its chain pins release, the
    shared pages survive IN THE CACHE (a later identical request still
    hits), and nothing leaks."""
    cfg, params = setup()
    eng = prefix_engine(cfg, params, stream_buffer_tokens=4)
    alloc = eng.backend.allocator
    warm_prefix(eng)
    hits0 = eng.stats["prefix_hits"]
    h = eng.submit([Message.user(PREFIX_SHARED + " unread.")], 64, GREEDY)
    deadline = time.time() + 30
    while eng.stats["backpressured"] < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert eng.stats["backpressured"] == 1
    ids = collect(h)
    assert h.finish_reason == "cancelled" and len(ids) < 64
    wait_cache_idle(eng)
    assert eng.stats["prefix_hits"] > hits0  # the unread stream HAD forked
    # The chain survived its holder's death: an identical prompt still hits.
    hits1 = eng.stats["prefix_hits"]
    h2 = eng.submit([Message.user(PREFIX_SHARED + " unread.")], 2, GREEDY)
    got = collect(h2)
    assert got and h2.finish_reason in ("stop", "length")
    assert eng.stats["prefix_hits"] > hits1
    wait_cache_idle(eng)
    eng._prefix.clear()
    assert alloc.pages_free == alloc.pages_total
    eng.stop()


def test_failover_migration_with_forked_shared_pages_bit_identical():
    """failover_local + a seeded crash mid-decode while lanes hold forked
    shared pages: migration CLEARS the cache (the rebuilt pool's bytes are
    fresh — chains never outlive their bytes), re-prefills through the same
    cached-chunk arithmetic, and the streams stay bit-identical to the
    fault-free warm run; finish re-inserts the chains; the pool drains."""
    cfg, params = setup()
    prompts = [
        PREFIX_SHARED + " stream1",
        PREFIX_SHARED + " stream2",
    ]
    eng = prefix_engine(cfg, params)
    warm_prefix(eng)
    handles = [eng.submit([Message.user(p)], 16, GREEDY) for p in prompts]
    want = [collect(h) for h in handles]
    eng.stop()

    eng = prefix_engine(cfg, params, failover_local=True)
    alloc = eng.backend.allocator
    warm_prefix(eng)
    # Install AFTER warmup so the crash lands in the warm epoch's decode.
    faults.install(faults.parse("crash@backend.decode:after=2:count=1"))
    handles = [eng.submit([Message.user(p)], 16, GREEDY) for p in prompts]
    got = [collect(h) for h in handles]
    assert got == want  # bit-identical through the migration
    assert [h.finish_reason for h in handles] == ["length", "length"]
    assert eng.stats["failovers"] == 1
    assert eng.stats["stream_errors"] == 0
    assert eng._prefix.counters["clears"] >= 1  # migration dropped the cache
    wait_cache_idle(eng)
    assert eng._prefix.stats()["pages"] > 0  # finish re-inserted the chains
    eng._prefix.clear()
    assert alloc.pages_free == alloc.pages_total
    eng.stop()


def test_epoch_failure_clears_cache_and_frees_pool():
    """PR 6 error isolation + prefix cache: a crash that CANNOT migrate
    finishes live streams as "error", clears the cache (its buffer was not
    retained), and still drains the pool — the next epoch rebuilds from
    zero and serves correctly."""
    cfg, params = setup()
    eng = prefix_engine(cfg, params)
    alloc = eng.backend.allocator
    warm_prefix(eng)
    want = None
    faults.install(faults.parse("crash@backend.decode:after=2:count=1"))
    h = eng.submit([Message.user(PREFIX_SHARED + " victim1")], 24, GREEDY)
    got = collect(h)
    assert h.finish_reason == "error" and len(got) < 24
    deadline = time.time() + 30
    while alloc.pages_free != alloc.pages_total and time.time() < deadline:
        time.sleep(0.01)
    assert alloc.pages_free == alloc.pages_total  # cache cleared too
    assert eng._prefix.stats()["pages"] == 0
    # The engine serves on: a fresh (cold) epoch completes and re-caches.
    h2 = eng.submit([Message.user(PREFIX_SHARED + " victim1")], 8, GREEDY)
    want = collect(h2)
    assert want and h2.finish_reason in ("stop", "length")
    wait_cache_idle(eng)
    assert eng._prefix.stats()["pages"] > 0
    eng.stop()


# --------------------------------------- stuck-epoch watchdog (ISSUE 11)


@pytest.mark.parametrize("scheduler", ["epoch", "continuous"])
def test_watchdog_isolates_stalled_backend_within_epoch_stall(scheduler):
    """A backend that stalls WITHOUT raising (the PR 6 ``stall`` fault
    kind) would park the engine thread forever — the watchdog converts it
    to the PR 6 error-isolation path within ``epoch_stall_s``: co-batched
    streams that already finished are bit-identical, the victim gets a
    clean ``"error"`` finish (not a hang), and the engine serves the next
    epoch. Both scheduler shapes (ISSUE 15: every PR 10 failure path
    survives the continuous scheduler)."""
    cfg, params = setup()
    # Fault-free oracle (watchdog off).
    eng = make_engine(cfg, params, scheduler=scheduler)
    h_s = eng.submit([Message.user("survivor stream")], 2, GREEDY)
    h_l = eng.submit([Message.user("the long victim stream")], 16, GREEDY)
    want_short, want_long = collect(h_s), collect(h_l)
    eng.stop()
    assert len(want_long) > 6  # the stall must land mid-stream

    eng = make_engine(cfg, params, epoch_stall_s=1.5, scheduler=scheduler)
    try:
        # Warm every jit shape first: a first-call compile on the watchdog
        # thread must not read as a stall.
        h_s = eng.submit([Message.user("survivor stream")], 2, GREEDY)
        h_l = eng.submit([Message.user("the long victim stream")], 16, GREEDY)
        assert (collect(h_s), collect(h_l)) == (want_short, want_long)
        # The second decode chunk hangs for 8s — far past epoch_stall_s.
        faults.install(
            faults.parse("stall@backend.decode:after=1:count=1:delay_s=8")
        )
        t0 = time.monotonic()
        h_s = eng.submit([Message.user("survivor stream")], 2, GREEDY)
        h_l = eng.submit([Message.user("the long victim stream")], 16, GREEDY)
        got_short, got_long = collect(h_s), collect(h_l)
        dt = time.monotonic() - t0
        faults.clear()
        # Detection within the bound, not the 8s stall.
        assert dt < 6.0, f"stall took {dt:.1f}s to isolate"
        assert got_short == want_short
        assert h_s.finish_reason in ("stop", "length")
        assert h_l.finish_reason == "error"
        assert got_long == want_long[: len(got_long)]
        assert len(got_long) < len(want_long)
        assert eng.stats["epoch_stalls"] == 1
        assert metrics.registry.counter(
            "cake_epoch_stalls_total"
        ).value() == 1
        assert any(
            e["event"] == "epoch-stall" for e in metrics.flight.snapshot()
        )
        # The engine survived: the next epoch (fresh watchdog thread)
        # serves bit-identically.
        h = eng.submit([Message.user("survivor stream")], 2, GREEDY)
        assert collect(h) == want_short
    finally:
        faults.clear()
        eng.stop()


# ------------------------------------------ overload storm (ISSUE 11)


@pytest.mark.parametrize("scheduler", ["epoch", "continuous"])
def test_overload_storm_fair_engine_bounds_compliant_latency(scheduler):
    """The tier-1 storm gate: an abusive tenant floods a fair paged
    engine. Quotas 429 the overflow with consistent Retry-After hints,
    every compliant stream finishes cleanly within a bounded factor of
    its isolated latency, a deadline-doomed request expires without
    mapping a page, and the pool drains to fully-free. Both scheduler
    shapes (ISSUE 15)."""
    from cake_tpu.runtime.admission import QuotaExceeded

    cfg, params = setup()
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(
            max_batch=4, decode_chunk_size=4, admission_window=0.02,
            kv_mode="paged", page_size=16, scheduler=scheduler,
            tenant_rate=40.0, tenant_burst=150.0,
        ),
    )
    eng.start()
    alloc = eng.backend.allocator
    sampled = SamplingConfig(temperature=0.8, repeat_penalty=1.0, seed=3)

    def timed(tenant):
        t0 = time.monotonic()
        h = eng.submit(
            [Message.user("compliant request")], 3, GREEDY, tenant=tenant
        )
        toks = collect(h)
        return time.monotonic() - t0, toks, h

    try:
        timed("warm")  # compile everything outside the clocks
        iso_s, want_good, _ = timed("good-iso")

        # Slow decode chunks slightly so the storm epoch reliably outlives
        # the doomed request's deadline on a warm cache.
        faults.install(
            faults.parse("stall@backend.decode:count=0:delay_s=0.01")
        )
        plug = eng.submit(
            [Message.user("storm plug stream")], 40, GREEDY, tenant="plug"
        )
        # Let the plug's decode get going before the flood lands (a
        # scheduler-agnostic progress signal: continuous mode serves the
        # whole plug as ONE segment, so "batches" never reaches 4 there).
        deadline = time.monotonic() + 10.0
        while plug.completion_tokens < 8 and time.monotonic() < deadline:
            time.sleep(0.002)
        abuse, refusals = [], []
        for i in range(10):
            try:
                abuse.append(
                    eng.submit(
                        [Message.user(f"abusive flood request {i:02d}")], 3,
                        GREEDY, tenant="abuser",
                    )
                )
            except QuotaExceeded as e:
                refusals.append(e.retry_after_s)
        # A request whose 50ms deadline cannot survive the storm: either
        # the deadline-aware shed refuses it on the spot (the estimator
        # already knows the queue wait dwarfs it) or it queues and expires
        # unadmitted — both end with zero tokens, no lane, no pages.
        doomed = None
        try:
            doomed = eng.submit(
                [Message.user("doomed by deadline")], 8, sampled,
                tenant="late", deadline_s=0.05,
            )
        except EngineOverloaded as e:
            assert "deadline" in str(e)
        results = {}

        def consume(tag, h):
            results[tag] = (time.monotonic(), collect(h))

        threads = [
            threading.Thread(
                target=consume, args=(f"abuse{i}", h), daemon=True
            )
            for i, h in enumerate(abuse)
        ]
        t0 = time.monotonic()
        hg = eng.submit(
            [Message.user("compliant request")], 3, GREEDY, tenant="good"
        )
        threads.append(
            threading.Thread(target=consume, args=("good", hg), daemon=True)
        )
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        assert not any(t.is_alive() for t in threads)
        storm_s = results["good"][0] - t0
        collect(plug)
        if doomed is not None:
            collect(doomed)
        faults.clear()

        # Quotas: the flood overflow was 429'd with consistent hints.
        assert len(refusals) >= 1
        assert all(r > 0 for r in refusals)
        assert max(refusals) - min(refusals) < 2.0
        # Fairness: the compliant stream finished cleanly, bit-identical,
        # within a bounded factor of its isolated latency.
        assert results["good"][1] == want_good
        assert hg.finish_reason in ("stop", "length")
        assert storm_s < max(2.0, 15.0 * iso_s), (
            f"compliant latency {storm_s:.2f}s vs isolated {iso_s:.2f}s"
        )
        # Every admitted abuser stream also finished cleanly (quota and
        # fairness shape WHEN they run, never break them).
        assert all(h.finish_reason in ("stop", "length") for h in abuse)
        # The doomed request never ran: no lane, no pages, no tokens —
        # whether it was shed up front or expired in the queue.
        if doomed is not None:
            assert doomed.finish_reason == "deadline"
            assert doomed.completion_tokens == 0
        else:
            assert eng.stats["shed"] >= 1
        # And the pool drains to fully-free.
        assert eng.quiesce(10.0)
        assert alloc.pages_free == alloc.pages_total
    finally:
        faults.clear()
        eng.stop()
