"""Real ``tokenizer.json`` fixture through the HFTokenizer path.

The reference loads HF tokenizer.json via the tokenizers crate
(llama.rs:19-32); this framework's HFTokenizer wraps the Python package. A
checked-in 2 MB Llama-3 vocab would be dead weight, so the fixture builds a
REAL byte-level-BPE tokenizer.json with the ``tokenizers`` library at test
time — same file format, same added-special-token mechanics (the chat-template
markers must encode to single ids, exactly as Meta's file declares them).
"""

import pytest

tokenizers = pytest.importorskip("tokenizers")

from cake_tpu.models.llama.chat import (
    BEGIN_OF_TEXT,
    END_HEADER,
    EOT,
    Message,
    START_HEADER,
    encode_dialog_to_prompt,
)
from cake_tpu.models.llama.tokenizer import HFTokenizer, load_tokenizer

SPECIALS = [BEGIN_OF_TEXT, START_HEADER, END_HEADER, EOT, "<|end_of_text|>"]


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory):
    """A model dir holding a real tokenizer.json (trained tiny BPE +
    Llama-3-style special tokens)."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers, decoders

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        special_tokens=[],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "you are a helpful assistant",
        "hello there, how are you today?",
        "system user assistant",
    ]
    tok.train_from_iterator(corpus, trainer)
    tok.add_special_tokens(SPECIALS)
    d = tmp_path_factory.mktemp("ckpt")
    tok.save(str(d / "tokenizer.json"))
    return d


def test_load_tokenizer_picks_hf_file(model_dir):
    t = load_tokenizer(model_dir)
    assert isinstance(t, HFTokenizer)
    # Trained BPE (tiny corpus caps merges below the requested 400) + the 5
    # added specials; anything above the byte alphabet proves real merges.
    assert t.vocab_size > 256 + len(SPECIALS)


def test_special_markers_encode_to_single_ids(model_dir):
    """The template markers are added tokens: one id each, never split —
    the property Meta's tokenizer.json declares and history.rs relies on."""
    t = load_tokenizer(model_dir)
    for marker in SPECIALS:
        ids = t.encode(marker)
        assert len(ids) == 1, (marker, ids)


def test_dialog_encoding_matches_tokenizers_direct(model_dir):
    """Our wrapper must add nothing: byte-for-byte agreement with the
    tokenizers library used directly on the rendered template."""
    from tokenizers import Tokenizer

    t = load_tokenizer(model_dir)
    direct = Tokenizer.from_file(str(model_dir / "tokenizer.json"))
    prompt = encode_dialog_to_prompt(
        [Message.system("you are a helpful assistant"), Message.user("hello there")]
    )
    assert t.encode(prompt) == direct.encode(prompt, add_special_tokens=False).ids


def test_roundtrip_plain_text(model_dir):
    t = load_tokenizer(model_dir)
    text = "hello there, how are you today?"
    assert t.decode(t.encode(text)) == text
