"""Request-log unit tests: schema validation, the bounded ring, the
JSONL sink, and the replay loader (obs/requestlog.py).

Stdlib-only module — no jax, no server; the engine-side wiring is
covered by the loadgen smoke and the serving tests.
"""

import json

import pytest

from cake_tpu.obs.requestlog import RequestLog, load_trace
from cake_tpu.obs.taxonomy import (
    REQUEST_LOG_FIELDS,
    REQUEST_OUTCOMES,
    REQUEST_SLO_VERDICTS,
)


def _rec(log: RequestLog, **over):
    fields = {
        "request_id": "chatcmpl-1",
        "tenant": "default",
        "finish_reason": "stop",
    }
    fields.update(over)
    return log.record(**fields)


class TestSchemaValidation:
    def test_unknown_field_raises(self):
        log = RequestLog()
        with pytest.raises(ValueError, match="latency_bucket"):
            _rec(log, latency_bucket="fast")

    def test_caller_cannot_stamp_seq(self):
        log = RequestLog()
        with pytest.raises(ValueError, match="seq"):
            log.record(
                seq=99, request_id="r", tenant="t", finish_reason="stop"
            )

    @pytest.mark.parametrize(
        "missing", ["request_id", "tenant", "finish_reason"]
    )
    def test_identity_fields_required(self, missing):
        log = RequestLog()
        with pytest.raises(ValueError, match=missing):
            _rec(log, **{missing: None})

    def test_finish_vocabulary_enforced(self):
        log = RequestLog()
        with pytest.raises(ValueError, match="evaporated"):
            _rec(log, finish_reason="evaporated")
        for finish in REQUEST_OUTCOMES:
            _rec(log, finish_reason=finish)

    def test_slo_vocabulary_enforced_and_defaulted(self):
        log = RequestLog()
        with pytest.raises(ValueError, match="fine"):
            _rec(log, slo="fine")
        for verdict in REQUEST_SLO_VERDICTS:
            _rec(log, slo=verdict)
        assert _rec(log)["slo"] == "none"

    def test_every_registered_field_accepted(self):
        log = RequestLog()
        fields = dict.fromkeys(REQUEST_LOG_FIELDS, 1)
        fields.pop("seq")
        fields.update(
            request_id="r", tenant="t", finish_reason="stop", slo="ok"
        )
        assert log.record(**fields)["seq"] == 1

    def test_t_wall_stamped_from_injected_clock(self):
        log = RequestLog(time_fn=lambda: 1234.5678)
        assert _rec(log)["t_wall"] == 1234.568
        # A caller-supplied wall time wins (the engine knows better).
        assert _rec(log, t_wall=99.0)["t_wall"] == 99.0


class TestRing:
    def test_bounded_with_monotonic_seq(self):
        log = RequestLog(keep=4)
        for i in range(10):
            _rec(log, request_id=f"r{i}")
        assert len(log) == 4
        assert log.last_seq == 10
        assert [r["seq"] for r in log.snapshot()] == [7, 8, 9, 10]
        assert log.stats() == {
            "count": 4, "capacity": 4, "last_seq": 10, "jsonl": None,
        }

    def test_keep_validated(self):
        with pytest.raises(ValueError):
            RequestLog(keep=0)

    def test_snapshot_filters(self):
        log = RequestLog()
        _rec(log, request_id="a", tenant="alice")
        _rec(log, request_id="b", tenant="bob", finish_reason="quota")
        _rec(log, request_id="c", tenant="alice", finish_reason="length")
        assert [r["request_id"] for r in log.snapshot(tenant="alice")] == [
            "a", "c",
        ]
        assert [r["request_id"] for r in log.snapshot(finish="quota")] == [
            "b",
        ]
        assert [r["seq"] for r in log.snapshot(since=1)] == [2, 3]
        assert [r["seq"] for r in log.snapshot(limit=2)] == [2, 3]
        assert log.snapshot(tenant="alice", since=1, limit=1) == [
            log.snapshot()[-1]
        ]

    def test_clear_resets_cursor(self):
        log = RequestLog()
        _rec(log)
        log.clear()
        assert len(log) == 0 and log.last_seq == 0
        assert _rec(log)["seq"] == 1


class TestJsonlSink:
    def test_roundtrip_through_load_trace(self, tmp_path):
        path = str(tmp_path / "cap.requestlog.jsonl")
        log = RequestLog()
        log.attach_jsonl(path)
        _rec(log, request_id="a", t_wall=10.0, prompt_tokens=7)
        _rec(log, request_id="b", t_wall=12.5, tenant="bob")
        trace = load_trace(path)
        assert [r["request_id"] for r in trace] == ["a", "b"]
        assert trace[0]["prompt_tokens"] == 7
        assert trace == log.snapshot()

    def test_append_mode_extends_across_attaches(self, tmp_path):
        path = str(tmp_path / "cap.jsonl")
        log = RequestLog()
        log.attach_jsonl(path)
        _rec(log, request_id="a", t_wall=1.0)
        log.attach_jsonl(None)
        _rec(log, request_id="skipped", t_wall=2.0)
        log.attach_jsonl(path)
        _rec(log, request_id="b", t_wall=3.0)
        assert [r["request_id"] for r in load_trace(path)] == ["a", "b"]

    def test_load_trace_sorts_and_skips_junk(self, tmp_path):
        path = tmp_path / "cap.jsonl"
        lines = [
            json.dumps({"request_id": "late", "t_wall": 9.0, "seq": 2}),
            "{truncated",
            json.dumps(["not", "a", "dict"]),
            json.dumps({"t_wall": 1.0}),          # no request_id: dropped
            json.dumps({"request_id": "x"}),       # no t_wall: dropped
            json.dumps({"request_id": "early", "t_wall": 2.0, "seq": 1}),
            "",
        ]
        path.write_text("\n".join(lines) + "\n")
        assert [r["request_id"] for r in load_trace(str(path))] == [
            "early", "late",
        ]

    def test_unwritable_sink_detaches_instead_of_raising(self, tmp_path):
        log = RequestLog()
        log.attach_jsonl(str(tmp_path / "no" / "such" / "dir" / "x.jsonl"))
        rec = _rec(log)
        # The record landed in the ring; the dead sink detached itself.
        assert rec["seq"] == 1 and len(log) == 1
        assert log.stats()["jsonl"] is None
