"""API server and CLI tests."""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.api import CHAT_ROUTE, ApiServer


@pytest.fixture(scope="module")
def server():
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=96, cache_dtype=jnp.float32)
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    api = ApiServer(gen, model_name="tiny-test", default_max_tokens=6)
    httpd = api.make_server("127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}"
    httpd.shutdown()


def post(url, body, raw=False):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=120)
    data = resp.read()
    return data if raw else json.loads(data)


def test_chat_completion_response_shape(server):
    out = post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 4},
    )
    # Reference response shape (api/mod.rs:26-62) + usage extension.
    assert out["object"] == "chat.completion"
    assert out["id"].startswith("chatcmpl-")
    assert out["model"] == "tiny-test"
    choice = out["choices"][0]
    assert choice["index"] == 0
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert out["usage"]["completion_tokens"] >= 1
    assert (
        out["usage"]["total_tokens"]
        == out["usage"]["prompt_tokens"] + out["usage"]["completion_tokens"]
    )


def test_chat_deterministic_across_requests(server):
    body = {"messages": [{"role": "user", "content": "same prompt"}]}
    a = post(server + CHAT_ROUTE, body)
    b = post(server + CHAT_ROUTE, body)
    # Greedy + per-request reset => identical output (exercises state isolation).
    assert a["choices"][0]["message"]["content"] == b["choices"][0]["message"]["content"]


def test_streaming_sse(server):
    raw = post(
        server + CHAT_ROUTE,
        {
            "messages": [{"role": "user", "content": "stream it"}],
            "stream": True,
            "max_tokens": 4,
        },
        raw=True,
    ).decode()
    events = [
        json.loads(line[len("data: ") :])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    assert raw.rstrip().endswith("data: [DONE]")
    assert all(e["object"] == "chat.completion.chunk" for e in events)
    assert events[0]["choices"][0]["delta"].get("role") == "assistant"
    assert events[-1]["choices"][0]["finish_reason"] in ("stop", "length")
    streamed = "".join(
        e["choices"][0]["delta"].get("content", "") for e in events
    )
    # Streamed concatenation equals the non-streaming result for the same prompt.
    full = post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "stream it"}], "max_tokens": 4},
    )
    assert streamed == full["choices"][0]["message"]["content"]


def test_concurrent_requests_both_valid(server):
    results = {}

    def hit(key, prompt):
        results[key] = post(
            server + CHAT_ROUTE,
            {"messages": [{"role": "user", "content": prompt}], "max_tokens": 3},
        )

    threads = [
        threading.Thread(target=hit, args=(i, f"prompt {i}")) for i in range(3)
    ]
    [t.start() for t in threads]
    [t.join(timeout=120) for t in threads]
    assert len(results) == 3
    for r in results.values():
        assert r["object"] == "chat.completion"


def test_per_request_sampling_override_takes_effect(server):
    # Server default is greedy (temperature=0). A high-temperature request must
    # actually change sampling (regression: jit once baked the first config's
    # constants into the sampler forever).
    body_greedy = {
        "messages": [{"role": "user", "content": "override test"}],
        "max_tokens": 6,
    }
    greedy = post(server + CHAT_ROUTE, body_greedy)["choices"][0]["message"][
        "content"
    ]
    hot_outputs = {
        post(
            server + CHAT_ROUTE,
            {**body_greedy, "temperature": 5.0, "seed": seed},
        )["choices"][0]["message"]["content"]
        for seed in range(5)
    }
    assert len(hot_outputs) > 1 or hot_outputs != {greedy}
    # And greedy again afterwards: defaults restored.
    assert (
        post(server + CHAT_ROUTE, body_greedy)["choices"][0]["message"]["content"]
        == greedy
    )


def test_null_sampling_fields_treated_as_unset(server):
    out = post(
        server + CHAT_ROUTE,
        {
            "messages": [{"role": "user", "content": "nulls"}],
            "temperature": None,
            "top_p": None,
            "seed": None,
            "max_tokens": 3,
        },
    )
    assert out["object"] == "chat.completion"


def test_finish_reason_length_on_truncation(server):
    out = post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "long"}], "max_tokens": 2},
    )
    assert out["choices"][0]["finish_reason"] == "length"


def test_unknown_route_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server + "/api/v1/other", {})
    assert e.value.code == 404


def test_empty_messages_400(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server + CHAT_ROUTE, {"messages": []})
    assert e.value.code == 400


def test_malformed_body_400(server):
    req = urllib.request.Request(
        server + CHAT_ROUTE,
        data=b"{not json",
        headers={"Content-Type": "application/json"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_health(server):
    with urllib.request.urlopen(server + "/health", timeout=30) as r:
        out = json.loads(r.read())
    assert out["status"] == "ok"


def test_stats_endpoint(server):
    from cake_tpu.utils import trace

    with trace.span("test.stats.probe"):
        pass
    with urllib.request.urlopen(server + "/stats", timeout=30) as r:
        out = json.loads(r.read())
    assert out["spans"]["test.stats.probe"]["count"] >= 1
    assert out["memory"].get("host_peak_rss_bytes", 0) > 0


# ---------------------------------------------------------------- CLI


def test_cli_parser_covers_reference_flags():
    from cake_tpu.cli import build_parser

    p = build_parser()
    args = p.parse_args(
        [
            "--model", "/m",
            "--mode", "worker",
            "--name", "w1",
            "--address", "0.0.0.0:10128",
            "--topology", "/t.yml",
            "--prompt", "hello",
            "--system-prompt", "sys",
            "--seed", "7",
            "-n", "50",
            "--temperature", "0.7",
            "--top-p", "0.9",
            "--top-k", "40",
            "--repeat-penalty", "1.3",
            "--repeat-last-n", "64",
            "--dtype", "f32",
            "--cpu",
            "--device", "1",
        ]
    )
    assert args.mode == "worker" and args.seed == 7 and args.sample_len == 50
    assert args.top_k == 40 and args.dtype == "f32" and args.cpu
    assert args.device == 1


def test_cli_distributed_flag_validation(capsys):
    """--distributed parses COORD,N,I and demands the mesh backend (the
    joining itself is covered by tests/test_multihost.py)."""
    from cake_tpu.cli import main

    rc = main(["--model", "/nope", "--distributed", "bad-spec"])
    assert rc == 2
    assert "COORDINATOR" in capsys.readouterr().err

    rc = main(
        ["--model", "/nope", "--distributed", "127.0.0.1:1,2,0", "--backend", "tcp"]
    )
    assert rc == 2
    assert "--backend mesh" in capsys.readouterr().err


def test_cli_device_ordinal_pins_and_validates(tmp_path, capsys):
    """--device N places single-device compute on jax.devices()[N]; an
    out-of-range ordinal is a clean error (utils/mod.rs:15-30 parity)."""
    from cake_tpu.cli import main

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    save_tiny_checkpoint(tmp_path / "model", params, cfg)
    common = [
        "--model", str(tmp_path / "model"),
        "--prompt", "hi",
        "-n", "2",
        "--temperature", "0",
        "--dtype", "f32",
        "--max-seq-len", "96",
    ]
    try:
        assert main(common + ["--device", "3"]) == 0
        capsys.readouterr()
        # The pinned default device now hosts fresh computations.
        assert jax.numpy.zeros(()).devices() == {jax.devices()[3]}

        rc = main(common + ["--device", "99"])
        assert rc == 2
        assert "out of range" in capsys.readouterr().err
    finally:
        jax.config.update("jax_default_device", None)


def test_cli_one_shot_generation(tmp_path, capsys):
    from cake_tpu.cli import main

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    save_tiny_checkpoint(tmp_path / "model", params, cfg)
    rc = main(
        [
            "--model", str(tmp_path / "model"),
            "--prompt", "hi",
            "-n", "3",
            "--temperature", "0",
            "--dtype", "f32",
            "--max-seq-len", "96",
        ]
    )
    assert rc == 0


def test_cli_stats_subcommand_renders_table(server, capsys):
    """``cake-tpu stats --count 1`` polls /stats and renders the table
    without demanding --model (it is a thin HTTP poller)."""
    from cake_tpu.cli import main
    from cake_tpu.utils import metrics

    post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "table"}], "max_tokens": 2},
    )
    metrics.registry.counter("cake_probe_total").inc(7)
    rc = main(["stats", "--url", server, "--count", "1", "--no-clear"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "model=tiny-test" in out
    assert "cake_prefill_seconds" in out
    assert "p99_ms" in out
    assert "cake_probe_total" in out


def test_cli_stats_subcommand_unreachable_server(capsys):
    from cake_tpu.cli import main

    rc = main(["stats", "--url", "http://127.0.0.1:9", "--count", "1"])
    assert rc == 1
    assert "poll" in capsys.readouterr().err


def test_cli_worker_requires_topology(tmp_path, capsys):
    from cake_tpu.cli import main

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(1), jnp.float32)
    save_tiny_checkpoint(tmp_path / "model", params, cfg)
    rc = main(["--model", str(tmp_path / "model"), "--mode", "worker"])
    assert rc == 2


def test_models_endpoint(server):
    """OpenAI SDK discovery surface: GET /api/v1/models lists the loaded
    model in the list-envelope shape."""
    with urllib.request.urlopen(server + "/api/v1/models", timeout=30) as r:
        out = json.loads(r.read())
    assert out["object"] == "list"
    (entry,) = out["data"]
    assert entry["object"] == "model"
    assert entry["id"]
    assert isinstance(entry["created"], int)


def test_metrics_endpoint(server):
    """Prometheus text exposition at /metrics: span summaries (count/sum
    pairs) that scrapers can point at the serving port."""
    from cake_tpu.utils import trace

    with trace.span("test.metrics.probe"):
        pass
    with urllib.request.urlopen(server + "/metrics", timeout=30) as r:
        assert r.headers["Content-Type"].startswith("text/plain")
        body = r.read().decode()
    assert "# TYPE cake_span_seconds summary" in body
    assert 'cake_span_seconds_count{span="test.metrics.probe"}' in body
    assert 'cake_span_seconds_sum{span="test.metrics.probe"}' in body


def _scrape(server: str) -> str:
    with urllib.request.urlopen(server + "/metrics", timeout=30) as r:
        return r.read().decode()


def test_metrics_exposition_contract(server):
    """Parse /metrics line-by-line: label escaping, TYPE correctness, HELP
    presence, monotone cumulative histogram buckets, build info + uptime."""
    from cake_tpu.utils import metrics, trace

    nasty = 'quo"te\\slash\nnewline'
    with trace.span(nasty):
        pass
    metrics.registry.histogram(
        "cake_probe_seconds", "probe latency", buckets=(0.01, 1.0)
    ).observe(0.005)
    metrics.registry.histogram("cake_probe_seconds").observe(0.5)
    metrics.registry.histogram("cake_probe_seconds").observe(9.0)
    metrics.registry.counter("cake_probe_total", "probe counter").inc(3)
    metrics.registry.gauge("cake_probe_level", "probe gauge").set(2)
    body = _scrape(server)

    # Every line is a comment or a `series value` pair — no raw newlines
    # from the nasty label broke the line discipline.
    types: dict[str, str] = {}
    series: dict[str, str] = {}
    for line in body.splitlines():
        assert line, "blank line in exposition"
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
        elif not line.startswith("#"):
            name, val = line.rsplit(" ", 1)
            float(val)  # parseable value
            series[name] = val

    # Label escaping: backslash, quote, and newline all escaped in-place.
    assert (
        'cake_span_seconds_count{span="quo\\"te\\\\slash\\nnewline"}' in series
    )

    # TYPE correctness per family.
    assert types["cake_probe_total"] == "counter"
    assert types["cake_probe_level"] == "gauge"
    assert types["cake_probe_seconds"] == "histogram"
    assert types["cake_build_info"] == "gauge"
    assert types["cake_uptime_seconds"] == "gauge"
    assert types["cake_span_seconds"] == "summary"

    # Self-describing scrape: a HELP line for every TYPE'd family.
    helps = {
        line.split(" ", 3)[2]
        for line in body.splitlines()
        if line.startswith("# HELP ")
    }
    assert set(types) <= helps

    # Histogram contract: cumulative monotone buckets, +Inf == _count.
    buckets = [
        int(series[f'cake_probe_seconds_bucket{{le="{le}"}}'])
        for le in ("0.01", "1", "+Inf")
    ]
    assert buckets == sorted(buckets) == [1, 2, 3]
    assert buckets[-1] == int(series["cake_probe_seconds_count"])
    assert float(series["cake_probe_seconds_sum"]) == pytest.approx(9.505)

    # Build info + uptime (satellite: self-describing scrapes).
    assert 'model="tiny-test"' in body
    info_line = next(
        l for l in body.splitlines() if l.startswith("cake_build_info")
    )
    assert info_line.endswith(" 1")
    assert float(series["cake_uptime_seconds"]) >= 0.0


def test_request_latency_histogram_on_metrics(server):
    """Acceptance: a served request surfaces at least one cake_*_seconds
    histogram with cumulative _bucket/_sum/_count series on /metrics."""
    post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "measured"}], "max_tokens": 3},
    )
    body = _scrape(server)
    assert "# TYPE cake_prefill_seconds histogram" in body
    assert 'cake_prefill_seconds_bucket{le="+Inf"}' in body
    assert "cake_prefill_seconds_sum" in body
    assert "cake_prefill_seconds_count" in body
    assert "# TYPE cake_decode_step_seconds histogram" in body


def test_events_endpoint_serialized_path(server):
    """GET /events: the flight recorder's ring, filterable by the chat
    response id (the serialized path records submitted/finished)."""
    out = post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "flight"}], "max_tokens": 3},
    )
    rid = out["id"]
    with urllib.request.urlopen(server + "/events", timeout=30) as r:
        all_events = json.loads(r.read())
    assert all_events["capacity"] > 0
    assert all_events["count"] == len(all_events["events"])
    with urllib.request.urlopen(
        server + "/events?request_id=" + rid, timeout=30
    ) as r:
        mine = json.loads(r.read())["events"]
    assert [e["event"] for e in mine] == ["submitted", "finished"]
    assert mine[0]["prompt_tokens"] == out["usage"]["prompt_tokens"]
    assert mine[1]["completion_tokens"] == out["usage"]["completion_tokens"]


def test_stats_includes_metrics_snapshot(server):
    post(
        server + CHAT_ROUTE,
        {"messages": [{"role": "user", "content": "snap"}], "max_tokens": 2},
    )
    with urllib.request.urlopen(server + "/stats", timeout=30) as r:
        out = json.loads(r.read())
    assert out["uptime_s"] >= 0
    hists = {h["name"] for h in out["metrics"]["histograms"]}
    assert "cake_prefill_seconds" in hists
    for h in out["metrics"]["histograms"]:
        assert {"count", "sum", "mean", "p50", "p90", "p99"} <= set(h)


def test_trace_endpoint_and_cli_export(server, tmp_path):
    """GET /trace returns Perfetto-loadable trace-event JSON and the
    `cake-tpu trace` subcommand (thin HTTP + stdlib, no --model/jax) fetches,
    writes, and schema-validates it."""
    from cake_tpu.cli import main
    from cake_tpu.obs.timeline import timeline, validate_export

    # The server shares this process's global timeline: land a span tree the
    # route must render (the serving engine does this for real requests).
    with timeline.span("epoch", rid="chatcmpl-trace-test", track="engine"):
        with timeline.span("prefill", track="engine"):
            pass
    with urllib.request.urlopen(server + "/trace", timeout=30) as r:
        trace = json.loads(r.read())
    assert validate_export(trace) == []
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] != "M"}
    assert {"epoch", "prefill"} <= names
    # Filtered fetch: only the tagged request's spans.
    with urllib.request.urlopen(
        server + "/trace?request_id=chatcmpl-trace-test", timeout=30
    ) as r:
        mine = json.loads(r.read())
    assert validate_export(mine) == []
    assert any(
        e.get("args", {}).get("request_id") == "chatcmpl-trace-test"
        for e in mine["traceEvents"]
    )

    out = tmp_path / "t.json"
    rc = main(["trace", "--url", server, "--out", str(out), "--validate"])
    assert rc == 0
    assert validate_export(json.loads(out.read_text())) == []


def test_trace_cli_offline_jsonl_mode(tmp_path, capsys):
    """`cake-tpu trace --jsonl` renders a --trace-jsonl stream offline."""
    from cake_tpu.cli import main
    from cake_tpu.obs.timeline import Timeline, validate_export

    jsonl = tmp_path / "t.jsonl"
    tl = Timeline()
    tl.attach_jsonl(str(jsonl))
    with tl.span("decode-chunk", rid="req-1", track="engine"):
        pass
    out = tmp_path / "t.json"
    rc = main(["trace", "--jsonl", str(jsonl), "--out", str(out),
               "--validate"])
    assert rc == 0
    trace = json.loads(out.read_text())
    assert validate_export(trace) == []
    assert any(e.get("name") == "decode-chunk" for e in trace["traceEvents"])
    assert "wrote" in capsys.readouterr().out


def test_cli_stats_spans_view(server, capsys):
    """`cake-tpu stats --spans`: top spans by total/self time from the
    timeline aggregate in /stats."""
    from cake_tpu.cli import main
    from cake_tpu.obs.timeline import timeline

    with timeline.span("epoch", track="engine"):
        with timeline.span("decode-chunk", track="engine"):
            pass
    rc = main(["stats", "--url", server, "--count", "1", "--no-clear",
               "--spans"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "model=tiny-test" in out
    assert "epoch" in out and "decode-chunk" in out
    assert "self_ms" in out


# ----------------------------------------------------- failure-semantics API
# Cancellation route + load-shedding 503: the engine seam is duck-typed, so
# a stub engine pins the HTTP contract without spinning a real decode loop
# (tests/test_chaos.py covers the real engine behavior).


class _StubEngine:
    """Duck-typed BatchEngine surface the ApiServer touches."""

    def __init__(self, overloaded=False):
        self.overloaded = overloaded
        self.over_quota = False
        self.cancelled: list[str] = []
        self.priorities: list[int | None] = []
        self.tenants: list[str | None] = []
        self.deadlines: list[float | None] = []
        self.stats = {"batches": 0}

    def start(self):
        pass

    def submit(
        self, messages, max_tokens, sampling, request_id=None, priority=None,
        tenant=None, deadline_s=None,
    ):
        from cake_tpu.runtime.admission import QuotaExceeded
        from cake_tpu.runtime.serving import EngineOverloaded

        self.priorities.append(priority)
        self.tenants.append(tenant)
        self.deadlines.append(deadline_s)
        if self.over_quota:
            raise QuotaExceeded(
                "tenant 'abuser' over its token rate", retry_after_s=2.4,
                tenant="abuser", kind="rate",
            )
        if self.overloaded:
            raise EngineOverloaded(
                "engine overloaded: queue depth 8 >= 8", retry_after_s=2.0
            )
        raise AssertionError("stub engine only tests refusal paths")

    def tenant_stats(self):
        return {"abuser": {"active_streams": 1, "quota_refusals": 2}}

    def cancel(self, request_id: str) -> bool:
        self.cancelled.append(request_id)
        return request_id.startswith("chatcmpl-")


@pytest.fixture()
def stub_server():
    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=96, cache_dtype=jnp.float32)
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    engine = _StubEngine()
    api = ApiServer(gen, model_name="tiny-test", engine=engine)
    httpd = api.make_server("127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", engine
    httpd.shutdown()


def test_cancel_route_hits_engine(stub_server):
    url, engine = stub_server
    out = post(url + "/api/v1/cancel", {"id": "chatcmpl-abc"})
    assert out == {"id": "chatcmpl-abc", "cancelled": True}
    assert engine.cancelled == ["chatcmpl-abc"]
    # Unknown ids answer honestly instead of 404-ing (cancel is idempotent).
    out = post(url + "/api/v1/cancel", {"request_id": "nope"})
    assert out == {"id": "nope", "cancelled": False}


def test_cancel_route_requires_id_and_engine(stub_server, server):
    url, _ = stub_server
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(url + "/api/v1/cancel", {})
    assert ei.value.code == 400
    # The serialized (no-engine) server refuses with a clear message.
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(server + "/api/v1/cancel", {"id": "chatcmpl-abc"})
    assert ei.value.code == 400
    assert "engine" in json.loads(ei.value.read())["error"]


def test_shed_maps_to_503_with_retry_after(stub_server):
    url, engine = stub_server
    engine.overloaded = True
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(url + CHAT_ROUTE, {"messages": [{"role": "user", "content": "x"}]})
    assert ei.value.code == 503
    assert ei.value.headers["Retry-After"] == "2"
    assert "overloaded" in json.loads(ei.value.read())["error"]


def test_priority_field_reaches_engine_and_validates(stub_server):
    """The ``priority`` request field threads into engine.submit; values
    outside 0/1/2 are a 400 BEFORE the engine sees anything."""
    url, engine = stub_server
    engine.overloaded = True  # refusal path: submit records then raises
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(
            url + CHAT_ROUTE,
            {"messages": [{"role": "user", "content": "x"}], "priority": 0},
        )
    assert ei.value.code == 503
    assert engine.priorities == [0]
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(
            url + CHAT_ROUTE,
            {"messages": [{"role": "user", "content": "x"}], "priority": 7},
        )
    assert ei.value.code == 400
    assert "priority" in json.loads(ei.value.read())["error"]
    assert engine.priorities == [0]  # the bad request never reached submit


def post_h(url, body, headers=None):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    return json.loads(urllib.request.urlopen(req, timeout=120).read())


def test_quota_maps_to_429_with_retry_after(stub_server):
    """Per-tenant quota refusal is a 429 (caller over budget, Retry-After
    from their own bucket) — deliberately distinct from the 503 shed."""
    url, engine = stub_server
    engine.over_quota = True
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(url + CHAT_ROUTE, {"messages": [{"role": "user", "content": "x"}]})
    assert ei.value.code == 429
    assert ei.value.headers["Retry-After"] == "3"  # ceil(2.4)
    assert "token rate" in json.loads(ei.value.read())["error"]


def test_tenant_field_and_header_reach_engine(stub_server):
    """The explicit body field wins over X-Cake-Tenant; the header is the
    fallback; whitespace-only fields are a 400."""
    url, engine = stub_server
    engine.overloaded = True  # refusal path: submit records then raises
    msgs = {"messages": [{"role": "user", "content": "x"}]}
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_h(
            url + CHAT_ROUTE, dict(msgs, tenant="alice"),
            headers={"X-Cake-Tenant": "bob"},
        )
    assert ei.value.code == 503
    with pytest.raises(urllib.error.HTTPError):
        post_h(url + CHAT_ROUTE, msgs, headers={"X-Cake-Tenant": "bob"})
    with pytest.raises(urllib.error.HTTPError):
        post_h(url + CHAT_ROUTE, msgs)
    assert engine.tenants == ["alice", "bob", None]
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_h(url + CHAT_ROUTE, dict(msgs, tenant="   "))
    assert ei.value.code == 400
    assert engine.tenants == ["alice", "bob", None]  # 400 before submit


def test_deadline_field_reaches_engine_and_validates(stub_server):
    url, engine = stub_server
    engine.overloaded = True
    msgs = {"messages": [{"role": "user", "content": "x"}]}
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_h(url + CHAT_ROUTE, dict(msgs, deadline_s=2.5))
    assert ei.value.code == 503
    assert engine.deadlines == [2.5]
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_h(url + CHAT_ROUTE, dict(msgs, deadline_s=0))
    assert ei.value.code == 400
    assert "deadline_s" in json.loads(ei.value.read())["error"]
    assert engine.deadlines == [2.5]  # the bad one never reached submit


def test_stats_exposes_tenants_block(stub_server):
    url, _ = stub_server
    body = json.loads(
        urllib.request.urlopen(url + "/stats", timeout=30).read()
    )
    assert body["tenants"] == {
        "abuser": {"active_streams": 1, "quota_refusals": 2}
    }


def test_oversized_tenant_id_is_400(stub_server):
    from cake_tpu.runtime.api import MAX_TENANT_ID_LEN

    url, engine = stub_server
    engine.overloaded = True
    n0 = len(engine.tenants)
    msgs = {"messages": [{"role": "user", "content": "x"}]}
    with pytest.raises(urllib.error.HTTPError) as ei:
        post_h(
            url + CHAT_ROUTE, msgs,
            headers={"X-Cake-Tenant": "t" * (MAX_TENANT_ID_LEN + 1)},
        )
    assert ei.value.code == 400
    assert len(engine.tenants) == n0  # never reached submit


def _sse_events(raw: str) -> list[dict]:
    return [
        json.loads(line[len("data: "):])
        for line in raw.splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]


def test_stream_include_usage_final_chunk(server):
    """stream_options {"include_usage": true}: one usage chunk with empty
    choices between the finish chunk and [DONE], counts matching the
    non-streaming response for the same prompt."""
    body = {
        "messages": [{"role": "user", "content": "count me"}],
        "max_tokens": 4,
    }
    raw = post(
        server + CHAT_ROUTE,
        dict(body, stream=True, stream_options={"include_usage": True}),
        raw=True,
    ).decode()
    assert raw.rstrip().endswith("data: [DONE]")
    events = _sse_events(raw)
    usage_events = [e for e in events if e.get("usage")]
    assert len(usage_events) == 1
    last = events[-1]
    assert last is usage_events[0], "usage chunk must be the final chunk"
    assert last["choices"] == []
    assert last["object"] == "chat.completion.chunk"
    u = last["usage"]
    assert u["completion_tokens"] >= 1
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
    # The chunk before it carries the finish_reason as usual.
    assert events[-2]["choices"][0]["finish_reason"] in ("stop", "length")
    # Exact agreement with the non-streaming usage for the same prompt.
    full = post(server + CHAT_ROUTE, body)
    assert u == full["usage"]


def test_stream_without_include_usage_has_no_usage_chunk(server):
    for opts in ({}, {"stream_options": {"include_usage": False}},
                 {"stream_options": {}}):
        raw = post(
            server + CHAT_ROUTE,
            {
                "messages": [{"role": "user", "content": "no usage"}],
                "stream": True, "max_tokens": 3, **opts,
            },
            raw=True,
        ).decode()
        events = _sse_events(raw)
        assert not any(e.get("usage") for e in events)
        assert events[-1]["choices"][0]["finish_reason"] in ("stop", "length")


def test_stream_options_must_be_an_object(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(
            server + CHAT_ROUTE,
            {
                "messages": [{"role": "user", "content": "x"}],
                "stream": True, "stream_options": ["include_usage"],
            },
        )
    assert ei.value.code == 400
    assert "stream_options" in json.loads(ei.value.read())["error"]


def test_requests_and_timeseries_routes_gate_on_engine(server, stub_server):
    """/requests and /timeseries 404 cleanly without an engine-side ring
    (the serialized server, or an engine predating the request log), and
    serve the filtered ring when one is attached."""
    from cake_tpu.obs.requestlog import RequestLog
    from cake_tpu.obs.timeseries import SliTimeseries

    for base in (server, stub_server[0]):
        for route in ("/requests", "/timeseries"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + route, timeout=30)
            assert ei.value.code == 404

    url, engine = stub_server
    engine.requestlog = RequestLog()
    engine.timeseries = SliTimeseries()
    engine.requestlog.record(
        request_id="r1", tenant="alice", finish_reason="stop",
        prompt_tokens=9,
    )
    engine.requestlog.record(
        request_id="r2", tenant="bob", finish_reason="quota",
    )
    engine.timeseries.observe_tokens(3)
    engine.timeseries.observe_finish("stop")

    body = json.loads(
        urllib.request.urlopen(url + "/requests", timeout=30).read()
    )
    assert body["count"] == 2 and body["last_seq"] == 2
    assert [r["request_id"] for r in body["requests"]] == ["r1", "r2"]
    body = json.loads(
        urllib.request.urlopen(
            url + "/requests?tenant=bob&finish=quota&since=1&limit=5",
            timeout=30,
        ).read()
    )
    assert [r["request_id"] for r in body["requests"]] == ["r2"]
    ts = json.loads(
        urllib.request.urlopen(url + "/timeseries", timeout=30).read()
    )
    assert ts["points"] and ts["points"][-1]["finished"] == 1
