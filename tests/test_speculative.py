"""Prompt-lookup speculative decoding: exactness oracle + proposer unit tests."""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.speculative import greedy_accept, propose_lookup
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


# ---------------------------------------------------------------- proposer


def test_propose_lookup_finds_repeated_ngram():
    #           0  1  2  3  4  5  6  7
    tokens = [5, 6, 7, 9, 1, 5, 6, 7]
    # Suffix 3-gram (5,6,7) matches at start; following tokens: 9, 1, 5...
    assert propose_lookup(tokens, 3) == [9, 1, 5]


def test_propose_lookup_prefers_most_recent_occurrence():
    tokens = [1, 2, 8, 4, 1, 2, 9, 4, 1, 2]
    # 2-gram (1,2) occurs at 0 (-> 8) and 4 (-> 9); most recent earlier wins.
    assert propose_lookup(tokens, 1) == [9]


def test_propose_lookup_no_match_returns_empty():
    assert propose_lookup([1, 2, 3, 4, 5], 4) == []
    assert propose_lookup([], 4) == []
    assert propose_lookup([7], 4) == []


def test_greedy_accept_prefix_and_correction():
    draft = np.array([10, 11, 12, 13])
    argm = np.array([10, 11, 99, 13, 42])
    n, nxt = greedy_accept(draft, argm)
    assert (n, nxt) == (2, 99)  # d0, d1 accepted; correction at d2
    n, nxt = greedy_accept(draft, np.array([10, 11, 12, 13, 42]))
    assert (n, nxt) == (4, 42)  # full accept + bonus token
    n, nxt = greedy_accept(draft, np.array([9, 0, 0, 0, 0]))
    assert (n, nxt) == (0, 9)  # nothing accepted, plain correction


# ---------------------------------------------------------------- exactness


def run_gen(cfg, params, prompt, n, spec_k):
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
        speculative_k=spec_k,
    )
    gen.add_message(Message.user(prompt))
    text = gen.generate(n)
    return text, list(gen.generated_token_ids), gen.last_finish_reason


def test_speculative_matches_plain_greedy():
    """Repetitive prompt (n-gram hits in the template/prompt) — exact stream."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    prompt = "the cat and the dog and the cat and the dog and the"
    want = run_gen(cfg, params, prompt, 24, 0)
    got = run_gen(cfg, params, prompt, 24, 6)
    assert got == want


def test_speculative_wrong_drafts_never_corrupt(monkeypatch):
    """Adversarial proposer: always-wrong drafts must cost speed only.

    Exercises the reject-all path and proves stale KV from rejected tail
    writes never leaks into subsequent steps.
    """
    import cake_tpu.models.llama.generator as G

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(32), jnp.float32)
    prompt = "abc abc abc abc"
    want = run_gen(cfg, params, prompt, 16, 0)

    from cake_tpu.models.llama import speculative as S

    # Patch the propose function AS SEEN BY the generator module import site.
    monkeypatch.setattr(
        S, "propose_lookup", lambda tokens, k, **kw: [3] * k
    )
    got = run_gen(cfg, params, prompt, 16, 5)
    assert got == want


def test_speculative_disabled_for_penalty_configs():
    """repeat_penalty != 1.0 must silently skip the speculative path (the
    in-chunk target distribution would be history-dependent) — for sampled
    configs too, where speculation is otherwise supported."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(33), jnp.float32)
    s = SamplingConfig(temperature=0.8, repeat_penalty=1.1, seed=7)

    def run(spec_k):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            s,
            speculative_k=spec_k,
        )
        gen.add_message(Message.user("sampled config"))
        gen.generate(8)
        return list(gen.generated_token_ids)

    assert run(0) == run(6)  # same RNG stream: speculative never engaged


def test_speculative_actually_accelerates_repetitive_text():
    """On repetitive text the number of model dispatches must be well below
    the token count (accepted drafts produce >1 token per verify)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)

    class CountingStep(LocalForwardStep):
        calls = 0

        def __call__(self, *a, **kw):
            CountingStep.calls += 1
            return super().__call__(*a, **kw)

        def verify_chunk(self, *a, **kw):
            CountingStep.calls += 1
            return super().verify_chunk(*a, **kw)

    step = CountingStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), GREEDY, speculative_k=6
    )
    gen.add_message(
        Message.user("the cat and the dog and the cat and the dog and the")
    )
    gen.generate(24)
    produced = gen.generated_count
    assert produced >= 20
    # Plain decode would take `produced` + 1 dispatches; require a real win.
    assert CountingStep.calls <= produced - 2, (CountingStep.calls, produced)


def test_speculative_composes_with_sliding_window():
    """Prompt-lookup speculation on a Mistral-style windowed config: the
    chunked verify forward applies the window mask (greedy-exact contract)."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="mistral", sliding_window=8
    )
    params = M.init_params(cfg, jax.random.PRNGKey(33), jnp.float32)
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    prompt = "repeat repeat repeat repeat the repeated repeats"

    def run(k):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128,
                             cache_dtype=jnp.float32),
            ByteTokenizer(),
            greedy,
            speculative_k=k,
        )
        gen.add_message(Message.user(prompt))
        gen.generate(20)
        return gen.generated_token_ids

    assert run(4) == run(0)


# ---------------------------------------------------------------- sampled


def test_sampled_accept_marginal_matches_target():
    """The rejection-sampling acceptance must leave the emitted FIRST token
    distributed exactly as the target p_0 = softmax(filter(logits_0)) —
    draft choice must not bias it (Leviathan guarantee for a point-mass
    proposal). Empirical check over many keys, against the analytic target."""
    from cake_tpu.models.llama.speculative import sampled_accept
    from cake_tpu.ops.sampling import _filter

    v, k = 16, 3
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((k + 1, v)) * 2.0, jnp.float32)
    draft = jnp.asarray([5, 2, 9], jnp.int32)  # arbitrary, incl. a low-prob id
    n_draft = jnp.int32(k)

    for temp, top_k, top_p in [(0.7, None, None), (1.3, 4, None), (1.0, None, 0.8)]:
        target = np.asarray(
            jax.nn.softmax(_filter(logits, temp, top_k, top_p), axis=-1)
        )[0]

        # The sampling knobs are closed over, so each config NEEDS its own
        # trace; three compiles total, amortized over 4000 calls each.
        accept = jax.jit(  # cake-lint: disable=jit-in-hot-loop
            lambda key: sampled_accept(
                logits, draft, n_draft, key, temp, top_k, top_p
            )
        )
        n_trials = 4000
        counts = np.zeros(v)
        for i in range(n_trials):
            n_acc, nxt, _ = accept(jax.random.PRNGKey(i))
            first = int(draft[0]) if int(n_acc) >= 1 else int(nxt)
            counts[first] += 1
        emp = counts / n_trials
        # Binomial noise at 4000 trials: ~3 sigma of sqrt(p(1-p)/n) <= 0.024.
        np.testing.assert_allclose(emp, target, atol=0.035)


def test_sampled_speculative_topk1_matches_plain_stream():
    """top_k=1 at temperature>0 is a point-mass target, so the sampled
    speculative stream must equal the plain sampled stream token-for-token —
    a deterministic end-to-end oracle for the sampled acceptance plumbing."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(35), jnp.float32)
    s = SamplingConfig(temperature=0.8, top_k=1, repeat_penalty=1.0, seed=11)

    def run(spec_k):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
            ByteTokenizer(),
            s,
            speculative_k=spec_k,
        )
        gen.add_message(
            Message.user("repeat repeat repeat repeat repeat repeat repeat")
        )
        gen.generate(24)
        return list(gen.generated_token_ids)

    assert run(0) == run(6)


def test_sampled_speculative_runs_and_respects_support():
    """temperature>0 with top_k: every emitted token must lie in the top-k
    support of its position's distribution — checked by re-scoring the
    emitted stream — and the speculative path must actually engage."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(36), jnp.float32)
    s = SamplingConfig(temperature=0.9, top_k=4, repeat_penalty=1.0, seed=3)
    step = LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    calls = {"sampled": 0}
    orig = step.verify_chunk_sampled

    def counting(*a, **kw):
        calls["sampled"] += 1
        return orig(*a, **kw)

    step.verify_chunk_sampled = counting
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), s, speculative_k=4,
    )
    gen.add_message(
        Message.user("echo echo echo echo echo echo echo echo echo")
    )
    gen.generate(20)
    ids = list(gen.generated_token_ids)
    assert len(ids) >= 4
    assert calls["sampled"] >= 1, "sampled speculative path never engaged"

    # Re-score the emitted stream: each token must be in its top-k support.
    from cake_tpu.models.llama.cache import init_cache

    prompt = gen._tokens[: len(gen._tokens) - len(ids)]
    kv = init_cache(
        cfg.num_hidden_layers, 1, 256, cfg.num_key_value_heads, cfg.head_dim,
        jnp.float32,
    )
    toks = jnp.asarray([prompt + ids], jnp.int32)
    logits, _ = M.forward_all_logits(
        params, toks, kv, jnp.int32(0), cfg, cached_prefill=False
    )
    for i, tid in enumerate(ids):
        pos_logits = np.asarray(logits[0, len(prompt) - 1 + i])
        kth = np.sort(pos_logits)[-s.top_k]
        assert pos_logits[tid] >= kth, f"token {tid} at step {i} outside top-k"
