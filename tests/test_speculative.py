"""Prompt-lookup speculative decoding: exactness oracle + proposer unit tests."""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.speculative import greedy_accept, propose_lookup
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


# ---------------------------------------------------------------- proposer


def test_propose_lookup_finds_repeated_ngram():
    #           0  1  2  3  4  5  6  7
    tokens = [5, 6, 7, 9, 1, 5, 6, 7]
    # Suffix 3-gram (5,6,7) matches at start; following tokens: 9, 1, 5...
    assert propose_lookup(tokens, 3) == [9, 1, 5]


def test_propose_lookup_prefers_most_recent_occurrence():
    tokens = [1, 2, 8, 4, 1, 2, 9, 4, 1, 2]
    # 2-gram (1,2) occurs at 0 (-> 8) and 4 (-> 9); most recent earlier wins.
    assert propose_lookup(tokens, 1) == [9]


def test_propose_lookup_no_match_returns_empty():
    assert propose_lookup([1, 2, 3, 4, 5], 4) == []
    assert propose_lookup([], 4) == []
    assert propose_lookup([7], 4) == []


def test_greedy_accept_prefix_and_correction():
    draft = np.array([10, 11, 12, 13])
    argm = np.array([10, 11, 99, 13, 42])
    n, nxt = greedy_accept(draft, argm)
    assert (n, nxt) == (2, 99)  # d0, d1 accepted; correction at d2
    n, nxt = greedy_accept(draft, np.array([10, 11, 12, 13, 42]))
    assert (n, nxt) == (4, 42)  # full accept + bonus token
    n, nxt = greedy_accept(draft, np.array([9, 0, 0, 0, 0]))
    assert (n, nxt) == (0, 9)  # nothing accepted, plain correction


# ---------------------------------------------------------------- exactness


def run_gen(cfg, params, prompt, n, spec_k):
    gen = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
        speculative_k=spec_k,
    )
    gen.add_message(Message.user(prompt))
    text = gen.generate(n)
    return text, list(gen.generated_token_ids), gen.last_finish_reason


def test_speculative_matches_plain_greedy():
    """Repetitive prompt (n-gram hits in the template/prompt) — exact stream."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)
    prompt = "the cat and the dog and the cat and the dog and the"
    want = run_gen(cfg, params, prompt, 24, 0)
    got = run_gen(cfg, params, prompt, 24, 6)
    assert got == want


def test_speculative_wrong_drafts_never_corrupt(monkeypatch):
    """Adversarial proposer: always-wrong drafts must cost speed only.

    Exercises the reject-all path and proves stale KV from rejected tail
    writes never leaks into subsequent steps.
    """
    import cake_tpu.models.llama.generator as G

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(32), jnp.float32)
    prompt = "abc abc abc abc"
    want = run_gen(cfg, params, prompt, 16, 0)

    from cake_tpu.models.llama import speculative as S

    # Patch the propose function AS SEEN BY the generator module import site.
    monkeypatch.setattr(
        S, "propose_lookup", lambda tokens, k, **kw: [3] * k
    )
    got = run_gen(cfg, params, prompt, 16, 5)
    assert got == want


def test_speculative_disabled_for_sampled_configs():
    """Non-greedy sampling must silently skip the speculative path."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(33), jnp.float32)
    s = SamplingConfig(temperature=0.8, repeat_penalty=1.1, seed=7)

    def run(spec_k):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
            ByteTokenizer(),
            s,
            speculative_k=spec_k,
        )
        gen.add_message(Message.user("sampled config"))
        gen.generate(8)
        return list(gen.generated_token_ids)

    assert run(0) == run(6)  # same RNG stream: speculative never engaged


def test_speculative_actually_accelerates_repetitive_text():
    """On repetitive text the number of model dispatches must be well below
    the token count (accepted drafts produce >1 token per verify)."""
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(31), jnp.float32)

    class CountingStep(LocalForwardStep):
        calls = 0

        def __call__(self, *a, **kw):
            CountingStep.calls += 1
            return super().__call__(*a, **kw)

        def verify_chunk(self, *a, **kw):
            CountingStep.calls += 1
            return super().verify_chunk(*a, **kw)

    step = CountingStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), GREEDY, speculative_k=6
    )
    gen.add_message(
        Message.user("the cat and the dog and the cat and the dog and the")
    )
    gen.generate(24)
    produced = gen.generated_count
    assert produced >= 20
    # Plain decode would take `produced` + 1 dispatches; require a real win.
    assert CountingStep.calls <= produced - 2, (CountingStep.calls, produced)


def test_speculative_composes_with_sliding_window():
    """Prompt-lookup speculation on a Mistral-style windowed config: the
    chunked verify forward applies the window mask (greedy-exact contract)."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="mistral", sliding_window=8
    )
    params = M.init_params(cfg, jax.random.PRNGKey(33), jnp.float32)
    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    prompt = "repeat repeat repeat repeat the repeated repeats"

    def run(k):
        gen = LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, params, max_seq_len=128,
                             cache_dtype=jnp.float32),
            ByteTokenizer(),
            greedy,
            speculative_k=k,
        )
        gen.add_message(Message.user(prompt))
        gen.generate(20)
        return gen.generated_token_ids

    assert run(4) == run(0)
