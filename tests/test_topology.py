"""Topology tests: YAML schema, range DSL, ownership, stage planning."""

import pytest

from cake_tpu.parallel.topology import MASTER_NODE, Node, Stage, Topology

EXAMPLE_YAML = """
linux_server_1:
  host: "10.0.0.1:10128"
  description: "NVIDIA Titan X Pascal (12GB)"
  layers:
    - "model.layers.0-5"
linux_server_2:
  host: "10.0.0.2:10128"
  description: "NVIDIA GeForce RTX 4090 (24GB)"
  layers:
    - "model.layers.6-16"
iphone:
  host: "10.0.0.3:10128"
  description: "iPhone 15 Pro Max"
  layers:
    - "model.layers.17"
"""


@pytest.fixture
def topo(tmp_path):
    p = tmp_path / "topology.yml"
    p.write_text(EXAMPLE_YAML)
    return Topology.from_path(p)


def test_range_expansion_inclusive(topo):
    # topology.rs:56-63: start..=stop inclusive.
    assert topo.nodes["linux_server_1"].layer_indices() == list(range(0, 6))
    assert topo.nodes["linux_server_2"].layer_indices() == list(range(6, 17))
    assert topo.nodes["iphone"].layer_indices() == [17]


def test_range_rejects_end_not_greater_than_start():
    n = Node("x", "h:1", layers=["model.layers.5-5"])
    with pytest.raises(ValueError, match="end > start"):
        n.layer_indices()


def test_malformed_spec_rejected():
    n = Node("x", "h:1", layers=["model.layer.3"])
    with pytest.raises(ValueError, match="malformed"):
        n.layer_indices()


def test_get_node_for_layer(topo):
    assert topo.get_node_for_layer(3).name == "linux_server_1"
    assert topo.get_node_for_layer(16).name == "linux_server_2"
    assert topo.get_node_for_layer(17).name == "iphone"
    assert topo.get_node_for_layer(18) is None


def test_is_layer_owner_prefix_match(topo):
    # topology.rs:25-32 semantics: weight names under an owned block match.
    n1 = topo.nodes["linux_server_1"]
    assert n1.is_layer_owner("model.layers.3.self_attn.q_proj.weight")
    assert not n1.is_layer_owner("model.layers.13.self_attn.q_proj.weight")
    # No false prefix hits: layer 1 owner must not claim layer 17.
    assert not Node("x", "h", layers=["model.layers.1"]).is_layer_owner(
        "model.layers.17.mlp.up_proj.weight"
    )


def test_stage_plan_groups_contiguous_runs(topo):
    # 20-layer model: layers 18-19 unowned -> master tail stage.
    stages = topo.stage_plan(20)
    assert stages == [
        Stage("linux_server_1", 0, 6),
        Stage("linux_server_2", 6, 17),
        Stage("iphone", 17, 18),
        Stage(MASTER_NODE, 18, 20),
    ]
    assert sum(s.n_layers for s in stages) == 20


def test_stage_plan_interleaved_local_runs():
    t = Topology.from_dict(
        {
            "w1": {"host": "a:1", "layers": ["model.layers.2-3"]},
            "w2": {"host": "b:1", "layers": ["model.layers.6"]},
        }
    )
    stages = t.stage_plan(8)
    assert [(s.node, s.lo, s.hi) for s in stages] == [
        (MASTER_NODE, 0, 2),
        ("w1", 2, 4),
        (MASTER_NODE, 4, 6),
        ("w2", 6, 7),
        (MASTER_NODE, 7, 8),
    ]


def test_empty_topology_is_all_master():
    t = Topology.from_dict({})
    assert t.stage_plan(4) == [Stage(MASTER_NODE, 0, 4)]


def test_validate_rejects_overlap_and_range():
    t = Topology.from_dict(
        {
            "a": {"host": "x:1", "layers": ["model.layers.0-3"]},
            "b": {"host": "y:1", "layers": ["model.layers.3-5"]},
        }
    )
    with pytest.raises(ValueError, match="owned by both"):
        t.validate(8)
    t2 = Topology.from_dict({"a": {"host": "x:1", "layers": ["model.layers.0-9"]}})
    with pytest.raises(ValueError, match="out of range"):
        t2.validate(8)


def test_save_roundtrip(tmp_path, topo):
    out = tmp_path / "t2.yml"
    topo.save(out)
    t2 = Topology.from_path(out)
    assert t2.to_dict() == topo.to_dict()


# ------------------------------------------------------------------ replicas


REPLICA_YAML = """
w0:
  host: "10.0.0.1:10128"
  layers: ["model.layers.0-3"]
w0b:
  host: "10.0.0.2:10128"
  layers: ["model.layers.0-3"]
w1:
  host: "10.0.0.3:10128"
  layers: ["model.layers.4-7"]
"""


def replica_topo(tmp_path):
    p = tmp_path / "replicas.yml"
    p.write_text(REPLICA_YAML)
    return Topology.from_path(p)


def test_identical_layer_sets_are_replicas(tmp_path):
    topo = replica_topo(tmp_path)
    topo.validate(8)  # identical sets: legal
    groups = topo.replica_groups()
    # Primary = first declaring node, members in declaration order.
    assert groups == {"w0": ["w0", "w0b"], "w1": ["w1"]}


def test_stage_plan_names_only_the_primary(tmp_path):
    topo = replica_topo(tmp_path)
    plan = topo.stage_plan(8)
    assert [s.node for s in plan] == ["w0", "w1"]
    assert [(s.lo, s.hi) for s in plan] == [(0, 4), (4, 8)]
    # owner_map agrees: the replica never appears as an owner.
    assert set(topo.owner_map(8)) == {"w0", "w1"}


def test_partial_overlap_still_rejected():
    topo = Topology.from_dict(
        {
            "a": {"host": "h:1", "layers": ["model.layers.0-3"]},
            "b": {"host": "h:2", "layers": ["model.layers.2-5"]},
        }
    )
    with pytest.raises(ValueError, match="IDENTICAL"):
        topo.validate(8)


def test_replica_layers_still_range_checked(tmp_path):
    topo = replica_topo(tmp_path)
    with pytest.raises(ValueError, match="out of range"):
        topo.validate(4)  # w1 declares layers 4-7
