"""Draft-model speculative decoding (models/llama/speculative.py proposers).

Contracts: streams NEVER depend on the proposer (greedy byte-identity vs
plain decode, with a different-weight draft and with garbage drafts); a
self-draft (draft == target) achieves full acceptance, so the round count
collapses below the token count; the common-prefix resync handles resets
and engine lane joins with no invalidation protocol.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.speculative import (
    DraftModelProposer,
    LookupProposer,
    propose_lookup,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 128


@pytest.fixture(scope="module")
def target():
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(50), jnp.float32)
    return cfg, params


@pytest.fixture(scope="module")
def draft():
    # A DIFFERENT (smaller, differently-seeded) model: drafts will often be
    # wrong, which is exactly what the exactness contract must absorb.
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(51), jnp.float32)
    return cfg, params


def _gen(target, k=0, proposer=None):
    cfg, params = target
    return LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
        speculative_k=k,
        proposer=proposer,
    )


def _stream(gen, prompt="draft model spec", n=24):
    gen.add_message(Message.user(prompt))
    gen.generate(n)
    return list(gen.generated_token_ids)


def test_draft_model_greedy_stream_identical(target, draft):
    dcfg, dparams = draft
    proposer = DraftModelProposer(
        dcfg, dparams, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    want = _stream(_gen(target))
    got = _stream(_gen(target, k=3, proposer=proposer))
    assert got == want


def test_lookup_proposer_equals_inline_lookup(target):
    want = _stream(_gen(target, k=3))  # the inline propose_lookup path
    got = _stream(_gen(target, k=3, proposer=LookupProposer()))
    assert got == want


def test_self_draft_full_acceptance(target):
    """Draft == target: every draft token IS the greedy continuation, so
    acceptance is total and the verify-round count collapses to about
    n/(k+1) — the mechanism's acceleration, observable without a chip."""
    cfg, params = target
    proposer = DraftModelProposer(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    calls = []
    real = proposer.propose

    def counting(tokens, k):
        d = real(tokens, k)
        calls.append(len(d))
        return d

    proposer.propose = counting
    k, n = 4, 25
    want = _stream(_gen(target), n=n)
    got = _stream(_gen(target, k=k, proposer=proposer), n=n)
    assert got == want
    assert calls, "proposer never consulted"
    assert all(c == k for c in calls), "self-draft should always fill K"
    # Full acceptance: every verify round emits k+1 tokens, so rounds stay
    # well under the token count (plain decode would need ~n rounds).
    assert len(calls) <= n // (k + 1) + 2


def test_resync_after_reset(target, draft):
    """reset() + a different dialog reuses the SAME proposer: the common-
    prefix resync must rewind the draft cache, and the stream must equal a
    fresh generator's."""
    dcfg, dparams = draft
    proposer = DraftModelProposer(
        dcfg, dparams, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    gen = _gen(target, k=3, proposer=proposer)
    _stream(gen, "first dialog first dialog")
    gen.reset()
    got = _stream(gen, "second, unrelated")
    want = _stream(_gen(target), "second, unrelated")
    assert got == want


def test_propose_respects_cache_bounds(draft):
    dcfg, dparams = draft
    proposer = DraftModelProposer(
        dcfg, dparams, max_seq_len=32, cache_dtype=jnp.float32
    )
    assert proposer.propose(list(range(1, 30)), 4) == []  # would overflow
    assert proposer.propose([], 4) == []
    assert proposer.propose([5, 6, 7], 0) == []
    d = proposer.propose([5, 6, 7], 4)
    assert len(d) == 4 and all(0 <= t < dcfg.vocab_size for t in d)


def test_engine_proposer_factory_streams_identical(target, draft):
    """The engine's per-lane proposer seam: draft-model speculation across
    joins produces byte-identical streams to the plain engine."""
    from cake_tpu.runtime.serving import BatchEngine

    cfg, params = target
    dcfg, dparams = draft

    def factory():
        return DraftModelProposer(
            dcfg, dparams, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        )

    def run(speculative_k, proposer_factory=None):
        eng = BatchEngine(
            cfg, params, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=4,
            admission_window=0.05, speculative_k=speculative_k,
            proposer_factory=proposer_factory,
        )
        eng.start()
        try:
            prompts = ["abc abc abc abc", "xy xy xy xy xy", "free text here"]
            handles = [
                eng.submit([Message.user(p)], 14, GREEDY) for p in prompts
            ]
            return [[t.id for t in h.tokens()] for h in handles], eng.stats
        finally:
            eng.stop()

    plain, _ = run(0)
    spec, stats = run(3, factory)
    assert spec == plain
    assert stats["spec_rounds"] > 0, "draft-model rounds never ran"


def test_batched_proposer_unit(draft):
    """Direct propose_batch: dead lanes, ragged histories, a lane join
    (changed history), and steady-state extension all produce k-length
    drafts for live lanes via the shared pad-aware window."""
    from cake_tpu.models.llama.speculative import BatchedDraftModelProposer

    dcfg, dparams = draft
    bp = BatchedDraftModelProposer(
        dcfg, dparams, max_seq_len=64, cache_dtype=jnp.float32
    )
    hists = [[5, 6, 7, 8], None, [9, 10]]
    out = bp.propose_batch(hists, 3)
    assert out[1] is None
    assert len(out[0]) == 3 and len(out[2]) == 3
    assert all(0 <= t < dcfg.vocab_size for t in out[0] + out[2])
    # steady state: every live lane extends by the same two tokens
    hists2 = [[5, 6, 7, 8, 1, 2], None, [9, 10, 3, 4]]
    out2 = bp.propose_batch(hists2, 3)
    assert len(out2[0]) == 3 and len(out2[2]) == 3
    # join: lane 1 comes alive with a fresh history, lane 0 diverges
    hists3 = [[5, 6, 99, 8, 1, 2, 7], [11, 12, 13, 14, 15, 16, 17], None]
    out3 = bp.propose_batch(hists3, 3)
    assert len(out3[0]) == 3 and len(out3[1]) == 3 and out3[2] is None
    # A dead lane's mirror is dropped: the shared ingest window overwrites
    # its KV row with pad garbage while it idles, so a rejoin must re-feed
    # from scratch even if pad and prefix coincidentally match.
    assert bp._hist[2] is None
    # Sub-pad window rows: a short fresh lane next to a long fresh lane
    # makes the shared window start BEFORE the short lane's left pad
    # (negative q_pos rows, zeroed by the all-masked-row attention guards —
    # the load-bearing contract documented in batch.py). Drafts stay valid.
    bp2 = BatchedDraftModelProposer(
        dcfg, dparams, max_seq_len=64, cache_dtype=jnp.float32
    )
    out4 = bp2.propose_batch([list(range(1, 11)), [3, 4, 5]], 3)
    assert len(out4[0]) == 3 and len(out4[1]) == 3
    assert all(0 <= t < dcfg.vocab_size for t in out4[0] + out4[1])
    # cache-bound bail
    assert bp.propose_batch([list(range(1, 63))], 3) == [None]


def test_engine_batched_proposer_streams_identical(target, draft):
    """The engine's batched drafting mode (one ingest + one scan for ALL
    lanes): byte-identical streams, real speculative rounds."""
    from cake_tpu.models.llama.speculative import BatchedDraftModelProposer
    from cake_tpu.runtime.serving import BatchEngine

    cfg, params = target
    dcfg, dparams = draft

    def run(speculative_k, factory=None):
        eng = BatchEngine(
            cfg, params, ByteTokenizer(), max_seq_len=MAX_SEQ,
            cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=4,
            admission_window=0.05, speculative_k=speculative_k,
            proposer_factory=factory,
        )
        eng.start()
        try:
            prompts = ["abc abc abc abc", "xy xy xy xy xy", "free text here"]
            handles = [
                eng.submit([Message.user(p)], 14, GREEDY) for p in prompts
            ]
            return [[t.id for t in h.tokens()] for h in handles], eng
        finally:
            eng.stop()

    plain, _ = run(0)
    spec, eng = run(
        3,
        lambda: BatchedDraftModelProposer(
            dcfg, dparams, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        ),
    )
    assert spec == plain
    assert eng._proposer_mode == "batched"
    assert eng.stats["spec_rounds"] > 0, "batched rounds never ran"


def test_engine_batched_self_draft_accelerates(target):
    """Draft == target through the batched proposer: acceptance is (near-)
    total, so the per-round advance must exceed K tokens — the mechanism's
    acceleration, observable in engine stats without a chip."""
    from cake_tpu.models.llama.speculative import BatchedDraftModelProposer
    from cake_tpu.runtime.serving import BatchEngine

    cfg, params = target
    K = 3
    eng = BatchEngine(
        cfg, params, ByteTokenizer(), max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32, decode_chunk_size=4, max_batch=4,
        admission_window=0.05, speculative_k=K,
        proposer_factory=lambda: BatchedDraftModelProposer(
            cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
        ),
    )
    eng.start()
    try:
        hs = [
            eng.submit([Message.user(p)], 16, GREEDY)
            for p in ("self draft a", "self draft bb")
        ]
        streams = [[t.id for t in h.tokens()] for h in hs]
    finally:
        eng.stop()
    assert all(len(s) == 16 for s in streams)
    assert eng.stats["spec_rounds"] > 0
    assert eng.stats["spec_tokens"] > K * eng.stats["spec_rounds"]


def test_batched_proposer_random_lane_churn(draft):
    """Property-style churn: arbitrary sequences of lane births, deaths,
    extensions, and divergences must always yield k-length in-vocab drafts
    for live lanes and None for dead ones — the mirror/resync logic can
    never wedge or emit malformed proposals. (Fixed seed: JAX compiles per
    shape, so a bounded generated schedule keeps runtime sane.)"""
    import numpy as np

    from cake_tpu.models.llama.speculative import BatchedDraftModelProposer

    dcfg, dparams = draft
    bp = BatchedDraftModelProposer(
        dcfg, dparams, max_seq_len=96, cache_dtype=jnp.float32
    )
    rng = np.random.default_rng(123)
    B, K = 3, 3
    hists: list = [None] * B
    for step in range(12):
        for lane in range(B):
            r = rng.random()
            if hists[lane] is None:
                if r < 0.5:  # birth: fresh prompt
                    hists[lane] = rng.integers(
                        0, dcfg.vocab_size, rng.integers(2, 9)
                    ).tolist()
            elif r < 0.15:  # death
                hists[lane] = None
            elif r < 0.3:  # divergence (engine correction overwrote a tail)
                hists[lane] = hists[lane][: max(1, len(hists[lane]) - 2)] + \
                    rng.integers(0, dcfg.vocab_size, 3).tolist()
            else:  # plain extension
                hists[lane] = hists[lane] + rng.integers(
                    0, dcfg.vocab_size, rng.integers(1, 4)
                ).tolist()
        out = bp.propose_batch(hists, K)
        assert len(out) == B
        for lane in range(B):
            if hists[lane]:
                # Unconditional: this schedule never reaches the bounds
                # bail (max history ~52 + K < 96), so live lanes MUST draft.
                assert out[lane] is not None, (step, lane, len(hists[lane]))
                assert len(out[lane]) == K
                assert all(
                    0 <= t < dcfg.vocab_size for t in out[lane]
                ), out[lane]
            else:
                assert out[lane] is None
