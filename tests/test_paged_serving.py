"""Paged serving path: dense-vs-paged stream equivalence, admission by free
pages, and the capacity win the paged pool exists for.

Equivalence is EXACT (fp32, CPU): the paged forward keeps the dense path's
left-padded position/mask arithmetic and per-row PRNG; only storage routing
differs, and the gather fallback reconstructs the dense view bit-for-bit at
every live slot. So dense engine streams are the oracle, token for token.
"""

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime.serving import BatchEngine, ServeConfig
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
PAGE = 16  # small pages on CPU: boundary crossings every 16 tokens


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def make_engine(cfg, params, serve=None, **kw):
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("decode_chunk_size", 4)
    kw.setdefault("admission_window", 0.05)
    eng = BatchEngine(cfg, params, ByteTokenizer(), serve=serve, **kw)
    eng.start()
    return eng


def paged_cfg(**over):
    kw = dict(
        max_batch=8, decode_chunk_size=4, admission_window=0.05,
        kv_mode="paged", page_size=PAGE,
    )
    kw.update(over)
    return ServeConfig(**kw)


def collect(handle):
    return [t.id for t in handle.tokens()]


def run_prompts(eng, prompts, n, sampling=GREEDY):
    if isinstance(sampling, SamplingConfig):
        sampling = [sampling] * len(prompts)
    handles = [
        eng.submit([Message.user(p)], n, s)
        for p, s in zip(prompts, sampling)
    ]
    return [collect(h) for h in handles]


# ----------------------------------------------------------- equivalence


def test_dense_vs_paged_greedy_streams_identical():
    """Acceptance: heterogeneous prompt lengths, greedy fp32 CPU; the long
    row's history (prompt + 24 new tokens) spans >= 4 pages of 16."""
    cfg, params = setup()
    prompts = [
        "short",
        "a deliberately long prompt that occupies well over three sixteen"
        " token pages once tokenized byte by byte",
        "mid-size prompt row",
    ]
    eng_d = make_engine(cfg, params)
    dense = run_prompts(eng_d, prompts, 24)
    stats_d = dict(eng_d.stats)
    eng_d.stop()

    eng_p = make_engine(cfg, params, serve=paged_cfg())
    alloc = eng_p.backend.allocator
    assert alloc is not None and eng_p.kv_mode == "paged"
    paged = run_prompts(eng_p, prompts, 24)
    stats_p = dict(eng_p.stats)
    eng_p.stop()

    assert dense == paged
    assert stats_p["max_rows"] == stats_d["max_rows"] == 3
    assert len(prompts[1]) + 24 >= 3 * PAGE  # the >= 3 pages criterion
    # Epoch over: every page is back on the free list.
    assert alloc.pages_free == alloc.pages_total


def test_dense_vs_paged_sampled_streams_identical():
    cfg, params = setup(seed=32)
    sampling = [
        SamplingConfig(temperature=0.8, top_k=20, repeat_penalty=1.0, seed=s)
        for s in (7, 1234, 999)
    ]
    prompts = ["same prompt for everyone"] * 3
    eng_d = make_engine(cfg, params)
    dense = run_prompts(eng_d, prompts, 10, sampling)
    eng_d.stop()
    eng_p = make_engine(cfg, params, serve=paged_cfg())
    paged = run_prompts(eng_p, prompts, 10, sampling)
    eng_p.stop()
    assert dense == paged
    assert len({tuple(g) for g in dense}) > 1  # sampling is live


def test_paged_late_join_matches_dense():
    cfg, params = setup(seed=33)

    def run(serve):
        eng = make_engine(cfg, params, serve=serve, admission_window=0.02)
        h1 = eng.submit([Message.user("first long-running request")], 40, GREEDY)
        it = h1.tokens()
        first = [next(it).id for _ in range(6)]  # epoch is running
        h2 = eng.submit([Message.user("late joiner")], 10, GREEDY)
        ids2 = collect(h2)
        ids1 = first + [t.id for t in it]
        joins = eng.stats["joins"]
        eng.stop()
        return ids1, ids2, joins

    d1, d2, dj = run(None)
    p1, p2, pj = run(paged_cfg(admission_window=0.02))
    assert (d1, d2) == (p1, p2)
    assert pj == dj == 1  # the joiner really joined the running epoch


# ------------------------------------------------- admission by free pages


def test_capacity_win_half_pool_full_occupancy():
    """Acceptance: pool at 50% of the dense batch*max_seq footprint. Dense
    accounting at that HBM affords 4 lanes of 256 slots; the paged engine
    runs all 8 short requests concurrently and completes them correctly."""
    cfg, params = setup()
    dense_slots = 8 * 256
    serve = paged_cfg(max_pages=dense_slots // 2 // PAGE, admission_window=0.3)
    dense_equiv_lanes = dense_slots // 2 // 256
    assert dense_equiv_lanes == 4

    eng_d = make_engine(cfg, params)  # full-size dense engine: the oracle
    prompts = [f"query number {i}" for i in range(8)]
    want = run_prompts(eng_d, prompts, 8)
    eng_d.stop()

    eng_p = make_engine(cfg, params, serve=serve)
    got = run_prompts(eng_p, prompts, 8)
    stats = dict(eng_p.stats)
    alloc = eng_p.backend.allocator
    eng_p.stop()

    assert got == want  # correctness at reduced HBM
    # Strictly higher concurrency than dense slot accounting permits.
    assert stats["max_rows"] == 8 > dense_equiv_lanes
    assert stats["batches"] == 1
    assert alloc.pages_free == alloc.pages_total


def test_oversized_prompt_rejected_at_submit():
    cfg, params = setup()
    eng = make_engine(cfg, params, serve=paged_cfg(max_pages=4))
    with pytest.raises(ValueError, match="pages"):
        eng.submit([Message.user("x" * 100)], 4, GREEDY)
    eng.stop()


def _encoded_len(cfg, prompt):
    from cake_tpu.models.llama.chat import encode_dialog

    return len(
        ByteTokenizer().encode(
            encode_dialog([Message.user(prompt)], cfg.dialog_template)
        )
    )


def test_admission_defers_until_pages_free():
    """A pool too small for two long prompts serves them as two epochs —
    the second request waits for pages instead of failing."""
    cfg, params = setup()
    p = "a prompt long enough to need roughly five sixteen token pages xx"
    # One request's peak: prompt pages + one decode page + the reserve at
    # admission. A pool of need+1 holds ONE such request at a time.
    need = -(-_encoded_len(cfg, p) // PAGE) + 1
    eng = make_engine(
        cfg, params,
        serve=paged_cfg(max_pages=need + 1, admission_window=0.2),
    )
    got = run_prompts(eng, [p, p], 8)
    stats = dict(eng.stats)
    eng.stop()
    assert got[0] == got[1]  # same prompt, same greedy stream
    assert len(got[0]) == 8
    # Page accounting kept them SEQUENTIAL: never both in flight — the
    # second either opened its own epoch or joined only after the first
    # finished and returned its pages.
    assert stats["max_rows"] == 1
    assert stats["batches"] + stats["joins"] == 2


def test_pool_pressure_truncates_stream_not_epoch():
    """Decode hits an empty free list at a page boundary: the starved stream
    force-finishes as "length"; the engine keeps serving afterwards."""
    metrics.registry.clear()
    cfg, params = setup()
    # The prompt is admitted (prompt pages + reserve fit exactly), but the
    # budget wants far more tokens than the pool can ever map: the free
    # list empties at a decode page boundary.
    p = "pressure test prompt xxxx"
    pool = -(-_encoded_len(cfg, p) // PAGE) + 1
    eng = make_engine(
        cfg, params,
        serve=paged_cfg(max_pages=pool, page_reserve=1),
    )
    h = eng.submit([Message.user(p)], 200, GREEDY)
    ids = collect(h)
    assert h.finish_reason == "length"
    assert 0 < len(ids) < 200  # truncated, not hung, not errored
    assert eng.stats["page_truncations"] == 1
    assert (
        metrics.registry.counter(
            "cake_kv_page_alloc_failures_total"
        ).value()
        >= 1
    )
    # The pool recovered: a small follow-up request completes normally.
    h2 = eng.submit([Message.user("after pressure")], 4, GREEDY)
    assert len(collect(h2)) == 4
    eng.stop()


def test_page_gauges_live_on_registry():
    cfg, params = setup()
    eng = make_engine(cfg, params, serve=paged_cfg(max_pages=32))
    run_prompts(eng, ["observe me"], 4)
    total = metrics.registry.gauge("cake_kv_pages_total").value()
    free = metrics.registry.gauge("cake_kv_pages_free").value()
    eng.stop()
    assert total == 32
    assert free == 32  # all returned after the epoch


def test_serve_config_validates_knobs():
    with pytest.raises(ValueError, match="kv_mode"):
        ServeConfig(kv_mode="ragged")
    with pytest.raises(ValueError, match="page_size"):
        ServeConfig(kv_mode="paged", page_size=0)
    # reserve >= 1 is what makes the admission charge an upper bound on the
    # left-padded layout's page-straddle (see ServeConfig.__post_init__).
    with pytest.raises(ValueError, match="page_reserve"):
        ServeConfig(kv_mode="paged", page_reserve=0)


def test_dense_backend_with_paged_serve_config_refuses():
    from cake_tpu.runtime.batch_backend import LocalBatchBackend

    cfg, params = setup()
    backend = LocalBatchBackend(
        cfg, params, max_seq_len=256, cache_dtype=jnp.float32
    )
    with pytest.raises(ValueError, match="paged"):
        BatchEngine(
            cfg, None, ByteTokenizer(), max_seq_len=256,
            backend=backend, serve=paged_cfg(),
        )


# ----------------------------------------------------------------- stress


@pytest.mark.slow
def test_paged_churn_stress_large_pool():
    """Heterogeneous churn through a half-size pool: waves of requests with
    varying lengths/budgets all complete, streams match the dense oracle,
    and the pool drains back to fully free."""
    cfg, params = setup(seed=40)
    prompts = [
        ("w%d " % i) * (1 + (i * 7) % 23) for i in range(12)
    ]
    budgets = [4 + (i * 5) % 17 for i in range(12)]

    def run(serve):
        eng = make_engine(cfg, params, serve=serve, admission_window=0.1)
        handles = [
            eng.submit([Message.user(p)], n, GREEDY)
            for p, n in zip(prompts, budgets)
        ]
        got = [collect(h) for h in handles]
        alloc = getattr(eng.backend, "allocator", None)
        eng.stop()
        return got, alloc

    want, _ = run(None)
    got, alloc = run(paged_cfg(max_pages=8 * 256 // 2 // PAGE))
    assert got == want
    assert alloc.pages_free == alloc.pages_total
