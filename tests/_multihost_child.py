"""Child process for the multi-host integration test (test_multihost.py).

Usage: python _multihost_child.py <coordinator_port> <process_id>

Each of the two processes joins a jax.distributed cluster over a virtual
4-device CPU backend (8 global devices), builds the SAME PipelineRunner over
the global mesh (4 stages x tp 2), and runs lockstep generation through
MultiHostStep: process 0 drives a greedy LlamaGenerator and checks the token
stream against a local single-device oracle; process 1 replays the leader's
steps until STOP. Prints MH_TOKENS_OK on the leader when the oracle matches.

The env (JAX_PLATFORMS=cpu, device count, axon pool cleared) must be set by
the SPAWNING process: the sitecustomize reads it at interpreter start.
"""

import sys

from cake_tpu.parallel import multihost

port, pid = sys.argv[1], int(sys.argv[2])
multihost.initialize(f"127.0.0.1:{port}", num_processes=2, process_id=pid)

import jax
import jax.numpy as jnp

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.multihost import MultiHostStep
from cake_tpu.parallel.pipeline import PipelineRunner

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

cfg = LlamaConfig.tiny(num_hidden_layers=4)
params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)  # deterministic
runner = PipelineRunner(
    cfg,
    params,
    [(0, 1), (1, 2), (2, 3), (3, 4)],
    tp=2,
    max_seq_len=128,
    cache_dtype=jnp.float32,
)
step = MultiHostStep(runner)

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)

if step.leader:
    gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
    gen.add_message(Message.user("multi host pipeline oracle"))
    gen.generate(8)
    got = list(gen.generated_token_ids)

    # Second dialog exercises RESET on the broadcast channel.
    gen.reset()
    gen.add_message(Message.user("second dialog"))
    gen.generate(4)
    second = list(gen.generated_token_ids)
    step.stop()

    # Local single-device oracle (leader-only computation is fine after STOP).
    oracle = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
    )
    oracle.add_message(Message.user("multi host pipeline oracle"))
    oracle.generate(8)
    assert got == list(oracle.generated_token_ids), (got, oracle.generated_token_ids)
    oracle.reset()
    oracle.add_message(Message.user("second dialog"))
    oracle.generate(4)
    assert second == list(oracle.generated_token_ids)
    print("MH_TOKENS_OK", flush=True)
else:
    step.follow()
    print("MH_FOLLOWER_DONE", flush=True)
