"""SLO-hardened admission (runtime/admission.py + engine wiring, ISSUE 11).

Three layers under test:

  * unit — TokenBucket arithmetic, deficit-weighted round-robin order in
    FairQueue (DRR across tenant subqueues; FIFO when fairness is off),
    TenantMeter quota refusals with honest Retry-After hints, StallGuard
    stall conversion + late-resolution bookkeeping.
  * engine — per-tenant 429s from submit(), the fairness A/B (an abusive
    flood cannot starve a compliant tenant's request out of the join
    order; with fairness off the SAME flood pushes it to the back — the
    A/B is the proof the subsystem earns its complexity), end-to-end
    deadlines (queued requests expire BEFORE admission and never map a
    page; running streams finish ``"deadline"`` at a chunk boundary with
    their pages returned), and deadline-aware shedding.
  * API-facing contracts live in tests/test_api_cli.py (429 mapping,
    tenant header/field, deadline_s validation) and the chaos-grade storm
    + watchdog scenarios in tests/test_chaos.py.
"""

from __future__ import annotations

import threading
import time

import jax
import jax.numpy as jnp
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.runtime import faults
from cake_tpu.runtime.admission import (
    DEFAULT_TENANT,
    FairQueue,
    QuotaExceeded,
    StallGuard,
    TenantMeter,
    TokenBucket,
    WaitEstimator,
)
from cake_tpu.runtime.serving import BatchEngine, EngineOverloaded, ServeConfig
from cake_tpu.utils import metrics

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
MAX_SEQ = 128


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    faults.clear()
    yield
    faults.clear()


def setup(n_layers=2, seed=31):
    cfg = LlamaConfig.tiny(num_hidden_layers=n_layers)
    params = M.init_params(cfg, jax.random.PRNGKey(seed), jnp.float32)
    return cfg, params


def make_engine(cfg, params, *, start=True, **serve_kw):
    serve_kw.setdefault("max_batch", 4)
    serve_kw.setdefault("decode_chunk_size", 4)
    serve_kw.setdefault("admission_window", 0.02)
    eng = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=MAX_SEQ, cache_dtype=jnp.float32,
        serve=ServeConfig(**serve_kw),
    )
    if start:
        eng.start()
    return eng


def collect(handle):
    return [tok.id for tok in handle.tokens()]


# ------------------------------------------------------------------- unit


class TestTokenBucket:
    def test_grant_charge_and_refill(self):
        b = TokenBucket(rate=10.0, burst=20.0)
        t0 = time.monotonic()
        assert b.try_take(15, now=t0) == 0.0
        assert b.level == pytest.approx(5.0)
        # Not enough left: the hint is the caller's own refill arithmetic.
        wait = b.try_take(15, now=t0)
        assert wait == pytest.approx(1.0)  # (15 - 5) / 10 tok/s
        # After the hinted wait it grants.
        assert b.try_take(15, now=t0 + wait + 1e-6) == 0.0

    def test_oversized_request_runs_on_debt(self):
        # cost > burst: granted from a full bucket, charged into debt, so
        # the long-run rate still converges while big requests can pass.
        b = TokenBucket(rate=10.0, burst=20.0)
        t0 = time.monotonic()
        assert b.try_take(50, now=t0) == 0.0
        assert b.level == pytest.approx(-30.0)
        wait = b.try_take(1, now=t0)
        assert wait == pytest.approx((1 + 30) / 10.0, abs=0.05)

    def test_zero_rate_never_grants_after_burst(self):
        b = TokenBucket(rate=0.0, burst=0.0)
        assert b.try_take(1) == float("inf")


class TestFairQueue:
    class R:
        def __init__(self, tenant, n, t_submit=0.0, deadline=0.0):
            self.tenant = tenant
            self.n = n
            self.t_submit = t_submit
            self.deadline = deadline

        def __repr__(self):
            return f"{self.tenant}{self.n}"

    def test_drr_alternates_tenants_under_flood(self):
        q = FairQueue(fair=True, quantum=10, cost=lambda r: 10.0)
        for i in range(6):
            q.append(self.R("a", i))
        q.append(self.R("b", 0))
        q.append(self.R("b", 1))
        out = q.take(4, lambda r: "take")
        # One quantum buys one request per visit: strict alternation.
        assert [(r.tenant, r.n) for r in out] == [
            ("a", 0), ("b", 0), ("a", 1), ("b", 1)
        ]
        assert len(q) == 4

    def test_fifo_when_fairness_off(self):
        q = FairQueue(fair=False, quantum=10, cost=lambda r: 10.0)
        for i in range(3):
            q.append(self.R("a", i))
        q.append(self.R("b", 0))
        q.append(self.R("a", 3))
        out = q.take(5, lambda r: "take")
        assert [(r.tenant, r.n) for r in out] == [
            ("a", 0), ("a", 1), ("a", 2), ("b", 0), ("a", 3)
        ]

    def test_cost_gates_per_visit_and_boost_terminates(self):
        # A head costing many quanta still comes out of ONE take() call
        # (the fast-forward boost), and the cheap tenant is not starved.
        q = FairQueue(fair=True, quantum=10, cost=lambda r: 100.0)
        q.append(self.R("a", 0))
        q.append(self.R("b", 0))
        out = q.take(2, lambda r: "take")
        assert {(r.tenant, r.n) for r in out} == {("a", 0), ("b", 0)}

    def test_skip_next_and_drop_verdicts(self):
        q = FairQueue(fair=True, quantum=100, cost=lambda r: 1.0)
        for i in range(3):
            q.append(self.R("a", i))
        q.append(self.R("b", 0))

        def accept(r):
            if r.tenant == "a" and r.n == 0:
                return "skip"   # stays queued, a1 still reachable
            if r.tenant == "a" and r.n == 1:
                return "drop"   # removed without counting
            if r.tenant == "a" and r.n == 2:
                return "next"   # stops tenant a this call
            return "take"

        out = q.take(4, accept)
        assert [(r.tenant, r.n) for r in out] == [("b", 0)]
        # a0 (skipped) and a2 (next-stopped) remain; a1 was dropped.
        assert [(r.tenant, r.n) for r in q] == [("a", 0), ("a", 2)]

    def test_remove_iter_oldest_and_deadline_count(self):
        q = FairQueue(fair=True, quantum=10, cost=lambda r: 1.0)
        a = self.R("a", 0, t_submit=2.0)
        b = self.R("b", 0, t_submit=1.0, deadline=99.0)
        q.append(a)
        q.append(b)
        assert q.deadline_count == 1
        assert q.oldest_head() is b
        assert set(q) == {a, b}
        assert q.remove(b) and not q.remove(b)
        assert q.deadline_count == 0
        assert q.oldest_head() is a
        q.clear()
        assert len(q) == 0 and q.oldest_head() is None

    def test_idle_tenant_leaves_no_state_behind(self):
        # A drained tenant's entries are DELETED: no banked deficit
        # (classic DRR's no-idle-credit rule) and — the hostile-churn
        # bound — no per-tenant dict growth for ids never seen again.
        q = FairQueue(fair=True, quantum=10, cost=lambda r: 10.0)
        q.append(self.R("a", 0))
        assert [r.n for r in q.take(1, lambda r: "take")] == [0]
        assert "a" not in q._deficit and "a" not in q._q


class TestTenantMeter:
    def test_rate_refusal_with_retry_hint(self):
        m = TenantMeter(rate=10.0, burst=20.0)
        m.admit("a", "r1", 20)
        with pytest.raises(QuotaExceeded) as ei:
            m.admit("a", "r2", 20)
        assert ei.value.kind == "rate"
        assert ei.value.tenant == "a"
        assert 1.0 <= ei.value.retry_after_s <= 3.0
        # An unrelated tenant has its own bucket.
        m.admit("b", "r3", 20)
        assert metrics.registry.counter(
            "cake_quota_refusals_total"
        ).value(tenant="a", kind="rate") == 1

    def test_stream_cap_and_close(self):
        m = TenantMeter(max_streams=1)
        m.admit("a", "r1", 5)
        with pytest.raises(QuotaExceeded) as ei:
            m.admit("a", "r2", 5)
        assert ei.value.kind == "streams"
        m.close("r1")
        m.close("r1")  # idempotent
        m.admit("a", "r2", 5)
        snap = m.snapshot()
        assert snap["a"]["active_streams"] == 1
        assert snap["a"]["submitted"] == 2
        assert snap["a"]["quota_refusals"] == 1

    def test_admit_is_atomic_on_refusal(self):
        m = TenantMeter(rate=1.0, burst=1.0, max_streams=8)
        m.admit("a", "r1", 1)
        with pytest.raises(QuotaExceeded):
            m.admit("a", "r2", 1)
        # The refused rid left no state: the stream cap still sees one.
        assert m.snapshot()["a"]["active_streams"] == 1


class TestStallGuard:
    def test_fast_calls_pass_through_values_and_errors(self):
        g = StallGuard(stall_s=5.0)
        assert g.call(lambda: 42, op="decode") == 42
        with pytest.raises(KeyError):
            g.call(lambda: {}["x"], op="decode")
        g.stop()

    def test_stall_converts_to_worker_error_and_recovers(self):
        from cake_tpu.runtime.batch_backend import BackendWorkerError

        stalled = []
        g = StallGuard(stall_s=0.15, on_stall=stalled.append)
        release = threading.Event()

        def hung():
            release.wait(5.0)
            return "late"

        t0 = time.monotonic()
        with pytest.raises(BackendWorkerError) as ei:
            g.call(hung, op="decode")
        assert time.monotonic() - t0 < 2.0  # detected within the bound
        assert ei.value.node == StallGuard.NODE
        assert stalled == ["decode"]
        assert g.stalls == 1
        # A fresh watchdog thread serves the next dispatch immediately,
        # and the abandoned call's late result is discarded + counted.
        assert g.call(lambda: "ok", op="decode") == "ok"
        release.set()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if metrics.registry.counter(
                "cake_epoch_stalls_resolved_total"
            ).value():
                break
            time.sleep(0.01)
        assert metrics.registry.counter(
            "cake_epoch_stalls_resolved_total"
        ).value() == 1
        g.stop()


def test_wait_estimator_cold_start_and_scaling():
    e = WaitEstimator()
    assert e.estimate(100, 8) == 0.0  # honest cold start: never sheds
    e.observe(2.0)
    assert e.estimate(0, 8) == pytest.approx(2.0)
    assert e.estimate(8, 8) == pytest.approx(4.0)


# ------------------------------------------------------------------ engine


def test_engine_quota_rate_limits_per_tenant():
    cfg, params = setup()
    eng = make_engine(
        cfg, params, start=False, tenant_rate=10.0, tenant_burst=30.0
    )
    msgs = [Message.user("quota limited prompt")]
    eng.submit(msgs, 16, GREEDY, tenant="abuser")
    with pytest.raises(QuotaExceeded) as ei:
        eng.submit(msgs, 16, GREEDY, tenant="abuser")
    assert ei.value.retry_after_s > 0
    assert eng.stats["quota_refusals"] == 1
    # A different tenant (and the default tenant) are unaffected.
    eng.submit(msgs, 16, GREEDY, tenant="polite")
    eng.submit(msgs, 16, GREEDY)
    stats = eng.tenant_stats()
    assert stats["abuser"]["quota_refusals"] == 1
    assert stats["polite"]["quota_refusals"] == 0
    assert stats[DEFAULT_TENANT]["queued"] == 1


def test_engine_stream_cap_releases_on_finish():
    cfg, params = setup()
    eng = make_engine(cfg, params, tenant_streams=1)
    try:
        h = eng.submit([Message.user("capped")], 2, GREEDY, tenant="t")
        with pytest.raises(QuotaExceeded) as ei:
            eng.submit([Message.user("capped")], 2, GREEDY, tenant="t")
        assert ei.value.kind == "streams"
        collect(h)
        # The finished stream released its quota slot through the handle's
        # close hook — whichever path closed it.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                h2 = eng.submit(
                    [Message.user("capped")], 2, GREEDY, tenant="t"
                )
                break
            except QuotaExceeded:
                time.sleep(0.01)
        else:
            pytest.fail("quota slot never released after finish")
        collect(h2)
    finally:
        eng.stop()


def _storm_finish_order(fair: bool, cfg, params):
    """One plug epoch + an abusive 6-request flood + one compliant request;
    returns how many abuser streams finished before the compliant one."""
    eng = make_engine(
        cfg, params, max_batch=2, decode_chunk_size=4,
        admission_window=0.02, fair_queue=fair,
    )
    done: list[str] = []
    lock = threading.Lock()

    def consume(tag, h):
        for _ in h.tokens():
            pass
        with lock:
            done.append(tag)

    threads = []
    try:
        plug = eng.submit(
            [Message.user("plug stream holding the epoch")], 40, GREEDY,
            tenant="plug",
        )
        threads.append(
            threading.Thread(target=consume, args=("plug", plug), daemon=True)
        )
        threads[-1].start()
        deadline = time.monotonic() + 10.0
        while eng.stats["batches"] < 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert eng.stats["batches"] >= 1, "plug epoch never started"
        handles = []
        for i in range(6):
            handles.append(
                (
                    "abuser",
                    eng.submit(
                        [Message.user(f"abusive flood request {i}")], 3,
                        GREEDY, tenant="abuser",
                    ),
                )
            )
        handles.append(
            (
                "compliant",
                eng.submit(
                    [Message.user("one compliant request")], 3, GREEDY,
                    tenant="compliant",
                ),
            )
        )
        for tag, h in handles:
            t = threading.Thread(target=consume, args=(tag, h), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(60.0)
        assert not any(t.is_alive() for t in threads), "a stream hung"
    finally:
        eng.stop()
    return done.index("compliant") - (
        1 if done.index("plug") < done.index("compliant") else 0
    )


def test_fair_queue_ab_flood_cannot_starve_compliant_tenant():
    """THE A/B: same storm, fairness on vs off. With DRR the compliant
    tenant's single request joins within the first couple of scheduling
    turns; with the global FIFO it queues behind the entire flood."""
    cfg, params = setup()
    abusers_before_fair = _storm_finish_order(True, cfg, params)
    abusers_before_fifo = _storm_finish_order(False, cfg, params)
    assert abusers_before_fair <= 2, (
        f"fairness on: compliant finished after {abusers_before_fair} "
        "abuser streams"
    )
    assert abusers_before_fifo == 6, (
        "fairness off should demonstrably starve the compliant tenant "
        f"(finished after {abusers_before_fifo}/6 abuser streams)"
    )


def test_queued_deadline_expires_before_admission_no_pages():
    """A queued request past its deadline NEVER occupies a lane or maps a
    page: it cannot join the running epoch (incompatible knobs), expires
    at a chunk-boundary sweep, and the paged pool shows no trace of it."""
    cfg, params = setup()
    eng = make_engine(
        cfg, params, kv_mode="paged", page_size=16, max_batch=2,
    )
    alloc = eng.backend.allocator
    try:
        # Slow decode chunks (seeded stall) so the plug epoch reliably
        # outlives the 30ms deadline even with every jit cache warm.
        faults.install(
            faults.parse("stall@backend.decode:count=0:delay_s=0.02")
        )
        plug = eng.submit(
            [Message.user("plug stream holding the epoch")], 24, GREEDY
        )
        deadline = time.monotonic() + 10.0
        while eng.stats["batches"] < 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        sampled = SamplingConfig(
            temperature=0.7, top_k=5, repeat_penalty=1.0, seed=3
        )
        h = eng.submit(
            [Message.user("doomed request")], 8, sampled, deadline_s=0.03
        )
        got = collect(h)
        assert got == []
        assert h.finish_reason == "deadline"
        assert h.completion_tokens == 0
        collect(plug)
        faults.clear()
        assert eng.quiesce(10.0)
        assert alloc.pages_free == alloc.pages_total
        assert eng.stats["deadline_expired"] == 1
        assert metrics.registry.counter(
            "cake_deadline_expired_total"
        ).value(where="queued") == 1
        assert any(
            e["event"] == "deadline-expired" and e.get("where") == "queued"
            for e in metrics.flight.snapshot()
        )
    finally:
        eng.stop()


def test_running_deadline_expires_at_chunk_boundary_frees_pages():
    """A running stream past its deadline finishes ``"deadline"`` at the
    next chunk boundary: the tokens already streamed stand (a clean prefix
    of the fault-free run), its pages return, and a co-batched stream
    without a deadline is untouched, bit-identical."""
    cfg, params = setup()
    # Oracle: the same pair fault-free, no deadlines.
    eng = make_engine(cfg, params, kv_mode="paged", page_size=16)
    try:
        h_s = eng.submit([Message.user("short co-batched")], 2, GREEDY)
        h_l = eng.submit([Message.user("long deadline victim")], 24, GREEDY)
        want_short, want_long = collect(h_s), collect(h_l)
    finally:
        eng.stop()

    eng = make_engine(cfg, params, kv_mode="paged", page_size=16)
    alloc = eng.backend.allocator
    try:
        # Warm the paths, then slow decode chunks so the 0.25s deadline
        # lands deterministically mid-stream (CPU chunk time is noise).
        h_s = eng.submit([Message.user("short co-batched")], 2, GREEDY)
        h_l = eng.submit([Message.user("long deadline victim")], 24, GREEDY)
        collect(h_s), collect(h_l)
        faults.install(
            faults.parse("stall@backend.decode:count=0:delay_s=0.08")
        )
        h_s = eng.submit([Message.user("short co-batched")], 2, GREEDY)
        h_l = eng.submit(
            [Message.user("long deadline victim")], 24, GREEDY,
            deadline_s=0.25,
        )
        got_short, got_long = collect(h_s), collect(h_l)
        faults.clear()
        assert got_short == want_short
        assert h_s.finish_reason in ("stop", "length")
        assert h_l.finish_reason == "deadline"
        assert got_long == want_long[: len(got_long)]
        assert 0 < len(got_long) < len(want_long)
        assert eng.quiesce(10.0)
        assert alloc.pages_free == alloc.pages_total
        assert metrics.registry.counter(
            "cake_deadline_expired_total"
        ).value(where="running") == 1
    finally:
        faults.clear()
        eng.stop()


def test_default_deadline_applies_to_bare_submissions():
    cfg, params = setup()
    eng = make_engine(cfg, params, start=False, default_deadline_s=9.0)
    h = eng.submit([Message.user("bare")], 4, GREEDY)
    with eng._cv:
        (req,) = list(eng._queue)
    assert req.deadline > time.monotonic()
    assert req.deadline == pytest.approx(time.monotonic() + 9.0, abs=1.0)
    assert h.finish_reason == "length"  # untouched until it actually runs


def test_deadline_aware_shed_refuses_doomed_submissions():
    cfg, params = setup()
    eng = make_engine(cfg, params, start=False)
    # The estimator has seen 5s queue waits; a 1s deadline is hopeless.
    eng._wait_est.observe(5.0)
    with pytest.raises(EngineOverloaded) as ei:
        eng.submit([Message.user("doomed")], 4, GREEDY, deadline_s=1.0)
    assert "deadline" in str(ei.value)
    assert eng.stats["shed"] == 1
    # Without a deadline the same submission queues fine.
    eng.submit([Message.user("fine")], 4, GREEDY)


def test_submit_validates_deadline_and_books_default_tenant():
    cfg, params = setup()
    eng = make_engine(cfg, params, start=False)
    with pytest.raises(ValueError, match="deadline_s"):
        eng.submit([Message.user("bad")], 4, GREEDY, deadline_s=-1)
    eng.submit([Message.user("ok")], 4, GREEDY, tenant="  ")
    with eng._cv:
        (req,) = list(eng._queue)
    assert req.tenant == DEFAULT_TENANT


def test_shed_refunds_quota_charge():
    """A 503 shed after the quota grant credits the bucket back: server
    overload must never drain the caller's own budget (the 429-vs-503
    attribution contract)."""
    cfg, params = setup()
    eng = make_engine(
        cfg, params, start=False, tenant_rate=10.0, tenant_burst=200.0,
        shed_queue_depth=1,
    )
    msgs = [Message.user("refund probe")]
    eng.submit(msgs, 16, GREEDY, tenant="t")  # queued: depth 1
    after_one = eng.tenant_meter.snapshot()["t"]
    for _ in range(3):
        with pytest.raises(EngineOverloaded):
            eng.submit(msgs, 16, GREEDY, tenant="t")
    snap = eng.tenant_meter.snapshot()["t"]
    # The three shed submissions charged nothing durable: the bucket and
    # the admitted-token ledger sit exactly where one submission left them.
    assert snap["bucket_level"] >= after_one["bucket_level"]
    assert snap["tokens"] == pytest.approx(after_one["tokens"])
    assert snap["active_streams"] == 1
