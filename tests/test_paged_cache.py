"""Paged KV cache: allocator bookkeeping + write/gather storage parity.

The allocator is pure host-side state (no jax needed for its tests); the
write/gather tests pin the paged pool against the dense cache as the storage
oracle — every mapped slot must hold exactly what the dense layout holds, and
every unmapped write must drop.
"""
# Deliberate pre-mutation snapshots assert what CoW splits did;
# cake-lint: disable-file=stale-block-table

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama.cache import init_cache, write_layer
from cake_tpu.models.llama.paged_cache import (
    PageAllocator,
    PageExhausted,
    copy_pages,
    gather_pages,
    init_paged_cache,
    paged_write_layer,
)
from cake_tpu.utils import metrics


# ---------------------------------------------------------------- allocator


def make_alloc(n_pages=8, page_size=16, batch=4, per_seq=4, reserve=1):
    return PageAllocator(
        n_pages, page_size, batch, per_seq, reserve_pages=reserve
    )


def test_map_range_allocates_only_boundary_crossings():
    a = make_alloc()
    a.map_range(0, 5, 40)  # slots 5..39 -> logical pages 0..2
    assert a.pages_free == 5
    assert (a.block_tables[0, :3] >= 0).all() and a.block_tables[0, 3] < 0
    a.map_range(0, 40, 48)  # still inside page 2: nothing new
    assert a.pages_free == 5
    a.map_range(0, 48, 49)  # first slot of page 3
    assert a.pages_free == 4


def test_release_returns_pages_and_unmaps():
    a = make_alloc()
    a.map_range(0, 0, 64)
    a.map_range(1, 0, 16)
    assert a.pages_free == 3
    a.release(0)
    assert a.pages_free == 7
    assert not a.lane_mapped(0) and a.lane_mapped(1)
    a.release(1)
    assert a.pages_free == 8


def test_front_pages_below_pad_are_not_allocated():
    # Left-padded lockstep: a lane whose live window starts mid-sequence
    # maps only the pages its window touches.
    a = make_alloc()
    a.map_range(2, 35, 60)  # pages 2..3 only
    assert a.pages_free == 6
    assert (a.block_tables[2, :2] < 0).all()
    assert (a.block_tables[2, 2:4] >= 0).all()


def test_exhaustion_is_atomic_and_counted():
    metrics.registry.clear()
    a = make_alloc(n_pages=3)
    a.map_range(0, 0, 32)  # 2 pages
    with pytest.raises(PageExhausted):
        a.map_range(1, 0, 33)  # needs 3, only 1 free
    # Nothing partially mapped, nothing leaked.
    assert not a.lane_mapped(1)
    assert a.pages_free == 1
    assert (
        metrics.registry.counter(
            "cake_kv_page_alloc_failures_total"
        ).value()
        == 1
    )


def test_can_admit_reserve_accounting():
    a = make_alloc(n_pages=4, reserve=1)
    assert a.can_admit(33)  # 3 pages + 1 reserve == 4
    assert not a.can_admit(49)  # 4 + 1 > 4
    a.map_range(0, 0, 16)
    assert not a.can_admit(33)  # 3 + 1 > 3 free


def test_fork_refcounts_and_release_order():
    a = make_alloc()
    a.map_range(0, 0, 48)  # 3 pages
    a.fork(0, 1)
    assert a.pages_shared == 3
    assert (a.block_tables[0] == a.block_tables[1]).all()
    assert a.pages_free == 5  # sharing cost nothing
    a.release(0)
    # Lane 1 still holds every page: nothing freed, nothing shared anymore.
    assert a.pages_free == 5
    assert a.pages_shared == 0
    a.release(1)
    assert a.pages_free == 8


def test_fork_into_mapped_lane_refuses():
    a = make_alloc()
    a.map_range(0, 0, 16)
    a.map_range(1, 0, 16)
    with pytest.raises(ValueError):
        a.fork(0, 1)


def test_make_private_copy_on_write_split():
    a = make_alloc()
    a.map_range(0, 0, 32)
    a.fork(0, 1)
    shared_phys = int(a.block_tables[1, 1])
    pair = a.make_private(1, 1)
    assert pair is not None
    src, dst = pair
    assert src == shared_phys and dst != src
    assert int(a.block_tables[1, 1]) == dst
    assert int(a.block_tables[0, 1]) == src  # owner keeps the original
    assert a.refcount[src] == 1 and a.refcount[dst] == 1
    # Exclusive page: a second split is a no-op.
    assert a.make_private(1, 1) is None


def test_make_private_exhaustion():
    a = make_alloc(n_pages=2)
    a.map_range(0, 0, 32)
    a.fork(0, 1)
    with pytest.raises(PageExhausted):
        a.make_private(1, 0)


def test_pool_gauges_track_state():
    metrics.registry.clear()
    a = make_alloc(n_pages=8)
    reg = metrics.registry
    assert reg.gauge("cake_kv_pages_total").value() == 8
    a.map_range(0, 0, 48)
    a.fork(0, 1)
    assert reg.gauge("cake_kv_pages_free").value() == 5
    assert reg.gauge("cake_kv_pages_shared").value() == 3
    a.release(0)
    a.release(1)
    assert reg.gauge("cake_kv_pages_free").value() == 8
    assert reg.gauge("cake_kv_pages_shared").value() == 0


def test_reset_frees_everything():
    a = make_alloc()
    a.map_range(0, 0, 64)
    a.reset(batch=2)
    assert a.pages_free == 8
    assert a.block_tables.shape == (2, 4)
    assert (a.block_tables < 0).all()


def test_map_range_beyond_table_capacity_raises():
    a = make_alloc(per_seq=2)
    with pytest.raises(ValueError):
        a.map_range(0, 0, 33)  # logical page 2 of a 2-page table


# ------------------------------------------------------------ write / gather


def test_paged_write_matches_dense_across_page_boundary():
    rng = np.random.default_rng(0)
    L, B, n_kv, hd, ps, n_pages, per_seq = 2, 2, 2, 8, 16, 10, 4
    dense = init_cache(L, B, per_seq * ps, n_kv, hd, jnp.float32)
    paged = init_paged_cache(L, n_pages, n_kv, ps, hd, jnp.float32)
    a = PageAllocator(n_pages, ps, B, per_seq)
    a.map_range(0, 0, 40)
    a.map_range(1, 3, 20)
    bt = jnp.asarray(a.block_tables)
    k_new = jnp.asarray(rng.normal(size=(B, 7, n_kv, hd)), jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(B, 7, n_kv, hd)), jnp.float32)
    pos = jnp.int32(12)  # slots 12..18 straddle the page-16 boundary
    for layer in range(L):
        dk, dv = write_layer(
            dense.k[layer], dense.v[layer], k_new, v_new, pos
        )
        pk, pv = paged_write_layer(
            paged.k[layer], paged.v[layer], k_new, v_new, pos, bt
        )
        np.testing.assert_array_equal(
            np.asarray(dk)[:, :, : per_seq * ps], np.asarray(gather_pages(pk, bt))
        )
        np.testing.assert_array_equal(
            np.asarray(dv)[:, :, : per_seq * ps], np.asarray(gather_pages(pv, bt))
        )


def test_unmapped_writes_drop():
    B, n_kv, hd, ps, n_pages, per_seq = 2, 2, 8, 16, 6, 4
    paged = init_paged_cache(1, n_pages, n_kv, ps, hd, jnp.float32)
    a = PageAllocator(n_pages, ps, B, per_seq)
    a.map_range(0, 0, 16)  # page 0 only; pages 1..3 unmapped
    bt = jnp.asarray(a.block_tables)
    ones = jnp.ones((B, 4, n_kv, hd), jnp.float32)
    pk, pv = paged_write_layer(
        paged.k[0], paged.v[0], ones, ones, jnp.int32(30), bt
    )
    # Row 0's write targeted unmapped page 1; row 1 has no pages at all.
    assert float(jnp.abs(pk).sum()) == 0.0
    g = gather_pages(pk, bt)
    assert float(jnp.abs(g).sum()) == 0.0


def test_gather_respects_physical_permutation():
    # Two lanes mapping the SAME logical content at different physical pages
    # must gather identical dense views — the indirection oracle.
    rng = np.random.default_rng(1)
    n_kv, hd, ps, n_pages = 2, 8, 16, 8
    pool = jnp.asarray(
        rng.normal(size=(n_pages, n_kv, ps, hd)), jnp.float32
    )
    bt = jnp.asarray([[3, 0, 5], [3, 0, 5]], jnp.int32)
    g = gather_pages(pool, bt)
    np.testing.assert_array_equal(np.asarray(g[0]), np.asarray(g[1]))
    np.testing.assert_array_equal(
        np.asarray(g[0, :, :ps]), np.asarray(pool[3])
    )
    np.testing.assert_array_equal(
        np.asarray(g[0, :, ps : 2 * ps]), np.asarray(pool[0])
    )


def test_copy_pages_moves_bytes_for_cow():
    rng = np.random.default_rng(2)
    cache = init_paged_cache(2, 6, 2, 16, 8, jnp.float32)
    cache = cache._replace(
        k=jnp.asarray(rng.normal(size=cache.k.shape), jnp.float32),
        v=jnp.asarray(rng.normal(size=cache.v.shape), jnp.float32),
    )
    out = copy_pages(cache, jnp.asarray([1, 3]), jnp.asarray([4, 5]))
    np.testing.assert_array_equal(
        np.asarray(out.k[:, 4]), np.asarray(cache.k[:, 1])
    )
    np.testing.assert_array_equal(
        np.asarray(out.v[:, 5]), np.asarray(cache.v[:, 3])
    )
    # Untouched pages keep their bytes.
    np.testing.assert_array_equal(
        np.asarray(out.k[:, 0]), np.asarray(cache.k[:, 0])
    )


def test_cow_fork_write_isolation_end_to_end():
    """fork -> make_private -> copy_pages -> diverging write: the owner's
    page is untouched, the forked lane sees its own bytes."""
    rng = np.random.default_rng(3)
    n_kv, hd, ps, n_pages, per_seq = 2, 8, 16, 8, 3
    cache = init_paged_cache(1, n_pages, n_kv, ps, hd, jnp.float32)
    a = PageAllocator(n_pages, ps, 2, per_seq)
    a.map_range(0, 0, 32)
    base = jnp.asarray(rng.normal(size=(1, 32, n_kv, hd)), jnp.float32)
    k0, v0 = paged_write_layer(
        cache.k[0], cache.v[0], base, base, jnp.int32(0),
        jnp.asarray(a.block_tables[:1]),
    )
    a.fork(0, 1)
    pair = a.make_private(1, 1)
    assert pair is not None
    full = cache._replace(k=k0[None], v=v0[None])
    full = copy_pages(full, np.asarray([pair[0]]), np.asarray([pair[1]]))
    # Lane 1 overwrites slot 20 (page 1) through ITS table only.
    delta = jnp.full((1, 1, n_kv, hd), 7.0, jnp.float32)
    bt1 = jnp.asarray(a.block_tables[1:2])
    k1, v1 = paged_write_layer(
        full.k[0], full.v[0], delta, delta, jnp.int32(20), bt1
    )
    g0 = gather_pages(k1, jnp.asarray(a.block_tables[:1]))
    g1 = gather_pages(k1, bt1)
    np.testing.assert_array_equal(
        np.asarray(g0[0, :, :32]),
        np.asarray(gather_pages(k0, jnp.asarray(a.block_tables[:1]))[0, :, :32]),
    )
    assert float(jnp.abs(g1[0, :, 20] - 7.0).max()) == 0.0
    assert float(jnp.abs(g0[0, :, 20] - 7.0).min()) > 0.0
