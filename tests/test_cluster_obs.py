"""Cluster observability plane (obs/cluster.py + the STATS wire message).

Covers the federation contract end to end: STATS frame symmetry, the
worker-side snapshot + replay safety, the NTP-style clock-offset oracle,
merge semantics (label collisions, counter monotonicity across pulls,
worker-restart snapshot reset), clock-aligned event/trace merging, and a
live 1-worker TCP cluster whose merged trace must nest worker op spans
inside the master's wire spans.
"""

import socket
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.obs.cluster import ClockOffsetEstimator, ClusterObserver
from cake_tpu.obs.timeline import validate_export
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import proto
from cake_tpu.runtime.master import DistributedForwardStep
from cake_tpu.runtime.worker import Worker
from cake_tpu.utils import metrics

MAX_SEQ = 96

# ------------------------------------------------------------- wire contract


def test_stats_frame_roundtrip():
    req = proto.stats_request_frame(events=7, timeline=9)
    back = proto.decode_frame(memoryview(proto.encode_frame(req)))
    assert back.type == proto.MsgType.STATS
    assert back.header == {"events": 7, "timeline": 9}
    reply = proto.stats_reply_frame(
        {"node": "w0", "wall": 1.5, "metrics": {"metrics": []},
         "events": [], "timeline": []}
    )
    back = proto.decode_frame(memoryview(proto.encode_frame(reply)))
    assert back.type == proto.MsgType.STATS
    assert back.header["report"]["node"] == "w0"


def test_ping_frame_wall_clock_is_optional():
    assert proto.ping_frame().header == {}
    f = proto.ping_frame(t=123.456789)
    assert f.header == {"t": 123.456789}


# --------------------------------------------------------- offset estimator


def test_clock_offset_oracle_recovers_seeded_skew():
    """Synthetic skew: worker clock = master clock + true_offset; reply
    stamps taken at the true round-trip midpoint +/- asymmetry. The
    estimate must land within RTT/2 of the truth (the documented bound)."""
    rng = np.random.default_rng(7)
    true_offset = 1.837
    rtt = 0.02
    est = ClockOffsetEstimator()
    t = 1000.0
    for _ in range(40):
        asym = float(rng.uniform(-rtt / 2, rtt / 2))
        t_send = t
        t_recv = t + rtt
        # Worker reads its clock at midpoint + asym on the worker clock.
        t_worker = (t_send + rtt / 2 + asym) + true_offset
        est.observe(t_send, t_recv, t_worker)
        t += 1.0
    assert abs(est.offset - true_offset) <= rtt / 2
    assert est.error_bound_s == pytest.approx(rtt / 2)


def test_clock_offset_rejects_congested_round_trips():
    est = ClockOffsetEstimator()
    for i in range(5):
        t = float(i)
        est.observe(t, t + 0.01, t + 0.005 + 2.0)  # clean: offset 2.0
    # A wildly congested sample (RTT 30x best) with a bogus midpoint must
    # not move the estimate.
    before = est.offset
    est.observe(100.0, 100.3, 100.0)
    assert est.offset == before


def test_clock_offset_gate_reopens_after_regime_shift():
    """A sustained RTT increase (route change, loaded link) must not
    freeze the estimate on the stale idle-link minimum: each rejection
    ages the gate, so the new regime is accepted within a few probes."""
    est = ClockOffsetEstimator()
    for i in range(5):
        t = float(i)
        est.observe(t, t + 0.001, t + 0.0005 + 1.0)  # idle link, offset 1
    # RTT jumps 20x and STAYS there; the worker clock also steps.
    accepted_at = None
    for i in range(20):
        t = 100.0 + i
        before = est.samples
        est.observe(t, t + 0.02, t + 0.01 + 3.0)
        if est.samples > before:
            accepted_at = i
            break
    assert accepted_at is not None and accepted_at < 15
    for i in range(40):
        t = 200.0 + i
        est.observe(t, t + 0.02, t + 0.01 + 3.0)
    assert abs(est.offset - 3.0) < 0.25  # converging on the new regime


def test_merged_exposition_respects_per_node_buckets():
    """A version-skewed node shipping different bucket edges renders
    against ITS OWN edges; a series whose counts/buckets disagree in
    length is dropped, never mislabeled."""
    obs = ClusterObserver()
    obs.update_report("w0", _report("w0", {
        "name": "cake_op_seconds", "kind": "histogram", "help": "h",
        "buckets": [0.1, 1.0],
        "series": [{"labels": {"node": "w0"}, "counts": [1, 2, 3],
                    "sum": 4.0, "count": 6, "min": 0.05, "max": 5.0}],
    }))
    obs.update_report("w1", _report("w1", {
        "name": "cake_op_seconds", "kind": "histogram", "help": "h",
        "buckets": [0.5],  # different edges (older worker)
        "series": [
            {"labels": {"node": "w1"}, "counts": [4, 1],
             "sum": 2.0, "count": 5, "min": 0.1, "max": 1.0},
            {"labels": {"node": "w1", "kind": "x"}, "counts": [1, 2, 3],
             "sum": 1.0, "count": 6, "min": 0.1, "max": 1.0},  # malformed
        ],
    }))
    text = obs.merged_exposition({"metrics": []})
    assert 'cake_op_seconds_bucket{node="w0",le="1"} 3' in text
    assert 'cake_op_seconds_bucket{node="w1",le="0.5"} 4' in text
    assert 'cake_op_seconds_bucket{node="w1",le="+Inf"} 5' in text
    assert 'kind="x"' not in text  # malformed series dropped whole


def test_observer_exports_offset_gauge():
    obs = ClusterObserver()
    obs.observe_ping("w0", 10.0, 10.02, 11.01)
    g = metrics.registry.gauge("cake_clock_offset_seconds")
    assert g.value(node="w0") == pytest.approx(1.0, abs=0.011)
    # Old worker: no reply stamp -> node registered, nothing estimated.
    obs.observe_ping("w1", 10.0, 10.02, None)
    assert obs.offset("w1") == 0.0


# ------------------------------------------------------------ merge semantics


def _dump_counter(name, value, **labels):
    return {
        "name": name, "kind": "counter", "help": "h",
        "series": [{"labels": labels, "value": value}],
    }


def _report(node, *metric_dumps, events=(), timeline=()):
    return {
        "node": node, "wall": 0.0,
        "metrics": {"metrics": list(metric_dumps)},
        "events": list(events), "timeline": list(timeline),
    }


def test_merged_exposition_label_collision_keeps_both_nodes():
    """The same family from two nodes shares ONE header; node labels keep
    the series distinct (no silent collision)."""
    obs = ClusterObserver()
    obs.update_report(
        "w0", _report("w0", _dump_counter("cake_ops_total", 3, node="w0"))
    )
    obs.update_report(
        "w1", _report("w1", _dump_counter("cake_ops_total", 5, node="w1"))
    )
    local = {"metrics": [_dump_counter("cake_ops_total", 7)]}
    text = obs.merged_exposition(local)
    assert text.count("# TYPE cake_ops_total counter") == 1
    assert 'cake_ops_total{node="w0"} 3' in text
    assert 'cake_ops_total{node="w1"} 5' in text
    assert 'cake_ops_total{node="master"} 7' in text


def test_merged_exposition_counter_monotonic_across_pulls():
    """Pull model: the latest snapshot REPLACES — two pulls of a growing
    counter expose the newest value once, never a sum."""
    obs = ClusterObserver()
    obs.update_report(
        "w0", _report("w0", _dump_counter("cake_ops_total", 3, node="w0"))
    )
    obs.update_report(
        "w0", _report("w0", _dump_counter("cake_ops_total", 9, node="w0"))
    )
    text = obs.merged_exposition({"metrics": []})
    assert 'cake_ops_total{node="w0"} 9' in text
    assert "12" not in text  # never summed across pulls


def test_merged_exposition_worker_restart_resets_to_worker_truth():
    obs = ClusterObserver()
    obs.update_report(
        "w0", _report("w0", _dump_counter("cake_ops_total", 50, node="w0"))
    )
    # Restarted worker reports from scratch: the node's series resets.
    obs.update_report(
        "w0", _report("w0", _dump_counter("cake_ops_total", 2, node="w0"))
    )
    text = obs.merged_exposition({"metrics": []})
    assert 'cake_ops_total{node="w0"} 2' in text
    assert "50" not in text


def test_merged_exposition_keeps_master_series_about_workers():
    """Master-side observations ABOUT w0 (hop latency, clock offset) exist
    nowhere else and must survive the merge; only EXACT duplicates of
    reported series (shared-registry test clusters) are dropped."""
    obs = ClusterObserver()
    obs.update_report(
        "w0",
        _report("w0", _dump_counter("cake_worker_ops_total", 4, node="w0")),
    )
    local = {
        "metrics": [
            # The master's own view of the hop — not in the report.
            _dump_counter("cake_hop_failures_total", 1, node="w0"),
            # Shared-registry duplicate of the reported series.
            _dump_counter("cake_worker_ops_total", 4, node="w0"),
        ]
    }
    text = obs.merged_exposition(local)
    assert 'cake_hop_failures_total{node="w0"} 1' in text
    assert text.count('cake_worker_ops_total{node="w0"} 4') == 1


def test_merged_events_interleave_by_aligned_time():
    obs = ClusterObserver()
    # Worker clock 5 s AHEAD of the master: converge the estimator.
    for i in range(20):
        t = float(i)
        obs.observe_ping("w0", t, t + 0.01, t + 0.005 + 5.0)
    obs.update_report(
        "w0",
        _report(
            "w0",
            events=[{"ts": 105.2, "event": "op-replayed", "node": "w0"}],
        ),
    )
    merged = obs.merged_events(
        [{"ts": 100.1, "event": "submitted"},
         {"ts": 100.3, "event": "finished"}]
    )
    assert [e["event"] for e in merged] == [
        "submitted", "op-replayed", "finished"
    ]  # 105.2 - 5.0 = 100.2 lands between the master events
    assert merged[1]["node"] == "w0"
    assert merged[0]["node"] == "master"
    assert merged[1]["ts"] == pytest.approx(100.2, abs=0.02)


def test_merged_trace_aligns_seeded_skew_into_nesting():
    """A worker trace recorded on a clock 5 s ahead: after the offset
    shift its op span must sit INSIDE the master wire span that caused it,
    and the export must validate with two process tracks."""
    obs = ClusterObserver()
    for i in range(20):
        t = float(i)
        obs.observe_ping("w0", t, t + 0.01, t + 0.005 + 5.0)
    local = [
        {"ph": "X", "name": "wire.w0", "wall": 100.0, "mono": 0.0,
         "dur": 0.1, "id": 1, "track": "wire"},
        {"ph": "s", "name": "hop", "wall": 100.005, "mono": 0.0,
         "flow": 42, "track": "wire"},
    ]
    obs.update_report(
        "w0",
        _report(
            "w0",
            timeline=[
                {"ph": "X", "name": "worker.chunk", "wall": 105.02,
                 "mono": 0.0, "dur": 0.05, "id": 2, "node": "w0",
                 "track": "ops"},
                {"ph": "f", "name": "hop", "wall": 105.03, "mono": 0.0,
                 "flow": 42, "node": "w0", "track": "ops"},
            ],
        ),
    )
    trace = obs.merged_trace(local)
    assert validate_export(trace) == []
    events = trace["traceEvents"]
    pids = {
        e["args"]["name"]: e["pid"]
        for e in events
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert set(pids) == {"master", "w0"}
    wire = next(e for e in events if e.get("name") == "wire.w0")
    op = next(e for e in events if e.get("name") == "worker.chunk")
    assert op["pid"] == pids["w0"] and wire["pid"] == pids["master"]
    # Nesting in aligned time: the op interval inside the wire interval.
    assert wire["ts"] <= op["ts"]
    assert op["ts"] + op["dur"] <= wire["ts"] + wire["dur"]


# --------------------------------------------------------------- live worker


@pytest.fixture(scope="module")
def one_worker(tmp_path_factory):
    model_dir = tmp_path_factory.mktemp("ckpt") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w0": {"host": "placeholder", "layers": ["model.layers.1-2"]}}
    )
    w = Worker(
        "w0", model_dir, topo, ("127.0.0.1", 0),
        dtype=jnp.float32, max_seq_len=MAX_SEQ,
    )
    w.start()
    topo.nodes["w0"].host = f"127.0.0.1:{w.address[1]}"
    yield cfg, params, model_dir, topo, w
    w.stop()


def _handshake(topo):
    host, port = topo.nodes["w0"].host.split(":")
    sock = socket.create_connection((host, int(port)), timeout=10)
    sock.settimeout(10)
    proto.write_frame(sock, proto.hello_frame())
    info = proto.read_frame(sock)
    assert info.type == proto.MsgType.WORKER_INFO
    return sock, proto.WorkerInfo.from_dict(info.header["info"])


def test_worker_ping_stamps_wall_clock(one_worker):
    _, _, _, topo, _ = one_worker
    sock, info = _handshake(topo)
    try:
        assert info.stats_ops is True
        proto.write_frame(sock, proto.ping_frame())
        reply = proto.read_frame(sock)
        assert reply.type == proto.MsgType.PING
        assert abs(reply.header["t"] - time.time()) < 5.0
    finally:
        sock.close()


def test_stats_pull_is_replay_safe_mid_session(one_worker):
    """A STATS pull between a session's ops must not disturb its replay
    state: the next seq succeeds, and a duplicate (sid, seq) resend is
    still answered from the replay cache."""
    cfg, _, _, topo, worker = one_worker
    from cake_tpu.runtime.client import StageClient

    client = StageClient(topo.nodes["w0"].host, "w0", timeout=10)
    try:
        client.begin_session("obs-sess")
        x = proto.WireTensor.from_numpy(
            np.zeros((1, 1, cfg.hidden_size), np.float32)
        )
        out0 = client.forward(x, [(1, 3)], pos=0)
        # STATS mid-session on the SAME socket (request-reply protocol).
        proto.write_frame(client._sock, proto.stats_request_frame())
        stats = proto.read_frame(client._sock)
        assert stats.type == proto.MsgType.STATS
        report = stats.header["report"]
        assert report["node"] == "w0"
        names = {m["name"] for m in report["metrics"]["metrics"]}
        assert "cake_worker_op_seconds" in names
        # Session still intact: the next seq executes...
        out1 = client.forward(x, [(1, 3)], pos=1)
        assert out1.shape == out0.shape
        # ...and a duplicate (sid, seq=1) resend replays, not re-executes.
        dup = proto.forward_frame(
            x, [(1, 3)], pos=1, sid="obs-sess", seq=1
        )
        proto.write_frame(client._sock, dup)
        replay = proto.read_frame(client._sock)
        assert replay.type == proto.MsgType.TENSOR
        np.testing.assert_array_equal(
            replay.tensor().to_numpy(), out1.to_numpy()
        )
        assert metrics.registry.counter(
            "cake_worker_replays_total"
        ).value(node="w0") >= 1
    finally:
        client.close()


def test_e2e_tcp_merged_plane(one_worker):
    """Live 1-worker TCP serve: the master pulls the worker's telemetry
    (fresh-connection pull path), the merged exposition carries both
    nodes, and the merged trace validates with worker op spans nested
    inside the master's wire.w0 spans."""
    cfg, params, model_dir, topo, worker = one_worker
    from cake_tpu.obs.timeline import timeline

    obs = ClusterObserver()
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
    )
    try:
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
        )
        gen.add_message(Message.user("cluster trace"))
        gen.generate(4)
        assert step.pull_cluster_stats(observer=obs) == ["w0"]
    finally:
        step.close()

    # Merged exposition: worker op series under node="w0", master-side
    # hop series (recorded locally ABOUT w0) preserved, master's own
    # series under node="master".
    text = obs.merged_exposition(metrics.registry.dump())
    assert 'cake_worker_op_seconds_count{kind="chunk",node="w0"}' in text
    assert 'cake_hop_seconds_count{node="w0"}' in text
    assert 'cake_clock_offset_seconds{node="w0"}' in text

    trace = obs.merged_trace(timeline.snapshot())
    assert validate_export(trace) == []
    events = trace["traceEvents"]
    pid_names = {
        e["pid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e["name"] == "process_name"
    }
    assert set(pid_names.values()) >= {"master", "w0"}
    wire = [
        (e["ts"], e["ts"] + e["dur"])
        for e in events
        if e.get("ph") == "X" and e.get("name") == "wire.w0"
        and pid_names[e["pid"]] == "master"
    ]
    ops = [
        (e["ts"], e["ts"] + e["dur"])
        for e in events
        if e.get("ph") == "X"
        and str(e.get("name", "")).startswith("worker.")
        and pid_names[e["pid"]] == "w0"
    ]
    assert wire and ops
    nested = sum(
        any(w0 <= o0 and o1 <= w1 for (w0, w1) in wire) for (o0, o1) in ops
    )
    assert nested > 0
    # Flow arrows cross the process tracks.
    flows: dict = {}
    for e in events:
        if e.get("ph") in ("s", "f"):
            flows.setdefault(e["id"], {})[e["ph"]] = pid_names[e["pid"]]
    assert any(
        v.get("s") == "master" and v.get("f") == "w0"
        for v in flows.values()
    )
