"""Multi-host seam (parallel/multihost.py): 2-process CPU-mesh integration.

SURVEY.md §7 step 4: multi-host runs use jax.distributed + the existing
shard_map pipeline; the TCP protocol stays the heterogeneity escape hatch.
This spawns two REAL processes (the same virtual-device seam the driver's
multichip dryrun uses — 4 CPU devices each, 8 global), joins them through a
localhost coordinator, and checks lockstep generation over the global
4-stage x tp-2 mesh against the single-device oracle.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

CHILD = Path(__file__).with_name("_multihost_child.py")

# The environmental-failure signature (SMOKE.md): this jaxlib's CPU client
# has no cross-process collective implementation.
_NO_CPU_COLLECTIVES = (
    "Multiprocess computations aren't implemented on the CPU backend"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_matches_local_oracle():
    port = _free_port()
    repo_root = str(CHILD.parent.parent)
    prior = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",  # skip the TPU-tunnel sitecustomize entirely
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=repo_root + (os.pathsep + prior if prior else ""),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(port), str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost children hung; partial output: {outs}")
    if any(p.returncode != 0 for p in procs) and any(
        _NO_CPU_COLLECTIVES in out for out in outs
    ):
        # Capability-probed environmental skip (SMOKE.md): this jaxlib's
        # CPU client has no multiprocess collective implementation — the
        # children die inside broadcast_one_to_all with exactly this error.
        # The probe IS the run: any OTHER failure still fails the test, so
        # real multihost regressions stay unmissable on backends that do
        # support cross-process collectives.
        pytest.skip(
            "jaxlib CPU backend lacks multiprocess collectives "
            f"({_NO_CPU_COLLECTIVES!r}); needs a multi-chip backend or a "
            "gloo-enabled jaxlib — see SMOKE.md"
        )
    assert procs[0].returncode == 0, outs[0][-3000:]
    assert procs[1].returncode == 0, outs[1][-3000:]
    assert "MH_TOKENS_OK" in outs[0]
    assert "MH_FOLLOWER_DONE" in outs[1]
