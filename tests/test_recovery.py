"""Elastic recovery: worker connection loss mid-generation heals via replay.

The reference tears the whole run down on any connection error (SURVEY.md §5:
no reconnect, no retry). Here the master reconnects the failed node, the
generator rebuilds ALL KV state by replaying its token history as a chunked
prefill, and the stream resumes — byte-identical to an uninterrupted run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
    StepConnectionError,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime.master import DistributedForwardStep
from cake_tpu.runtime.worker import Worker

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    model_dir = tmp_path_factory.mktemp("rckpt") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(41), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w": {"host": "placeholder", "layers": ["model.layers.1-2"]}}
    )
    worker = Worker(
        "w", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32, max_seq_len=128
    )
    worker.start()
    topo.nodes["w"].host = f"127.0.0.1:{worker.address[1]}"
    yield cfg, params, model_dir, topo
    worker.stop()


def make_gen(cfg, model_dir, topo):
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=128
    )
    return LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)


def test_connection_loss_mid_generation_recovers(cluster):
    cfg, params, model_dir, topo = cluster
    prompt = "resilience probe"

    # Uninterrupted oracle (local, same params/numerics).
    ref = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
    )
    ref.add_message(Message.user(prompt))
    want = ref.generate(12)

    gen = make_gen(cfg, model_dir, topo)
    gen.add_message(Message.user(prompt))
    first = gen.generate(5)
    # Simulate a network blip: kill the live socket under the master.
    gen.step.clients["w"]._sock.close()
    rest = gen.generate(7)
    assert (first + rest) == want
    gen.step.close()


def test_recovery_budget_is_per_incident_not_per_call(cluster):
    """Three blips inside ONE generate() call, separated by successful tokens,
    must not abort: the allowance resets once progress is made (ADVICE r1).
    Uses the default per-step decode path — the branch where a try/else-based
    reset would be skipped by `continue`."""
    cfg, params, model_dir, topo = cluster
    prompt = "three separate incidents"

    ref = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
    )
    ref.add_message(Message.user(prompt))
    want = ref.generate(16)

    gen = make_gen(cfg, model_dir, topo)
    gen.add_message(Message.user(prompt))
    emitted = 0

    def blip_every_4th(tok):
        nonlocal emitted
        emitted += 1
        if emitted in (4, 8, 12):  # 3 incidents > the per-incident budget of 2
            gen.step.clients["w"]._sock.close()

    out = gen.generate(16, on_token=blip_every_4th)
    assert out == want
    gen.step.close()


def test_recovery_gives_up_after_repeated_failures(cluster, monkeypatch):
    cfg, params, model_dir, topo = cluster
    gen = make_gen(cfg, model_dir, topo)
    gen.add_message(Message.user("fail forever"))

    def always_fail(*a, **kw):
        raise StepConnectionError("w")

    gen.generate(2)  # healthy prefill + a token first
    monkeypatch.setattr(gen.step.clients["w"], "forward", always_fail)
    # Replay itself also needs the worker -> every retry fails -> bounded raise.
    with pytest.raises(StepConnectionError):
        gen.generate(4)
    gen.step.close()
