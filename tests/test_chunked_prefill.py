"""Chunked prefill continuation: long prompts in bounded chunks == one shot.

The oracle everywhere: for a fixed seed and greedy sampling, prefilling the
prompt in chunks (cache-prefix attention per chunk, models/llama/model.py
``cached_prefill``) must reproduce the one-shot prefill token stream exactly,
on every execution backend.
"""

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
PROMPT = "a rather long prompt that spans several prefill chunks for sure"


def run(step_factory, prefill_chunk, n_new=8):
    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    gen = LlamaGenerator(
        cfg,
        step_factory(cfg, params),
        ByteTokenizer(),
        GREEDY,
        prefill_chunk=prefill_chunk,
    )
    gen.add_message(Message.user(PROMPT))
    gen.generate(n_new)
    return list(gen.generated_token_ids)


def local_step(cfg, params):
    return LocalForwardStep(cfg, params, max_seq_len=256, cache_dtype=jnp.float32)


def test_local_chunked_matches_one_shot():
    want = run(local_step, None)
    assert run(local_step, 16) == want
    # Chunk size that doesn't divide the prompt: exercises the bucketed tail.
    assert run(local_step, 13) == want


def test_prompt_equal_to_chunk_stays_single_shot():
    # Prompt shorter than the cap: must behave exactly like one-shot.
    want = run(local_step, None)
    assert run(local_step, 4096) == want


def test_pipeline_chunked_matches_one_shot():
    from cake_tpu.parallel.pipeline import PipelineRunner

    def step(cfg, params):
        return PipelineRunner(
            cfg, params, [(0, 2), (2, 4)], max_seq_len=256, cache_dtype=jnp.float32
        )

    assert run(step, 16) == run(step, None)


def test_tensor_parallel_chunked_matches_one_shot():
    from cake_tpu.parallel.tensor import TensorParallelRunner

    def step(cfg, params):
        return TensorParallelRunner(
            cfg, params, tp=2, max_seq_len=256, cache_dtype=jnp.float32
        )

    assert run(step, 16) == run(step, None)


def test_worker_chunked_matches_one_shot(tmp_path):
    """TCP path: the worker selects the cached-prefill variant per frame."""
    from cake_tpu.io.safetensors_io import save_tiny_checkpoint
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w": {"host": "placeholder", "layers": ["model.layers.1-2"]}}
    )
    worker = Worker(
        "w", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32, max_seq_len=256
    )
    worker.start()
    topo.nodes["w"].host = f"127.0.0.1:{worker.address[1]}"
    try:
        outs = []
        for chunk in (None, 16):
            step = DistributedForwardStep(
                cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=256
            )
            gen = LlamaGenerator(
                cfg, step, ByteTokenizer(), GREEDY, prefill_chunk=chunk
            )
            gen.add_message(Message.user(PROMPT))
            gen.generate(8)
            outs.append(list(gen.generated_token_ids))
            step.close()
        assert outs[0] == outs[1]
    finally:
        worker.stop()


def test_tail_bucket_clamped_to_cache_bounds():
    """Regression: a pow2 tail bucket must never write past max_seq_len.

    Crafted so the tail chunk's bucket (32) would overrun the cache end if not
    clamped — dynamic_update_slice would then clamp the START index and
    silently overwrite the last prompt positions' KV.
    """
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(4), jnp.float32)

    def step():
        return LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32)

    # Find content length giving a prompt of ~122 ids (117..127 window).
    probe = LlamaGenerator(cfg, step(), ByteTokenizer(), GREEDY)
    probe.add_message(Message.user(""))
    overhead = probe.prompt_token_count()
    content = "y" * (122 - overhead)

    outs = []
    for cap in (None, 100):  # cap=100: off=100, rem=22, bucket 32 > 128-100
        gen = LlamaGenerator(
            cfg, step(), ByteTokenizer(), GREEDY, prefill_chunk=cap
        )
        gen.add_message(Message.user(content))
        gen.generate(5)
        n = gen.prompt_token_count()
        assert 117 <= n <= 127, n  # precondition for the overrun scenario
        outs.append(list(gen.generated_token_ids))
    assert outs[0] == outs[1]


def test_prefill_chunk_must_be_positive():
    import pytest as _pytest

    cfg = LlamaConfig.tiny()
    with _pytest.raises(ValueError, match="prefill_chunk"):
        LlamaGenerator(
            cfg,
            LocalForwardStep(cfg, M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)),
            ByteTokenizer(),
            GREEDY,
            prefill_chunk=0,
        )
