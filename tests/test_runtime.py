"""Runtime tests: wire-protocol round trips and master<->worker TCP serving.

The multi-node-without-a-cluster seam from SURVEY.md §4: workers are plain TCP
servers on configurable localhost ports, so a real sharded deployment runs inside
one test process (threads), and its greedy tokens must equal the single-host
oracle's.
"""

import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.io.safetensors_io import save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import proto
from cake_tpu.runtime.client import StageClient
from cake_tpu.runtime.master import DistributedForwardStep, Master
from cake_tpu.runtime.worker import Worker

MAX_SEQ = 96

# ---------------------------------------------------------------- proto


def test_frame_roundtrip_with_payload():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    f = proto.forward_frame(
        proto.WireTensor.from_numpy(x), [(0, 2), (4, 6)], pos=7
    )
    buf = memoryview(proto.encode_frame(f))
    g = proto.decode_frame(buf)
    assert g.type == proto.MsgType.FORWARD
    assert g.header["ranges"] == [[0, 2], [4, 6]]
    assert g.header["pos"] == 7
    # The header is FULLY consumed by the worker: ranges + pos + the tensor
    # descriptor, nothing else (no per-chunk validity field travels — pad
    # tails are safe via causal masking, see proto.MsgType.FORWARD).
    assert set(g.header) == {"ranges", "pos", "tensor"}
    np.testing.assert_array_equal(g.tensor().to_numpy(), x)


def test_padded_tail_kv():
    """The contract that lets FORWARD travel without a validity field: a
    prefill chunk with a padded tail leaves garbage KV at FUTURE positions,
    which the causal mask hides from every later query until real decode
    tokens overwrite those slots — so the decode stream after a padded
    prefill equals the stream after an exact-width prefill."""
    from cake_tpu.models.llama.cache import init_cache

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(11), jnp.float32)
    prompt = [5, 3, 8]

    def run(pad_to: int) -> list[int]:
        kv = init_cache(
            cfg.num_hidden_layers, 1, 32, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        chunk = np.zeros((1, pad_to), np.int32)
        chunk[0, : len(prompt)] = prompt
        logits, kv = M.forward(
            params, jnp.asarray(chunk), kv, jnp.int32(0),
            jnp.int32(len(prompt)), cfg,
        )
        toks = [int(jnp.argmax(logits[0]))]
        pos = len(prompt)
        for _ in range(4):
            logits, kv = M.forward(
                params, jnp.asarray([[toks[-1]]], jnp.int32), kv,
                jnp.int32(pos), jnp.int32(1), cfg,
            )
            toks.append(int(jnp.argmax(logits[0])))
            pos += 1
        return toks

    assert run(len(prompt)) == run(16)  # exact width vs pow2-padded tail


def test_frame_roundtrip_over_socket_pair():
    a, b = socket.socketpair()
    x = np.ones((1, 4, 8), np.float16)
    proto.write_frame(a, proto.tensor_frame(proto.WireTensor.from_numpy(x)))
    got = proto.read_frame(b)
    assert got.type == proto.MsgType.TENSOR
    np.testing.assert_array_equal(got.tensor().to_numpy(), x)
    a.close(), b.close()


def test_forward_frame_session_headers_roundtrip():
    """sid/seq travel together (the epoch-replay contract, ISSUE 6) and are
    ABSENT without a session — old peers interoperate unchanged."""
    x = proto.WireTensor.from_numpy(np.zeros((1, 2, 4), np.float32))
    g = proto.decode_frame(memoryview(proto.encode_frame(
        proto.forward_frame(x, [(0, 2)], pos=3, sid="ep-abc", seq=7)
    )))
    assert g.header["sid"] == "ep-abc"
    assert g.header["seq"] == 7
    legacy = proto.decode_frame(memoryview(proto.encode_frame(
        proto.forward_frame(x, [(0, 2)], pos=3)
    )))
    assert "sid" not in legacy.header and "seq" not in legacy.header


def test_error_frame_code_and_reset_sid_roundtrip():
    g = proto.decode_frame(memoryview(proto.encode_frame(
        proto.error_frame("gone", code=proto.ERR_UNKNOWN_SESSION)
    )))
    assert g.header["code"] == proto.ERR_UNKNOWN_SESSION
    assert "code" not in proto.error_frame("plain").header
    r = proto.decode_frame(memoryview(proto.encode_frame(
        proto.reset_frame(sid="ep-abc")
    )))
    assert r.header["sid"] == "ep-abc"
    assert proto.reset_frame().header == {}


def test_reconnect_backoff_never_sleeps_after_final_attempt(monkeypatch):
    """The backoff fix pinned: N failed attempts sleep exactly N-1 times —
    the caller gets the ConnectionError immediately after the last dial."""
    from cake_tpu.runtime import client as client_mod

    sleeps: list[float] = []
    monkeypatch.setattr(
        client_mod.time, "sleep", lambda s: sleeps.append(s)
    )
    sc = StageClient.__new__(StageClient)
    sc.node_name = "w0"
    sc.host = "127.0.0.1:1"  # closed port: dial fails fast
    sc._timeout = 0.2
    sc.op_deadline_s = 0.2
    sc.op_retries = 0
    sc.reconnect_attempts = 3
    sc.reconnect_backoff_s = 0.25
    sc.sid = None
    sc._seq = 0

    class _DeadSock:
        def close(self):
            pass

    sc._sock = _DeadSock()
    with pytest.raises(ConnectionError, match="could not reconnect"):
        sc.reconnect()
    assert sleeps == [0.25, 0.5]  # exponential, none after the final failure
    # Attempts/backoff are configurable per client (ServeConfig/CLI thread
    # them through): explicit args override the instance defaults.
    sleeps.clear()
    with pytest.raises(ConnectionError):
        sc.reconnect(attempts=1)
    assert sleeps == []


def test_frame_rejects_bad_magic():
    f = proto.encode_frame(proto.hello_frame())
    corrupted = b"XXXX" + f[4:]
    with pytest.raises(ValueError, match="bad magic"):
        proto.decode_frame(memoryview(corrupted))


def test_frame_rejects_oversize(monkeypatch):
    monkeypatch.setattr(proto, "MAX_FRAME_SIZE", 64)
    x = np.zeros((1024,), np.float32)
    with pytest.raises(ValueError, match="exceeds cap"):
        proto.encode_frame(
            proto.tensor_frame(proto.WireTensor.from_numpy(x))
        )


def test_worker_info_roundtrip():
    info = proto.WorkerInfo(device="tpu", latency_ms=1.5, ranges=[[0, 4]])
    f = proto.worker_info_frame(info)
    g = proto.decode_frame(memoryview(proto.encode_frame(f)))
    info2 = proto.WorkerInfo.from_dict(g.header["info"])
    assert info2.device == "tpu"
    assert info2.ranges == [[0, 4]]
    assert info2.version == info.version


def test_bf16_wire_roundtrip():
    x = jnp.asarray([[1.5, -2.25, 3.0]], jnp.bfloat16)
    from cake_tpu.runtime.worker import jax_to_wire, wire_to_jax

    wt = jax_to_wire(x)
    assert wt.dtype == "bf16"
    back = wire_to_jax(
        proto.WireTensor(
            data=bytes(wt.data), dtype=wt.dtype, shape=wt.shape
        ),
        jnp.bfloat16,
    )
    np.testing.assert_array_equal(
        np.asarray(back.astype(jnp.float32)), np.asarray(x.astype(jnp.float32))
    )


# ---------------------------------------------------------------- live cluster


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """Two live workers + checkpoint + topology on localhost."""
    model_dir = tmp_path_factory.mktemp("ckpt") / "model"
    cfg = LlamaConfig.tiny(num_hidden_layers=6)
    params = M.init_params(cfg, jax.random.PRNGKey(11), jnp.float32)
    save_tiny_checkpoint(model_dir, params, cfg)

    topo = Topology.from_dict(
        {
            "w1": {"host": "placeholder", "layers": ["model.layers.0-1"]},
            "w2": {"host": "placeholder", "layers": ["model.layers.3-4"]},
        }
    )
    workers = []
    for name in ("w1", "w2"):
        w = Worker(
            name,
            model_dir,
            topo,
            ("127.0.0.1", 0),
            dtype=jnp.float32,
            max_seq_len=MAX_SEQ,
        )
        w.start()
        topo.nodes[name].host = f"127.0.0.1:{w.address[1]}"
        workers.append(w)

    yield cfg, params, model_dir, topo, workers
    for w in workers:
        w.stop()


def greedy_ids(cfg, step, prompt="distributed oracle"):
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    gen.add_message(Message.user(prompt))
    gen.generate(6)
    return gen.generated_token_ids


def test_worker_owns_only_its_ranges(cluster):
    cfg, params, model_dir, topo, workers = cluster
    assert workers[0].ranges == [(0, 2)]
    assert workers[1].ranges == [(3, 5)]


def test_distributed_matches_local_oracle(cluster):
    cfg, params, model_dir, topo, workers = cluster
    local = greedy_ids(
        cfg,
        LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32),
    )
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
    )
    try:
        assert greedy_ids(cfg, step) == local
        # reset + regenerate on live connections must reproduce (exercises RESET).
        assert greedy_ids(cfg, step) == local
    finally:
        step.close()


def test_distributed_speculative_matches_plain_and_saves_round_trips(cluster):
    """--speculative-k over TCP workers: exact greedy stream, fewer worker
    round trips than per-token decode on a draft-friendly (repetitive) prompt."""
    cfg, params, model_dir, topo, workers = cluster
    from cake_tpu.models.llama.chat import Message

    calls = {"n": 0}

    class CountingClient(StageClient):
        def forward(self, *a, **k):
            calls["n"] += 1
            return super().forward(*a, **k)

    def run(spec_k):
        calls["n"] = 0
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ,
            client_factory=CountingClient,
        )
        gen = LlamaGenerator(
            cfg,
            step,
            ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            speculative_k=spec_k,
        )
        try:
            gen.add_message(Message.user("ab ab ab ab ab ab ab ab"))
            gen.generate(16)
            return list(gen.generated_token_ids), calls["n"]
        finally:
            step.close()

    plain, plain_calls = run(0)
    spec, spec_calls = run(6)
    assert spec == plain  # speculation is exact: speed, never output
    assert spec_calls < plain_calls  # drafts actually verified in chunks


def test_distributed_prefix_reuse_matches_fresh(cluster):
    """prefix_cache over TCP workers: turn-2 reuses worker-side KV (reset is
    skipped), token stream identical to a fresh distributed run."""
    cfg, params, model_dir, topo, workers = cluster
    from cake_tpu.models.llama.chat import Message

    def run_two_turns(prefix_cache):
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
        )
        gen = LlamaGenerator(
            cfg,
            step,
            ByteTokenizer(),
            SamplingConfig(temperature=0.0, repeat_penalty=1.0),
            prefix_cache=prefix_cache,
        )
        try:
            user1 = Message.user("distributed prefix reuse probe")
            gen.add_message(user1)
            gen.generate(6)
            reply = ByteTokenizer().decode(
                [t for t in gen.generated_token_ids if t not in cfg.eos_token_ids]
            )
            gen.reset()
            for m in (user1, Message.assistant(reply), Message.user("turn two")):
                gen.add_message(m)
            gen.generate(6)
            return list(gen.generated_token_ids), gen.last_prefill_tokens
        finally:
            step.close()

    got, prefilled = run_two_turns(True)
    want, full = run_two_turns(False)
    assert got == want
    assert prefilled < full  # the shared prefix was not re-sent


def test_forward_frame_trace_field_is_optional():
    """Untraced frames keep the minimal header (old peers interoperate);
    a trace id rides as one extra header key."""
    x = proto.WireTensor.from_numpy(np.zeros((1, 2), np.float32))
    bare = proto.forward_frame(x, [(0, 2)], 0)
    assert "trace" not in bare.header
    traced = proto.forward_frame(x, [(0, 2)], 0, trace="req-abc")
    assert traced.header["trace"] == "req-abc"
    g = proto.decode_frame(memoryview(proto.encode_frame(traced)))
    assert g.header["trace"] == "req-abc"
    assert "trace" not in proto.tensor_frame(x).header
    assert proto.tensor_frame(x, trace="req-abc").header["trace"] == "req-abc"


def test_wire_trace_roundtrip_and_worker_op_metrics(cluster):
    """A FORWARD carrying a trace id gets it echoed in the TENSOR reply, and
    the worker records per-op telemetry attributed to its node."""
    from cake_tpu.utils import metrics

    cfg, params, model_dir, topo, workers = cluster
    c = StageClient(topo.nodes["w1"].host, "w1")
    try:
        x = proto.WireTensor.from_numpy(
            np.zeros((1, 4, cfg.hidden_size), np.float32)
        )
        proto.write_frame(
            c._sock, proto.forward_frame(x, [(0, 2)], 0, trace="req-wire")
        )
        reply = proto.read_frame(c._sock)
        assert reply.type == proto.MsgType.TENSOR
        assert reply.header["trace"] == "req-wire"
        # The worker stamps op/byte telemetry on its serving thread after
        # writing the reply, so the client can hold the TENSOR before the
        # series land — poll with a bounded deadline instead of asserting
        # the race away.
        import time as _time

        rx = metrics.registry.counter("cake_worker_bytes_total")
        deadline = _time.monotonic() + 5.0
        while True:
            ops = metrics.registry.histogram(
                "cake_worker_op_seconds"
            ).snapshot()
            if (
                len(ops) == 1
                and ops[0]["count"] == 1
                and rx.value(node="w1", direction="tx") > 0
            ) or _time.monotonic() > deadline:
                break
            _time.sleep(0.02)
        (op,) = ops
        assert op["labels"] == {"node": "w1", "kind": "chunk"}
        assert op["count"] == 1
        assert rx.value(node="w1", direction="rx") == len(x.data)
        assert rx.value(node="w1", direction="tx") > 0
    finally:
        c.close()


def test_distributed_step_records_hop_histograms(cluster):
    """The master's stage walk lands per-node cake_hop_seconds series and
    wire byte counters — per-hop attribution across the pipeline."""
    from cake_tpu.utils import metrics

    cfg, params, model_dir, topo, workers = cluster
    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
    )
    try:
        step.trace_id = "req-hops"
        greedy_ids(cfg, step, "hop telemetry probe")
        hops = {
            s["labels"]["node"]: s
            for s in metrics.registry.histogram("cake_hop_seconds").snapshot()
        }
        assert set(hops) == {"w1", "w2"}
        for s in hops.values():
            assert s["count"] > 0
            assert s["p99"] >= s["p50"] >= 0
        wire = metrics.registry.counter("cake_wire_bytes_total")
        for node in ("w1", "w2"):
            assert wire.value(node=node, direction="tx") > 0
            assert wire.value(node=node, direction="rx") > 0
    finally:
        step.close()


def test_client_handshake_and_ping(cluster):
    cfg, params, model_dir, topo, workers = cluster
    c = StageClient(topo.nodes["w1"].host, "w1")
    try:
        assert c.info.ranges == [[0, 2]]
        assert c.info.device == "cpu"
        assert c.ping() < 1000
    finally:
        c.close()


def test_worker_serves_batch2_stream(cluster):
    """A worker adapts its per-connection caches to a batch-2 master: prefill
    (pos=0, new batch dim) + a decode step must match local batch-2 compute."""
    cfg, params, model_dir, topo, workers = cluster
    from cake_tpu.models.llama.cache import init_cache
    from cake_tpu.ops.rope import rope_table

    rng = np.random.default_rng(5)
    x0 = rng.standard_normal((2, 4, cfg.hidden_size)).astype(np.float32)
    x1 = rng.standard_normal((2, 1, cfg.hidden_size)).astype(np.float32)

    # Local oracle over w1's layers (0-1) with a batch-2 cache.
    cos, sin = rope_table(cfg.head_dim, MAX_SEQ, cfg.rope_theta, cfg.rope_scaling)
    kv = init_cache(2, 2, MAX_SEQ, cfg.num_key_value_heads, cfg.head_dim, jnp.float32)
    layers01 = jax.tree.map(lambda a: a[0:2], params["layers"])
    want0, kv = M.blocks_forward(layers01, jnp.asarray(x0), kv, cos, sin, jnp.int32(0), cfg)
    want1, kv = M.blocks_forward(layers01, jnp.asarray(x1), kv, cos, sin, jnp.int32(4), cfg)

    c = StageClient(topo.nodes["w1"].host, "w1")
    try:
        got0 = c.forward(proto.WireTensor.from_numpy(x0), [(0, 2)], 0).to_numpy()
        got1 = c.forward(proto.WireTensor.from_numpy(x1), [(0, 2)], 4).to_numpy()
        np.testing.assert_allclose(got0, np.asarray(want0), atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(got1, np.asarray(want1), atol=1e-5, rtol=1e-5)
        # Mid-sequence batch change is a structured error, not a cache corruption.
        with pytest.raises(RuntimeError, match="batch changed mid-sequence"):
            c.forward(
                proto.WireTensor.from_numpy(x1[:1]), [(0, 2)], 5
            )
    finally:
        c.close()


def test_worker_error_frame_on_bad_range(cluster):
    cfg, params, model_dir, topo, workers = cluster
    c = StageClient(topo.nodes["w1"].host, "w1")
    try:
        x = proto.WireTensor.from_numpy(
            np.zeros((1, 1, cfg.hidden_size), np.float32)
        )
        with pytest.raises(RuntimeError, match="not owned"):
            c.forward(x, [(0, 5)], 0)
        # Connection survives the error (structured ERROR, not a drop).
        assert c.ping() < 1000
    finally:
        c.close()


def test_master_generate_reports_and_streams(cluster, caplog):
    cfg, params, model_dir, topo, workers = cluster
    import logging

    step = DistributedForwardStep(
        cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
    )
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    gen.add_message(Message.user("hello"))
    master = Master(gen, sample_len=5)
    tokens = []
    with caplog.at_level(logging.INFO, logger="cake_tpu.master"):
        master.generate(on_token=tokens.append)
    try:
        assert len(tokens) == 5 or tokens[-1].is_end_of_stream
        assert any("tok/s" in r.message for r in caplog.records)
    finally:
        step.close()


def test_distributed_sampled_speculative_topk1_matches_plain(cluster):
    """Sampled speculative (temperature>0) over TCP workers: with top_k=1 the
    target is a point mass, so the speculative stream must equal the plain
    sampled stream exactly — pins the master-side head acceptance path
    (runtime/master.py verify_chunk_sampled)."""
    cfg, params, model_dir, topo, workers = cluster
    from cake_tpu.models.llama.chat import Message

    def run(spec_k):
        step = DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ
        )
        gen = LlamaGenerator(
            cfg,
            step,
            ByteTokenizer(),
            SamplingConfig(temperature=0.7, top_k=1, repeat_penalty=1.0, seed=9),
            speculative_k=spec_k,
        )
        try:
            gen.add_message(Message.user("cd cd cd cd cd cd cd cd"))
            gen.generate(14)
            return list(gen.generated_token_ids)
        finally:
            step.close()

    assert run(5) == run(0)
