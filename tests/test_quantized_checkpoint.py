"""Offline checkpoint quantizer (io/quantizer.py) + quantized loading.

The contract: a quantized checkpoint loads to EXACTLY the tree
quantize_params builds in memory (bit-identical leaves), so every runtime
quantization oracle transfers to the offline path; and the quantized
checkpoint stays a drop-in directory (workers, splitter, generator.load).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.chat import Message
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.io.quantizer import quantize_checkpoint
from cake_tpu.io.safetensors_io import load_params, save_tiny_checkpoint
from cake_tpu.ops.quant import (
    Quant4Weight,
    QuantWeight,
    quantize_params,
    tree_quantization,
)

GREEDY = SamplingConfig(temperature=0.0, repeat_penalty=1.0)


def _trees_equal(a, b) -> bool:
    # jax.tree.leaves_with_path does not exist on this jax (0.4.37: the
    # jax.tree alias module predates the with_path members); the tree_util
    # spelling is the stable one across the versions this repo supports.
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = dict(jax.tree_util.tree_leaves_with_path(b))
    if len(la) != len(lb):
        return False
    return all(
        path in lb and np.array_equal(np.asarray(leaf), np.asarray(lb[path]))
        for path, leaf in la
    )


@pytest.mark.parametrize("mode", ["int8", "int4"])
def test_quantized_checkpoint_roundtrips_bitwise(tmp_path, mode):
    cfg = LlamaConfig.tiny(num_hidden_layers=2, tie_word_embeddings=False)
    params = M.init_params(cfg, jax.random.PRNGKey(80), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q", mode, dtype=jnp.float32)

    loaded = load_params(dst, cfg, jnp.float32)
    want = quantize_params(load_params(src, cfg, jnp.float32), mode)
    assert tree_quantization(loaded) == mode
    assert _trees_equal(loaded, want)
    # config carries the informational stamp
    import json

    assert json.load(open(dst / "config.json"))["cake_quantization"] == {
        "mode": mode
    }


def test_quantized_checkpoint_generation_matches_runtime_quantize(tmp_path):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(81), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q4", "int4", dtype=jnp.float32)

    def run(gen):
        gen.add_message(Message.user("offline quantized"))
        gen.generate(9)
        return list(gen.generated_token_ids)

    got = run(
        LlamaGenerator.load(
            dst, dtype=jnp.float32, max_seq_len=128, sampling=GREEDY
        )
    )
    want = run(
        LlamaGenerator.load(
            src, dtype=jnp.float32, max_seq_len=128, sampling=GREEDY,
            quantize="int4",
        )
    )
    assert got == want


def test_quantized_checkpoint_worker_range_load(tmp_path):
    """A worker loads only its block range from a quantized checkpoint —
    and serving from it matches the local quantized oracle."""
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(82), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q8", "int8", dtype=jnp.float32)

    shard = load_params(dst, cfg, jnp.float32, layer_range=(0, 2))
    assert isinstance(shard["layers"]["wq"], QuantWeight)

    topo = Topology.from_dict(
        {"w1": {"host": "placeholder", "layers": ["model.layers.0-1"]}}
    )
    w = Worker(
        "w1", dst, topo, ("127.0.0.1", 0), dtype=jnp.float32, max_seq_len=128
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        step = DistributedForwardStep(
            cfg, dst, topo, dtype=jnp.float32, max_seq_len=128
        )
        try:
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), GREEDY)
            gen.add_message(Message.user("quantized checkpoint worker"))
            gen.generate(8)
            got = list(gen.generated_token_ids)
        finally:
            step.close()
    finally:
        w.stop()

    oracle = dict(params)
    oracle["layers"] = quantize_params(params, "int8")["layers"]
    ref = LlamaGenerator(
        cfg,
        LocalForwardStep(cfg, oracle, max_seq_len=128, cache_dtype=jnp.float32),
        ByteTokenizer(),
        GREEDY,
    )
    ref.add_message(Message.user("quantized checkpoint worker"))
    ref.generate(8)
    assert got == list(ref.generated_token_ids)


def test_quantized_checkpoint_splits(tmp_path):
    """The splitter carves a quantized checkpoint exactly like a plain one
    (suffixed names keep their layer prefixes) and the bundle loads."""
    from cake_tpu.io.splitter import split_model

    cfg = LlamaConfig.tiny(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(83), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q", "int4", dtype=jnp.float32)

    topo_path = tmp_path / "topology.yml"
    topo_path.write_text(
        "w0:\n  host: h0:1\n  layers:\n    - model.layers.0-1\n"
        "w1:\n  host: h1:1\n  layers:\n    - model.layers.2-3\n"
    )
    split_model(dst, topo_path, tmp_path / "splits")
    bundle = tmp_path / "splits" / "w1-node" / "model"
    shard = load_params(bundle, cfg, jnp.float32, layer_range=(2, 4))
    want = quantize_params(load_params(src, cfg, jnp.float32), "int4")
    want_slice = jax.tree.map(lambda a: a[2:4], want["layers"])
    assert _trees_equal(shard["layers"], want_slice)


def test_phi3_source_canonicalized(tmp_path):
    """A fused-storage (Phi-3) source quantizes into standard per-projection
    names; the quantized checkpoint reloads without the fused-split path."""
    from cake_tpu.io.safetensors_io import hf_tensor_dict, write_safetensors

    cfg = LlamaConfig.tiny(num_hidden_layers=2, model_type="phi3")
    params = M.init_params(cfg, jax.random.PRNGKey(84), jnp.float32)
    src = tmp_path / "src"
    # Write a REAL fused checkpoint the way Phi-3 ships.
    import json

    src.mkdir(parents=True)
    tensors = hf_tensor_dict(params, cfg)
    fused = {}
    for i in range(2):
        q = tensors.pop(f"model.layers.{i}.self_attn.q_proj.weight")
        k = tensors.pop(f"model.layers.{i}.self_attn.k_proj.weight")
        v = tensors.pop(f"model.layers.{i}.self_attn.v_proj.weight")
        fused[f"model.layers.{i}.self_attn.qkv_proj.weight"] = (
            np.concatenate([q, k, v], axis=0)
        )
        g = tensors.pop(f"model.layers.{i}.mlp.gate_proj.weight")
        u = tensors.pop(f"model.layers.{i}.mlp.up_proj.weight")
        fused[f"model.layers.{i}.mlp.gate_up_proj.weight"] = (
            np.concatenate([g, u], axis=0)
        )
    tensors.update(fused)
    write_safetensors(src / "model.safetensors", tensors)
    with open(src / "config.json", "w") as f:
        json.dump(cfg.to_hf_dict(), f)

    dst = quantize_checkpoint(src, tmp_path / "q", "int4", dtype=jnp.float32)
    loaded = load_params(dst, cfg, jnp.float32)
    assert isinstance(loaded["layers"]["wq"], Quant4Weight)
    want = quantize_params(load_params(src, cfg, jnp.float32), "int4")
    assert _trees_equal(loaded, want)


def test_moe_mixed_mode_roundtrip(tmp_path):
    """qwen2_moe under int4: expert stacks store .q8, shared expert .q4."""
    cfg = LlamaConfig.tiny(
        num_hidden_layers=2, model_type="qwen2_moe",
        num_local_experts=4, num_experts_per_tok=2,
        shared_expert_intermediate_size=32,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(85), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q", "int4", dtype=jnp.float32)
    loaded = load_params(dst, cfg, jnp.float32)
    assert isinstance(loaded["layers"]["w_gate"], QuantWeight)  # experts int8
    assert isinstance(loaded["layers"]["sh_gate"], Quant4Weight)
    want = quantize_params(load_params(src, cfg, jnp.float32), "int4")
    assert _trees_equal(loaded, want)


def test_requantizing_quantized_checkpoint_fails_clearly(tmp_path):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(86), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(src, tmp_path / "q", "int8", dtype=jnp.float32)
    with pytest.raises(ValueError, match="already quantized"):
        quantize_checkpoint(dst, tmp_path / "qq", "int4", dtype=jnp.float32)
    with pytest.raises(ValueError, match="already quantized"):
        LlamaGenerator.load(
            dst, dtype=jnp.float32, max_seq_len=64, sampling=GREEDY,
            quantize="int8",
        )


def test_streaming_chunks_and_shards_match_whole_tree(tmp_path):
    """The streaming path (layer chunks through the incremental shard
    writer, uneven tail chunk, multi-file output) produces EXACTLY the
    whole-tree quantization — and leaves no tmp shards behind."""
    cfg = LlamaConfig.tiny(num_hidden_layers=5, tie_word_embeddings=False)
    params = M.init_params(cfg, jax.random.PRNGKey(87), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    dst = quantize_checkpoint(
        src, tmp_path / "q", "int8", dtype=jnp.float32,
        max_shard_bytes=64 << 10, layers_per_chunk=2,
    )
    shards = sorted(dst.glob("model-*.safetensors"))
    assert len(shards) > 1  # the shard writer actually flushed mid-stream
    assert not list(dst.glob(".model-part-*.tmp"))
    loaded = load_params(dst, cfg, jnp.float32)
    want = quantize_params(load_params(src, cfg, jnp.float32), "int8")
    assert _trees_equal(loaded, want)


def test_shard_writer_abort_and_stale_tmp_sweep(tmp_path):
    """abort() (and the context manager's exception path) deletes flushed
    tmp shards; a fresh writer sweeps stale tmp files from a died run."""
    from cake_tpu.io.safetensors_io import ShardedCheckpointWriter

    out = tmp_path / "out"
    with pytest.raises(RuntimeError, match="mid-stream"):
        with ShardedCheckpointWriter(out, max_shard_bytes=64) as w:
            w.add({"a": np.zeros((64,), np.float32)})
            w.add({"b": np.zeros((64,), np.float32)})  # forces a tmp flush
            assert list(out.glob(".model-part-*.tmp"))
            raise RuntimeError("mid-stream")
    assert not list(out.glob(".model-part-*.tmp"))
    assert not list(out.glob("model-*.safetensors"))

    # A stale tmp from a killed process is swept by the next writer.
    stale = out / ".model-part-00042.tmp"
    stale.write_bytes(b"stale")
    w = ShardedCheckpointWriter(out, max_shard_bytes=1 << 20)
    assert not stale.exists()
    w.add({"c": np.ones((4,), np.float32)})
    (path,) = w.finish()
    assert path.name == "model-00001-of-00001.safetensors"


def test_quantizer_bad_mode_writes_nothing(tmp_path):
    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(88), jnp.float32)
    src = tmp_path / "src"
    save_tiny_checkpoint(src, params, cfg)
    with pytest.raises(ValueError, match="unknown quantize mode"):
        quantize_checkpoint(src, tmp_path / "bad", "int2")
    assert not (tmp_path / "bad").exists()
