"""Generator-loop tests: sampling, chat template, tokenizer, decode loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.chat import Message, encode_dialog_to_prompt
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import (
    LlamaGenerator,
    LocalForwardStep,
    SamplingConfig,
    prefill_bucket,
)
from cake_tpu.models.llama.tokenizer import ByteTokenizer
from cake_tpu.ops.sampling import apply_repeat_penalty, sample

# ---------------------------------------------------------------- sampling


def test_sample_argmax_when_temperature_nonpositive():
    logits = jnp.array([[0.1, 3.0, -1.0, 0.5]])
    for t in (0.0, -1.0):
        got = sample(logits, jax.random.PRNGKey(0), temperature=t)
        assert int(got[0]) == 1


def test_sample_top_k_restricts_support():
    logits = jnp.array([[5.0, 4.0, -10.0, -10.0]])
    hits = set()
    for i in range(50):
        tok = sample(
            logits, jax.random.PRNGKey(i), temperature=10.0, top_k=2
        )
        hits.add(int(tok[0]))
    assert hits <= {0, 1}
    assert len(hits) == 2  # high temp: both survivors appear


def test_sample_top_p_keeps_minimal_nucleus():
    # One dominant token (p>0.9): nucleus of 0.5 = just that token.
    logits = jnp.array([[10.0, 1.0, 0.0, -1.0]])
    for i in range(20):
        tok = sample(logits, jax.random.PRNGKey(i), temperature=1.0, top_p=0.5)
        assert int(tok[0]) == 0


def test_sample_top_p_always_keeps_best_token():
    # Even with tiny p the argmax token must survive (candle semantics).
    logits = jnp.array([[1.0, 1.0, 1.0, 1.0]])
    tok = sample(logits, jax.random.PRNGKey(0), temperature=1.0, top_p=1e-9)
    assert 0 <= int(tok[0]) < 4


def test_repeat_penalty_matches_candle_formula():
    logits = jnp.array([[2.0, -2.0, 1.0, 3.0]])
    window = jnp.array([[0, 1, -1, -1]], jnp.int32)  # tokens 0 and 1 seen
    got = np.asarray(apply_repeat_penalty(logits, 2.0, window))
    np.testing.assert_allclose(got, [[1.0, -4.0, 1.0, 3.0]])


def test_repeat_penalty_one_is_noop():
    logits = jnp.array([[2.0, -2.0]])
    window = jnp.array([[0]], jnp.int32)
    assert apply_repeat_penalty(logits, 1.0, window) is logits


# ---------------------------------------------------------------- chat + tokenizer


def test_chat_template_matches_reference_layout():
    msgs = [Message.system("You are helpful."), Message.user("Hi  ")]
    prompt = encode_dialog_to_prompt(msgs)
    assert prompt == (
        "<|begin_of_text|>"
        "<|start_header_id|>system<|end_header_id|>\n\nYou are helpful.<|eot_id|>"
        "<|start_header_id|>user<|end_header_id|>\n\nHi<|eot_id|>"
        "<|start_header_id|>assistant<|end_header_id|>\n\n"
    )


def test_byte_tokenizer_roundtrip_with_specials():
    tok = ByteTokenizer()
    text = "<|begin_of_text|>héllo<|eot_id|>"
    ids = tok.encode(text)
    assert ids[0] == 256 and ids[-1] == 259
    assert tok.decode(ids) == text


def test_byte_tokenizer_ids_fit_tiny_vocab():
    tok = ByteTokenizer()
    cfg = LlamaConfig.tiny()
    ids = tok.encode(encode_dialog_to_prompt([Message.user("test")]))
    assert max(ids) < cfg.vocab_size
    assert cfg.bos_token_id == 256
    assert 259 in cfg.eos_token_ids


def test_prefill_bucket():
    assert prefill_bucket(5, 256) == 16
    assert prefill_bucket(16, 256) == 16
    assert prefill_bucket(17, 256) == 32
    assert prefill_bucket(300, 256) == 256


# ---------------------------------------------------------------- generator loop


class ScriptedStep:
    """Fake ForwardStep: always puts all mass on a scripted token sequence."""

    max_seq_len = 64

    def __init__(self, script, vocab=512):
        self.script = list(script)
        self.vocab = vocab
        self.calls = []
        self.resets = 0

    def reset(self):
        self.resets += 1
        self.i = 0

    def __call__(self, tokens, pos, seq_len):
        self.calls.append((tokens.shape, pos, seq_len))
        logits = np.full((1, self.vocab), -100.0, np.float32)
        logits[0, self.script[self.i]] = 100.0
        self.i += 1
        return logits


def make_scripted_generator(script, **sampling):
    cfg = LlamaConfig.tiny()
    step = ScriptedStep(script)
    gen = LlamaGenerator(
        cfg,
        step,
        ByteTokenizer(),
        SamplingConfig(temperature=0.0, repeat_penalty=1.0, **sampling),
    )
    return gen, step


def test_generator_prefill_then_decode_positions():
    gen, step = make_scripted_generator([ord("H"), ord("i"), 259])
    gen.add_message(Message.user("hello"))
    text = gen.generate(10)
    assert text == "Hi"
    # Call 1: padded prefill at pos 0; calls 2..: single-token decode.
    (s0, p0, l0), (s1, p1, l1), (s2, p2, l2) = step.calls
    assert p0 == 0 and s0[1] >= l0 > 1
    assert s1 == (1, 1) and l1 == 1 and p1 == l0
    assert s2 == (1, 1) and p2 == l0 + 1


def test_generator_eos_stops_stream():
    gen, step = make_scripted_generator([ord("A"), 260, ord("B")])
    gen.add_message(Message.user("x"))
    text = gen.generate(10)
    assert text == "A"
    assert gen.generated_count == 2  # 'A' + eos
    assert len(step.calls) == 2


def test_generator_reset_clears_state():
    gen, step = make_scripted_generator([ord("A"), 259, ord("B"), 259])
    gen.add_message(Message.user("x"))
    gen.generate(5)
    gen.reset()
    assert gen.messages == [] and gen.generated_count == 0
    assert step.resets == 2  # init + explicit


def test_generator_incremental_utf8_decode():
    # 'é' is two bytes; the first alone must not emit a replacement char.
    e_bytes = "é".encode("utf-8")
    gen, _ = make_scripted_generator([e_bytes[0], e_bytes[1], 259])
    gen.add_message(Message.user("x"))
    toks = []
    gen.generate(5, on_token=toks.append)
    assert "".join(t.text for t in toks) == "é"
    assert toks[0].text == ""  # partial byte held back


@pytest.fixture(scope="module")
def tiny_local():
    cfg = LlamaConfig.tiny()
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    step = LocalForwardStep(cfg, params, max_seq_len=128, cache_dtype=jnp.float32)
    return cfg, params, step


def test_end_to_end_greedy_matches_uncached_oracle(tiny_local):
    """Greedy decode through the full generator must match token-by-token argmax
    of the uncached forward — the reference's implicit single-host oracle
    (SURVEY.md §4)."""
    cfg, params, step = tiny_local
    gen = LlamaGenerator(
        cfg, step, ByteTokenizer(), SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    )
    gen.add_message(Message.user("once upon a time"))
    gen.generate(8)
    ids = gen._tokens
    assert len(ids) > gen._n_prompt

    # Oracle: for each generated position, argmax of full uncached forward.
    for t in range(gen._n_prompt, len(ids)):
        kv = init_cache(
            cfg.num_hidden_layers, 1, 128, cfg.num_key_value_heads, cfg.head_dim,
            jnp.float32,
        )
        logits, _ = M.forward(
            params,
            jnp.asarray([ids[:t]], jnp.int32),
            kv,
            jnp.int32(0),
            jnp.int32(t),
            cfg,
        )
        assert int(jnp.argmax(logits[0])) == ids[t]


def test_seeded_sampling_is_reproducible(tiny_local):
    cfg, params, step = tiny_local
    outs = []
    for _ in range(2):
        gen = LlamaGenerator(
            cfg, step, ByteTokenizer(),
            SamplingConfig(temperature=0.9, top_p=0.95, seed=42),
        )
        gen.add_message(Message.user("hello world"))
        outs.append(gen.generate(6))
    assert outs[0] == outs[1]


def test_generation_config_eos_merge(tmp_path):
    """generation_config.json's stop tokens union into the config: real
    Llama-3-Instruct checkpoints list <|eot_id|> only there, and a loader
    reading config.json alone would generate straight through turn ends
    (the reference inherits exactly that, config.rs:13-26)."""
    import json

    from cake_tpu.models.llama.config import LlamaConfig

    cfg = LlamaConfig.tiny(num_hidden_layers=1)
    d = tmp_path / "m"
    d.mkdir()
    hf = cfg.to_hf_dict()
    hf["eos_token_id"] = 128001
    (d / "config.json").write_text(json.dumps(hf))
    (d / "generation_config.json").write_text(
        json.dumps({"eos_token_id": [128001, 128008, 128009]})
    )
    loaded = LlamaConfig.from_model_dir(d)
    assert loaded.eos_token_ids == (128001, 128008, 128009)
    # Absent generation_config: config.json alone decides.
    (d / "generation_config.json").unlink()
    assert LlamaConfig.from_model_dir(d).eos_token_ids == (128001,)
