"""Mixtral sparse-MoE family: HF parity, expert parallelism, quantization.

Expert parallelism is absent from the reference (SURVEY.md §2.7 row "EP:
none — dense Llama only"); this is a beyond-parity family. The oracle
hierarchy mirrors the other families: HF transformers (external truth) for
numerics, then sharded == local for every execution backend.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from cake_tpu.io.safetensors_io import load_params, save_tiny_checkpoint
from cake_tpu.models.llama import model as M
from cake_tpu.models.llama.cache import init_cache
from cake_tpu.models.llama.config import LlamaConfig
from cake_tpu.models.llama.generator import LocalForwardStep
from cake_tpu.parallel.tensor import TensorParallelRunner, validate_tp

MAX_SEQ = 64


def make_mixtral_checkpoint(tmp_path, seed=0, n_experts=4, top_k=2):
    cfg = transformers.MixtralConfig(
        hidden_size=64,
        intermediate_size=96,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=n_experts,
        num_experts_per_tok=top_k,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        sliding_window=None,
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.MixtralForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def hf_greedy(model, prompt_ids, n_steps):
    ids = torch.tensor([prompt_ids], dtype=torch.long)
    out = []
    with torch.no_grad():
        for _ in range(n_steps):
            logits = model(ids).logits[0, -1]
            nxt = int(torch.argmax(logits))
            out.append(nxt)
            ids = torch.cat([ids, torch.tensor([[nxt]])], dim=1)
    return out


def ours_greedy(model_dir, prompt_ids, n_steps):
    cfg = LlamaConfig.from_model_dir(model_dir)
    params = load_params(model_dir, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    fwd = jax.jit(M.forward, static_argnames=("config",), donate_argnames=("kv",))
    logits, kv = fwd(
        params, jnp.asarray([prompt_ids], jnp.int32), kv, jnp.int32(0),
        jnp.int32(len(prompt_ids)), cfg,
    )
    out = []
    pos = len(prompt_ids)
    for _ in range(n_steps):
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        logits, kv = fwd(
            params, jnp.asarray([[nxt]], jnp.int32), kv, jnp.int32(pos),
            jnp.int32(1), cfg,
        )
        pos += 1
    return out


def test_mixtral_config_parses(tmp_path):
    make_mixtral_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "mixtral"
    assert cfg.num_local_experts == 4
    assert cfg.num_experts_per_tok == 2


def test_mixtral_greedy_tokens_match_transformers(tmp_path):
    hf_model = make_mixtral_checkpoint(tmp_path, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_mixtral_prefill_logits_match_transformers(tmp_path):
    """Full-position logits (routing is position-dependent — every token must
    route identically to HF, not just the argmax survive)."""
    hf_model = make_mixtral_checkpoint(tmp_path, seed=2)
    prompt = [256, 11, 205, 499, 3, 3, 64, 90]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )


def test_mixtral_top1_routing(tmp_path):
    """num_experts_per_tok=1: the degenerate top-1 renormalization (weight
    exactly 1.0 on one expert)."""
    hf_model = make_mixtral_checkpoint(tmp_path, seed=3, top_k=1)
    prompt = [256, 5, 77, 140, 9]
    assert ours_greedy(tmp_path, prompt, 10) == hf_greedy(hf_model, prompt, 10)


def _moe_cfg(**kw):
    kw.setdefault("model_type", "mixtral")
    kw.setdefault("num_local_experts", 4)
    kw.setdefault("num_experts_per_tok", 2)
    kw.setdefault("intermediate_size", 96)
    return LlamaConfig.tiny(**kw)


def _drive(step, tokens):
    n = tokens.shape[1]
    outs = [step(tokens, 0, n)]
    pos = n
    for _ in range(3):
        nxt = np.argmax(outs[-1], -1).astype(np.int32)[:, None]
        outs.append(step(nxt, pos, 1))
        pos += 1
    return np.stack(outs)


@pytest.mark.parametrize("tp", [2, 4])
def test_moe_expert_parallel_matches_local(tp):
    """Experts sharded over the tp axis == single-device oracle."""
    cfg = _moe_cfg(num_attention_heads=8, num_key_value_heads=4)
    params = M.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tokens = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 10)
    ).astype(np.int32)
    local = LocalForwardStep(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    ep = TensorParallelRunner(
        cfg, params, tp=tp, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        _drive(ep, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )


def test_moe_tp_requires_divisible_experts():
    with pytest.raises(ValueError, match="num_local_experts"):
        validate_tp(_moe_cfg(num_local_experts=5), 2)


def test_moe_checkpoint_roundtrip(tmp_path):
    """save_tiny_checkpoint -> load_params preserves MoE numerics exactly."""
    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(2), jnp.float32)
    save_tiny_checkpoint(tmp_path, params, cfg)
    loaded = load_params(tmp_path, cfg, jnp.float32)
    for k in ("router", "w_gate", "w_up", "w_down"):
        np.testing.assert_array_equal(
            np.asarray(loaded["layers"][k]), np.asarray(params["layers"][k]), k
        )


def test_moe_int8_quantization_bounded_drift(tmp_path):
    """int8 expert weights run through the quant-aware einsum path; logits
    stay close to full precision (loose bound: rounding only)."""
    from cake_tpu.ops.quant import quantize_params

    cfg = _moe_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    qparams = quantize_params(params)
    tokens = jnp.asarray([[256, 4, 9, 33]], jnp.int32)

    def run(p):
        kv = init_cache(
            cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
            cfg.head_dim, jnp.float32,
        )
        logits, _ = M.forward(p, tokens, kv, jnp.int32(0), jnp.int32(4), cfg)
        return np.asarray(logits)

    full, quant = run(params), run(qparams)
    assert np.isfinite(quant).all()
    # Same top token and small absolute drift for a tiny random model.
    assert int(full.argmax()) == int(quant.argmax())
    assert np.abs(full - quant).max() < 0.3


def test_moe_worker_layer_range_load(tmp_path):
    """A worker loading only its block range gets stacked MoE weights for
    exactly those layers (worker.rs:95-108 analogue)."""
    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(4), jnp.float32)
    save_tiny_checkpoint(tmp_path, params, cfg)
    shard = load_params(tmp_path, cfg, jnp.float32, layer_range=(1, 3))
    assert shard["layers"]["w_gate"].shape == (2, 4, 64, 96)
    np.testing.assert_array_equal(
        np.asarray(shard["layers"]["router"]),
        np.asarray(params["layers"]["router"][1:3]),
    )


def test_moe_pipeline_matches_local():
    """MoE layers sharded across ragged pipeline stages == local oracle
    (zero-padded experts inert, router replicated per stage)."""
    from cake_tpu.parallel.pipeline import PipelineRunner

    cfg = _moe_cfg(num_hidden_layers=5)
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    tokens = np.random.default_rng(2).integers(
        0, cfg.vocab_size, (1, 9)
    ).astype(np.int32)
    local = LocalForwardStep(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    pipe = PipelineRunner(
        cfg, params, [(0, 2), (2, 5)], max_seq_len=MAX_SEQ,
        cache_dtype=jnp.float32,
    )
    np.testing.assert_allclose(
        _drive(pipe, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )


def test_moe_generator_end_to_end(tmp_path):
    """LlamaGenerator.load over a Mixtral checkpoint dir: template dispatch
    ([INST]) + greedy decode + reset determinism."""
    from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig
    from cake_tpu.models.llama.chat import Message

    cfg = _moe_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(6), jnp.float32)
    save_tiny_checkpoint(tmp_path, params, cfg)
    gen = LlamaGenerator.load(
        tmp_path, dtype=jnp.float32, max_seq_len=MAX_SEQ,
        sampling=SamplingConfig(temperature=0.0, repeat_penalty=1.0),
    )
    assert gen.config.num_local_experts == 4
    gen.add_message(Message.user("hello moe"))
    gen.generate(6)
    ids = list(gen.generated_token_ids)
    assert gen._prompt_cache[0].startswith("<s>[INST] hello moe [/INST]")
    gen.reset()
    gen.add_message(Message.user("hello moe"))
    gen.generate(6)
    assert list(gen.generated_token_ids) == ids


def test_moe_sequence_parallel_matches_local():
    """Ring-attention SP serving over a MoE model == local oracle (experts
    replicated over sp; MLP type is orthogonal to the sequence sharding)."""
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.generator import LlamaGenerator, SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.sequence import SequenceParallelRunner

    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    cfg = _moe_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    prompt = "moe over sequence shards needs a longish prompt"

    def run(step):
        gen = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
        gen.add_message(Message.user(prompt))
        gen.generate(8)
        return gen.generated_token_ids

    ref = run(LocalForwardStep(cfg, params, max_seq_len=256,
                               cache_dtype=jnp.float32))
    got = run(SequenceParallelRunner(cfg, params, sp=4, max_seq_len=256,
                                     cache_dtype=jnp.float32))
    assert got == ref


def test_moe_tcp_workers_match_local(tmp_path):
    """TCP workers serving MoE layer ranges == local oracle (worker-side
    blocks_forward + range loading carry the router/expert weights)."""
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.generator import (
        LlamaGenerator,
        SamplingConfig,
    )
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.master import DistributedForwardStep
    from cake_tpu.runtime.worker import Worker

    greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
    cfg = _moe_cfg(num_hidden_layers=4)
    params = M.init_params(cfg, jax.random.PRNGKey(8), jnp.float32)
    model_dir = tmp_path / "model"
    save_tiny_checkpoint(model_dir, params, cfg)
    topo = Topology.from_dict(
        {"w1": {"host": "x", "layers": ["model.layers.1-2"]}}
    )
    w = Worker(
        "w1", model_dir, topo, ("127.0.0.1", 0), dtype=jnp.float32,
        max_seq_len=MAX_SEQ,
    )
    w.start()
    topo.nodes["w1"].host = f"127.0.0.1:{w.address[1]}"
    try:
        def run(step):
            gen = LlamaGenerator(cfg, step, ByteTokenizer(), greedy)
            gen.add_message(Message.user("moe over tcp"))
            gen.generate(6)
            return gen.generated_token_ids

        ref = run(LocalForwardStep(cfg, params, max_seq_len=MAX_SEQ,
                                   cache_dtype=jnp.float32))
        got = run(DistributedForwardStep(
            cfg, model_dir, topo, dtype=jnp.float32, max_seq_len=MAX_SEQ,
        ))
        assert got == ref
    finally:
        w.stop()


# ----------------------------------------------------------------- Qwen2-MoE


def make_qwen2_moe_checkpoint(tmp_path, seed=0, norm_topk=False, top_k=2):
    cfg = transformers.Qwen2MoeConfig(
        hidden_size=64,
        intermediate_size=96,
        moe_intermediate_size=80,
        shared_expert_intermediate_size=112,
        vocab_size=512,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_experts=4,
        num_experts_per_tok=top_k,
        norm_topk_prob=norm_topk,
        rope_theta=10000.0,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        bos_token_id=256,
        eos_token_id=260,
        use_sliding_window=False,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        attn_implementation="eager",
    )
    torch.manual_seed(seed)
    model = transformers.Qwen2MoeForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)
    return model


def test_qwen2_moe_config_parses(tmp_path):
    make_qwen2_moe_checkpoint(tmp_path)
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.model_type == "qwen2_moe"
    assert cfg.num_local_experts == 4
    assert cfg.norm_topk_prob is False
    assert cfg.attention_bias  # qwen2-family QKV bias
    assert cfg.moe_intermediate_size == 80
    assert cfg.shared_expert_intermediate_size == 112
    assert cfg.dialog_template == "qwen2_moe"  # -> ChatML encoder


def test_qwen2_moe_greedy_tokens_match_transformers(tmp_path):
    """Shared expert + sigmoid gate + unnormalized top-k routing + QKV bias,
    all pinned against transformers at once."""
    hf_model = make_qwen2_moe_checkpoint(tmp_path, seed=1)
    prompt = [256, 7, 301, 42, 42, 9, 123, 77]
    assert ours_greedy(tmp_path, prompt, 16) == hf_greedy(hf_model, prompt, 16)


def test_qwen2_moe_prefill_logits_match_transformers(tmp_path):
    hf_model = make_qwen2_moe_checkpoint(tmp_path, seed=2, norm_topk=True)
    prompt = [256, 11, 205, 499, 3, 3, 64, 90]
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor([prompt])).logits[0].numpy()
    cfg = LlamaConfig.from_model_dir(tmp_path)
    assert cfg.norm_topk_prob is True
    params = load_params(tmp_path, cfg, jnp.float32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    logits, _ = M.forward_all_logits(
        params, jnp.asarray([prompt], jnp.int32), kv, jnp.int32(0), cfg,
        cached_prefill=False,
    )
    np.testing.assert_allclose(
        np.asarray(logits[0]), hf_logits, atol=3e-4, rtol=3e-4
    )


def test_qwen2_moe_rejects_mixed_dense_sparse(tmp_path):
    import json

    make_qwen2_moe_checkpoint(tmp_path)
    cfg_path = tmp_path / "config.json"
    d = json.loads(cfg_path.read_text())
    d["decoder_sparse_step"] = 2
    cfg_path.write_text(json.dumps(d))
    with pytest.raises(ValueError, match="decoder_sparse_step"):
        LlamaConfig.from_model_dir(tmp_path)


def _qwen2_moe_cfg(**kw):
    kw.setdefault("model_type", "qwen2_moe")
    kw.setdefault("num_local_experts", 4)
    kw.setdefault("num_experts_per_tok", 2)
    kw.setdefault("norm_topk_prob", False)
    kw.setdefault("attention_bias", True)
    kw.setdefault("moe_intermediate_size", 80)
    kw.setdefault("shared_expert_intermediate_size", 112)
    return LlamaConfig.tiny(**kw)


def test_qwen2_moe_expert_parallel_matches_local():
    """Experts AND the shared expert shard over tp (experts on the expert
    axis, shared on its intermediate) == single-device oracle."""
    cfg = _qwen2_moe_cfg(num_attention_heads=8, num_key_value_heads=4)
    params = M.init_params(cfg, jax.random.PRNGKey(10), jnp.float32)
    tokens = np.random.default_rng(5).integers(
        0, cfg.vocab_size, (1, 10)
    ).astype(np.int32)
    local = LocalForwardStep(
        cfg, params, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    ep = TensorParallelRunner(
        cfg, params, tp=2, max_seq_len=MAX_SEQ, cache_dtype=jnp.float32
    )
    np.testing.assert_allclose(
        _drive(ep, tokens), _drive(local, tokens), atol=2e-4, rtol=2e-4
    )


def test_qwen2_moe_checkpoint_roundtrip_and_quant(tmp_path):
    cfg = _qwen2_moe_cfg(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(11), jnp.float32)
    save_tiny_checkpoint(tmp_path, params, cfg)
    loaded = load_params(tmp_path, cfg, jnp.float32)
    for k in ("router", "w_gate", "sh_gate", "sh_down", "se_gate", "bq"):
        np.testing.assert_array_equal(
            np.asarray(loaded["layers"][k]), np.asarray(params["layers"][k]), k
        )

    from cake_tpu.ops.quant import quantize_params

    qparams = quantize_params(loaded)
    tokens = jnp.asarray([[256, 4, 9, 33]], jnp.int32)
    kv = init_cache(
        cfg.num_hidden_layers, 1, MAX_SEQ, cfg.num_key_value_heads,
        cfg.head_dim, jnp.float32,
    )
    logits, _ = M.forward(
        qparams, tokens, kv, jnp.int32(0), jnp.int32(4), cfg
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_qwen2_moe_windowed_roundtrip_and_topk_default():
    """Review findings: the window must survive to_hf/from_hf, and an
    omitted num_experts_per_tok must follow HF's per-family default (4)."""
    import dataclasses

    cfg = _qwen2_moe_cfg(sliding_window=16)
    back = LlamaConfig.from_hf_dict(cfg.to_hf_dict())
    assert back.sliding_window == 16

    d = _qwen2_moe_cfg().to_hf_dict()
    del d["num_experts_per_tok"]
    assert LlamaConfig.from_hf_dict(d).num_experts_per_tok == 4
    d2 = dataclasses.replace(
        LlamaConfig.tiny(model_type="mixtral", num_local_experts=4)
    ).to_hf_dict()
    del d2["num_experts_per_tok"]
    assert LlamaConfig.from_hf_dict(d2).num_experts_per_tok == 2


@pytest.mark.parametrize("norm_topk,quantized", [
    (True, False), (False, False), (True, True),
])
def test_moe_grouped_dispatch_matches_dense(norm_topk, quantized):
    """The sorted/grouped ragged_dot dispatch (prefill chunks) must reproduce
    the dense masked-combine path bit-near-exactly for both weight
    representations and both renorm conventions. The transformers
    cross-checks above exercise the grouped path end-to-end (prefill chunks
    are >= GROUPED_MIN_TOKENS); this pins the two internal paths against
    each other directly."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.ops.quant import quantize_weight

    rng = np.random.default_rng(11)
    b, t, h, inter, e, k = 2, 16, 32, 64, 8, 2
    x = jnp.asarray(rng.standard_normal((b, t, h)), jnp.float32)
    router = jnp.asarray(rng.standard_normal((h, e)) * 0.1, jnp.float32)
    wg = jnp.asarray(rng.standard_normal((e, h, inter)) * h**-0.5, jnp.float32)
    wu = jnp.asarray(rng.standard_normal((e, h, inter)) * h**-0.5, jnp.float32)
    wd = jnp.asarray(rng.standard_normal((e, inter, h)) * inter**-0.5, jnp.float32)
    if quantized:
        wg, wu, wd = quantize_weight(wg), quantize_weight(wu), quantize_weight(wd)

    old = moe.GROUPED_MIN_TOKENS
    try:
        moe.GROUPED_MIN_TOKENS = 10**9
        dense = moe.moe_swiglu(x, router, wg, wu, wd, k, norm_topk=norm_topk)
        moe.GROUPED_MIN_TOKENS = 0
        grouped = moe.moe_swiglu(x, router, wg, wu, wd, k, norm_topk=norm_topk)
    finally:
        moe.GROUPED_MIN_TOKENS = old
    np.testing.assert_allclose(
        np.asarray(grouped), np.asarray(dense), atol=2e-6, rtol=2e-6
    )


# ---------------------------------------------------------- expert capacity


def test_capacity_dispatch_flops_scale_with_capacity():
    """The point of the capacity path: tp-sharded prefill MLP FLOPs ∝ the
    per-expert budget (~ k/tp of the dense all-experts combine), measured on
    the compiled per-device program."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.parallel.tensor import TP_AXIS, checked_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = _moe_cfg(
        num_local_experts=8, num_experts_per_tok=2, intermediate_size=256,
        hidden_size=128,
    )
    params = M.init_params(cfg, jax.random.PRNGKey(5), jnp.float32)
    lp = params["layers"]
    mesh = Mesh(np.array(jax.devices()[:2]), (TP_AXIS,))
    x = jnp.ones((1, 64, cfg.hidden_size), jnp.float32)

    def flops_with(min_tokens):
        old = moe.GROUPED_MIN_TOKENS
        moe.GROUPED_MIN_TOKENS = min_tokens
        try:
            def body(x, router, wg, wu, wd):
                return moe.moe_swiglu(
                    x, router, wg, wu, wd, cfg.num_experts_per_tok,
                    tp_axis=TP_AXIS,
                )

            mapped = checked_shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), P(), P(TP_AXIS), P(TP_AXIS), P(TP_AXIS)),
                out_specs=P(),
            )
            lowered = jax.jit(mapped).lower(
                x, lp["router"][0], lp["w_gate"][0], lp["w_up"][0],
                lp["w_down"][0],
            )
            a = lowered.compile().cost_analysis()
            if isinstance(a, list):
                a = a[0]
            return float(a["flops"])
        finally:
            moe.GROUPED_MIN_TOKENS = old

    dense = flops_with(10**9)  # force the dense all-experts combine
    capacity = flops_with(8)  # the capacity path (64 tokens >= 8)
    # Ideal MLP ratio = cf*k/E = 2*2/8 = 0.5; routing/scatter overhead eats
    # some of it — require a solid margin.
    assert capacity < 0.7 * dense, (capacity, dense)


def test_capacity_dispatch_drop_free_parity():
    """With the budget at or above the worst-case per-expert load (cap >= n,
    since each token selects an expert at most once), the capacity path must
    match the dense tp combine to reduction-order tolerance."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.parallel.tensor import TP_AXIS, checked_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(6), jnp.float32)
    lp = params["layers"]
    mesh = Mesh(np.array(jax.devices()[:2]), (TP_AXIS,))
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 12, cfg.hidden_size))

    def run(min_tokens):
        old = moe.GROUPED_MIN_TOKENS
        moe.GROUPED_MIN_TOKENS = min_tokens
        try:
            def body(x, router, wg, wu, wd):
                part = moe.moe_swiglu(
                    x, router, wg, wu, wd, cfg.num_experts_per_tok,
                    tp_axis=TP_AXIS,
                )
                return jax.lax.psum(part, TP_AXIS)

            mapped = checked_shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), P(), P(TP_AXIS), P(TP_AXIS), P(TP_AXIS)),
                out_specs=P(),
            )
            return np.asarray(
                jax.jit(mapped)(
                    x, lp["router"][0], lp["w_gate"][0], lp["w_up"][0],
                    lp["w_down"][0],
                )
            )
        finally:
            moe.GROUPED_MIN_TOKENS = old

    # n = 24 tokens, E = 4, k = 2 -> cap = ceil(2*48/4) = 24 = n: drop-free
    # by construction (a token contributes at most one row per expert).
    np.testing.assert_allclose(run(8), run(10**9), atol=2e-5, rtol=2e-5)


def test_capacity_dispatch_overflow_drops_are_bounded():
    """Forcing a tiny budget (EP_CAPACITY_FACTOR < 1) must stay finite and
    close to the dense result in norm — the documented routing-drop trade."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.parallel.tensor import TP_AXIS, checked_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(8), jnp.float32)
    lp = params["layers"]
    mesh = Mesh(np.array(jax.devices()[:2]), (TP_AXIS,))
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 32, cfg.hidden_size))

    def run_once():
        # Built FRESH per run: EP_CAPACITY_FACTOR is read at trace time, and
        # jax caches traces on the underlying callable.
        def body(x, router, wg, wu, wd):
            part = moe.moe_swiglu(
                x, router, wg, wu, wd, cfg.num_experts_per_tok,
                tp_axis=TP_AXIS,
            )
            return jax.lax.psum(part, TP_AXIS)

        mapped = checked_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(TP_AXIS), P(TP_AXIS), P(TP_AXIS)),
            out_specs=P(),
        )
        return np.asarray(
            jax.jit(mapped)(
                x, lp["router"][0], lp["w_gate"][0], lp["w_up"][0],
                lp["w_down"][0],
            )
        )

    full = run_once()
    old = moe.EP_CAPACITY_FACTOR
    moe.EP_CAPACITY_FACTOR = 0.5
    try:
        tight = run_once()
    finally:
        moe.EP_CAPACITY_FACTOR = old
    assert np.isfinite(tight).all()
    # Drops remove SOME contributions; the outputs stay in the same regime.
    rel = np.linalg.norm(tight - full) / np.linalg.norm(full)
    assert 0.0 < rel < 1.0, rel


def test_capacity_dispatch_pads_do_not_consume_capacity():
    """Left-pad slots (sentinel-position rows in lockstep batches) must not
    eat the expert budget ahead of real tokens: with the valid mask, the
    capacity output at real positions matches the dense combine; without it,
    a pad pile-up evicts real contributions."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.parallel.tensor import TP_AXIS, checked_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(10), jnp.float32)
    lp = params["layers"]
    mesh = Mesh(np.array(jax.devices()[:2]), (TP_AXIS,))
    h = cfg.hidden_size
    # 8 identical "pad" vectors (they all route to the same top-2 experts)
    # followed by 8 real tokens; budget cf=1.0 -> cap = 8 per expert, so the
    # pads alone can fill their experts' budgets.
    pad_vec = jnp.ones((1, 1, h)) * 0.7
    real = jax.random.normal(jax.random.PRNGKey(11), (1, 8, h))
    x = jnp.concatenate([jnp.tile(pad_vec, (1, 8, 1)), real], axis=1)
    valid = jnp.asarray([[False] * 8 + [True] * 8])

    def run(use_mask, min_tokens):
        old_mt, old_cf = moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR
        moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR = min_tokens, 1.0
        try:
            def body(x, router, wg, wu, wd):
                part = moe.moe_swiglu(
                    x, router, wg, wu, wd, cfg.num_experts_per_tok,
                    tp_axis=TP_AXIS, valid=valid if use_mask else None,
                )
                return jax.lax.psum(part, TP_AXIS)

            mapped = checked_shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(), P(TP_AXIS), P(TP_AXIS), P(TP_AXIS)),
                out_specs=P(),
            )
            return np.asarray(
                jax.jit(mapped)(
                    x, lp["router"][0], lp["w_gate"][0], lp["w_up"][0],
                    lp["w_down"][0],
                )
            )[0, 8:]  # real positions only
        finally:
            moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR = old_mt, old_cf

    dense = run(False, 10**9)  # dense combine = the drop-free oracle
    masked = run(True, 8)
    np.testing.assert_allclose(masked, dense, atol=2e-5, rtol=2e-5)


def test_dispatch_dense_forces_drop_free_even_with_min_tokens_zero():
    """dispatch="dense" (speculative verify chunks) must bypass BOTH grouped
    branches even under the documented GROUPED_MIN_TOKENS=0 forcing knob —
    output equals the dense combine exactly, never the droppy capacity path."""
    import cake_tpu.ops.moe as moe
    from cake_tpu.parallel.tensor import TP_AXIS, checked_shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    cfg = _moe_cfg()
    params = M.init_params(cfg, jax.random.PRNGKey(12), jnp.float32)
    lp = params["layers"]
    mesh = Mesh(np.array(jax.devices()[:2]), (TP_AXIS,))
    x = jax.random.normal(jax.random.PRNGKey(13), (1, 16, cfg.hidden_size))

    def run(dispatch, min_tokens, cf):
        old_mt, old_cf = moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR
        moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR = min_tokens, cf
        try:
            def body(x, router, wg, wu, wd):
                part = moe.moe_swiglu(
                    x, router, wg, wu, wd, cfg.num_experts_per_tok,
                    tp_axis=TP_AXIS, dispatch=dispatch,
                )
                return jax.lax.psum(part, TP_AXIS)

            mapped = checked_shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(), P(TP_AXIS), P(TP_AXIS), P(TP_AXIS)),
                out_specs=P(),
            )
            return np.asarray(
                jax.jit(mapped)(
                    x, lp["router"][0], lp["w_gate"][0], lp["w_up"][0],
                    lp["w_down"][0],
                )
            )
        finally:
            moe.GROUPED_MIN_TOKENS, moe.EP_CAPACITY_FACTOR = old_mt, old_cf

    oracle = run("auto", 10**9, 2.0)  # dense combine (width below threshold)
    # A tight capacity factor WOULD drop if the capacity path ran; "dense"
    # with GROUPED_MIN_TOKENS=0 must still match the oracle bit-for-bit.
    forced = run("dense", 0, 0.25)
    np.testing.assert_array_equal(forced, oracle)
