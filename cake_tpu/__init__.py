"""cake-tpu: a TPU-native distributed pipeline-parallel LLM inference framework.

Built from scratch in JAX/XLA (jit, shard_map, Pallas) with the capabilities of the
reference framework `cake` (distributed layer-sharded Llama-3 inference over a YAML
topology, master/worker CLI, OpenAI-compatible API, model splitter) — redesigned
TPU-first. See SURVEY.md at the repo root for the full capability map.
"""

__version__ = "0.1.0"
