"""Trace smoke gate: serve 2 concurrent streams, validate the timeline export.

``make trace-smoke`` (wired into ``make verify`` after lint) runs this on the
CPU backend with a tiny random-weight model: two concurrent requests through
the real BatchEngine with ``--trace-jsonl`` streaming, then the JSONL is read
back, rendered as Chrome trace-event JSON, and pushed through the schema
checker (cake_tpu/obs/timeline.validate_export). Exit is nonzero on malformed
output — a torn JSONL line, an unpaired B/E, a flow arrow with no start —
so the export contract that Perfetto depends on gates like a test.

Usage: ``python -m cake_tpu.obs.trace_smoke [--jsonl PATH] [--out PATH]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="cake-tpu trace-smoke")
    p.add_argument(
        "--jsonl", default=None,
        help="where to stream timeline events (default: a temp file)",
    )
    p.add_argument(
        "--out", default=None,
        help="also write the rendered Chrome trace JSON here",
    )
    p.add_argument("--tokens", type=int, default=12)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.obs.timeline import (
        export_events,
        load_jsonl,
        timeline,
        validate_export,
    )
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    jsonl = args.jsonl or os.path.join(
        tempfile.mkdtemp(prefix="cake-trace-smoke-"), "trace.jsonl"
    )
    timeline.attach_jsonl(jsonl)

    cfg = LlamaConfig.tiny(num_hidden_layers=2)
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    engine = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=128, cache_dtype=jnp.float32,
        serve=ServeConfig(
            max_batch=4, decode_chunk_size=4, admission_window=0.02,
            kv_mode="paged", page_size=16,
        ),
    )
    engine.start()
    try:
        greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
        handles = [
            engine.submit([Message.user(prompt)], args.tokens, greedy)
            for prompt in ("smoke stream one", "a second concurrent stream")
        ]
        counts = [sum(1 for _ in h.tokens()) for h in handles]
    finally:
        engine.stop()
        timeline.attach_jsonl(None)

    events = load_jsonl(jsonl)  # malformed line -> json error -> nonzero exit
    trace = export_events(events)
    problems = validate_export(trace)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace, f)
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] != "M"}
    required = {"epoch", "prefill", "decode-chunk", "request"}
    missing = required - names
    if missing:
        problems.append(f"expected span names absent: {sorted(missing)}")
    if min(counts) < 1:
        problems.append(f"a stream produced no tokens: {counts}")
    for prob in problems:
        print(f"trace-smoke: FAIL: {prob}", file=sys.stderr)
    if problems:
        return 1
    print(
        f"trace-smoke: OK — {len(events)} events, {counts} tokens/stream, "
        f"jsonl={jsonl}" + (f", trace={args.out}" if args.out else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
