"""Trace smoke gate: serve 2 concurrent streams, validate the timeline export.

``make trace-smoke`` (wired into ``make verify`` after lint) runs this on the
CPU backend with a tiny random-weight model: two concurrent requests through
the real BatchEngine with ``--trace-jsonl`` streaming, then the JSONL is read
back, rendered as Chrome trace-event JSON, and pushed through the schema
checker (cake_tpu/obs/timeline.validate_export). Exit is nonzero on malformed
output — a torn JSONL line, an unpaired B/E, a flow arrow with no start —
so the export contract that Perfetto depends on gates like a test.

Usage: ``python -m cake_tpu.obs.trace_smoke [--jsonl PATH] [--out PATH]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="cake-tpu trace-smoke")
    p.add_argument(
        "--jsonl", default=None,
        help="where to stream timeline events (default: a temp file)",
    )
    p.add_argument(
        "--out", default=None,
        help="also write the rendered Chrome trace JSON here",
    )
    p.add_argument("--tokens", type=int, default=12)
    p.add_argument(
        "--paged-pallas", action="store_true",
        help="serve through the paged Pallas kernel family (128-slot "
        "pages, attention_impl=pallas, prefix cache on) and GATE on the "
        "export showing kernel:* dispatch instants with impl=pallas — a "
        "silent fallback to the XLA gather path fails the smoke",
    )
    p.add_argument(
        "--fused-pallas", action="store_true",
        help="serve with the decode op-fusion kernels (fusion_impl="
        "all@pallas) and GATE on the export showing kernel:fused_* "
        "dispatch instants with impl=pallas — a silent fallback to the "
        "unfused path fails the smoke (mirrors --paged-pallas)",
    )
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from cake_tpu.models.llama import model as M
    from cake_tpu.models.llama.chat import Message
    from cake_tpu.models.llama.config import LlamaConfig
    from cake_tpu.models.llama.generator import SamplingConfig
    from cake_tpu.models.llama.tokenizer import ByteTokenizer
    from cake_tpu.obs.timeline import (
        export_events,
        load_jsonl,
        timeline,
        validate_export,
    )
    from cake_tpu.runtime.serving import BatchEngine, ServeConfig

    jsonl = args.jsonl or os.path.join(
        tempfile.mkdtemp(prefix="cake-trace-smoke-"), "trace.jsonl"
    )
    timeline.attach_jsonl(jsonl)

    if args.paged_pallas:
        # Kernel-path gate: 128-slot pages (the lane-tile minimum) and an
        # explicit pallas attention_impl; the prefix cache routes the warm
        # round through the cached-chunk kernel (suffix_prefill dispatch).
        cfg = LlamaConfig.tiny(num_hidden_layers=2, attention_impl="pallas")
        serve = ServeConfig(
            max_batch=2, decode_chunk_size=4, admission_window=0.02,
            kv_mode="paged", page_size=128, prefix_cache=True,
        )
        max_seq = 256
    elif args.fused_pallas:
        # Decode-fusion gate: fusion_impl=all@pallas over the dense local
        # backend (the fused kernels run interpret on CPU, exactly like
        # the paged round); the export must show the fused-kernel dispatch
        # instants with impl=pallas.
        cfg = LlamaConfig.tiny(num_hidden_layers=2)
        serve = ServeConfig(
            max_batch=2, decode_chunk_size=4, admission_window=0.02,
            fusion_impl="all@pallas",
        )
        max_seq = 128
    else:
        cfg = LlamaConfig.tiny(num_hidden_layers=2)
        serve = ServeConfig(
            max_batch=4, decode_chunk_size=4, admission_window=0.02,
            kv_mode="paged", page_size=16,
        )
        max_seq = 128
    params = M.init_params(cfg, jax.random.PRNGKey(7), jnp.float32)
    engine = BatchEngine(
        cfg, params, ByteTokenizer(),
        max_seq_len=max_seq, cache_dtype=jnp.float32, serve=serve,
    )
    engine.start()
    try:
        greedy = SamplingConfig(temperature=0.0, repeat_penalty=1.0)
        if args.paged_pallas:
            # Two ROUNDS, not two streams: round 2 re-serves the same
            # prompt warm so the suffix (cached-chunk) kernel dispatches.
            counts = []
            for _ in range(2):
                h = engine.submit(
                    [Message.user("kernel smoke prompt")],
                    min(args.tokens, 8), greedy,
                )
                counts.append(sum(1 for _ in h.tokens()))
                if not engine.quiesce(30.0):
                    raise RuntimeError("paged-pallas smoke pool never settled")
        else:
            handles = [
                engine.submit([Message.user(prompt)], args.tokens, greedy)
                for prompt in (
                    "smoke stream one", "a second concurrent stream"
                )
            ]
            counts = [sum(1 for _ in h.tokens()) for h in handles]
    finally:
        engine.stop()
        timeline.attach_jsonl(None)

    events = load_jsonl(jsonl)  # malformed line -> json error -> nonzero exit
    trace = export_events(events)
    problems = validate_export(trace)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(trace, f)
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] != "M"}
    required = {"epoch", "prefill", "decode-chunk", "request"}
    missing = required - names
    if missing:
        problems.append(f"expected span names absent: {sorted(missing)}")
    if args.paged_pallas:
        # The kernel-dispatch breadcrumbs (PagedLocalBackend._kernel_note):
        # every paged op of the warm serve must have resolved to the Pallas
        # family — an instant saying impl=xla means the kernel path
        # silently fell back, which is exactly what this gate exists to
        # catch before it lands.
        kernel = {
            e["name"]: e.get("args", {}).get("impl")
            for e in trace["traceEvents"]
            if e["ph"] == "i" and e["name"].startswith("kernel:")
        }
        # (Prefix-cache epochs route EVERY prefill — cold included —
        # through suffix_prefill, so kernel:prefill never fires here; the
        # fresh-chunk kernel path is pinned by tests/test_paged_prefill.py.)
        for op in ("kernel:suffix_prefill", "kernel:decode"):
            if op not in kernel:
                problems.append(f"paged kernel instant absent: {op}")
            elif kernel[op] != "pallas":
                problems.append(
                    f"{op} dispatched impl={kernel[op]!r}, wanted 'pallas' "
                    "(silent fallback to the XLA gather path)"
                )
    if args.fused_pallas:
        # The fused-kernel breadcrumbs (batch_backend._note_fusion_kernels):
        # every decode dispatch of the fused serve must have resolved the
        # fusion family to pallas — an instant saying impl=xla (or no
        # instant at all) means the fusion silently fell back to the
        # unfused path, which is exactly what this gate exists to catch.
        kernel = {
            e["name"]: e.get("args", {}).get("impl")
            for e in trace["traceEvents"]
            if e["ph"] == "i" and e["name"].startswith("kernel:fused_")
        }
        for op in (
            "kernel:fused_norm_matmul",
            "kernel:fused_qkv_ingest",
            "kernel:fused_sample_tail",
        ):
            if op not in kernel:
                problems.append(f"fused kernel instant absent: {op}")
            elif kernel[op] != "pallas":
                problems.append(
                    f"{op} dispatched impl={kernel[op]!r}, wanted 'pallas' "
                    "(silent fallback to the unfused path)"
                )
    if min(counts) < 1:
        problems.append(f"a stream produced no tokens: {counts}")
    for prob in problems:
        print(f"trace-smoke: FAIL: {prob}", file=sys.stderr)
    if problems:
        return 1
    print(
        f"trace-smoke: OK — {len(events)} events, {counts} tokens/stream, "
        f"jsonl={jsonl}" + (f", trace={args.out}" if args.out else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
