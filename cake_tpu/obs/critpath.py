"""Per-request critical-path attribution over the timeline span tree.

PRs 1, 5 and 11 record everything — span trees, lane tracks, clock-aligned
cluster traces — but nothing INTERPRETS them: "where did this request's
1.3 seconds go?" still means opening Perfetto. This module answers it as a
pure function over ring events (``Timeline.snapshot()`` or a
``--trace-jsonl`` file read back with ``load_jsonl``): decompose one
request's end-to-end latency into a canonical phase taxonomy, name the
dominant phase, and measure the **epoch convoy** — the lockstep tax the
ROADMAP's continuous-batching refactor must beat in an honest A/B.

Phase taxonomy (the documented contract; pinned by tests/test_critpath.py):

  * ``queue``        — submit to lane (fair-queue wait + admission window),
    from the ``queue_wait_s`` the engine stamps on the request span — PLUS
    a preempted lane's parked gaps: a spilled request closes its lane span
    and opens a fresh one at the restore, and the time between its request
    spans is capacity wait, attributed here (all of a rid's spans merge
    into one explanation; only the live intervals carry engine-span
    attribution).
  * ``admission``    — tokenize + quota/shed gate time inside ``submit()``
    (``admit_s``; t_submit is stamped after it, so this slice ADDS to the
    wall rather than carving into queue).
  * ``prefix_fork``  — prefix-cache chain fork + CoW split (the
    ``prefix-fork`` spans nested in prefill/join).
  * ``prefill``      — the request's OWN share of the epoch prefill (or its
    join prefill): epoch prefill compute covers the shared left-padded
    bucket, so a lane's own share is ``dur * prompt / bucket`` and the
    rest is convoy.
  * ``decode``       — the request's OWN share of each decode chunk it was
    live for: a chunk computes ``n`` tokens for every lane, the request
    consumed ``min(tokens_remaining, n)`` of them; the rest is convoy.
  * ``spec_accepted`` / ``spec_wasted`` — speculative verify rounds split
    by the round's cross-row accepted advance ``a``: the request's
    accepted share is ``dur * min(remaining, a) / (k + 1)``; the rest of
    the round (rejected drafts + co-batched rows' shape) is wasted.
  * ``convoy``       — time the lane sat computing co-batched streams' work
    the request did not need (prefill padding + unconsumed chunk/spec
    fractions). ``convoy_frac = convoy / wall`` is the headline lockstep
    tax: short requests co-batched with long ones show the higher value.
  * ``stall``        — stuck-epoch watchdog waits (``epoch-stall``
    instants), subtracted from the dispatch span they fired inside.
  * ``failover``     — live-stream migration (``failover-migrate`` spans).
  * ``restore``      — a preempted lane's re-attach prefill (``restore``
    spans, continuous scheduler): the redone work its spill cost it.
    Another request's restore in the shared segment is this lane's convoy.
  * ``wire``         — master-side worker round trips (``wire.<node>``
    spans, nested inside dispatches on TCP backends); subtracted from the
    enclosing compute attribution so nothing double-counts, and broken
    down per node in ``wire_nodes`` (riding the PR 11 clock alignment —
    merged cluster event lists work here too).
  * ``host``         — time inside the request span covered by NO engine
    span: scheduler bookkeeping, detokenization, readback glue. Measured
    as the complement, so the decomposition always sums to the wall.
  * ``other``        — the queue-side residual when the stamps disagree
    (normally ~0).

Everything is stdlib-only and side-effect free; the serving engine keeps
its own cheap live accounting for the aggregate ``cake_phase_seconds`` /
``cake_convoy_seconds`` metrics (runtime/serving.py), while this module
serves ``GET /explain``, ``cake-tpu explain``, and the blackbox doctor.
"""

from __future__ import annotations

from typing import Iterable

# Canonical phase order (rendering + tests iterate this, so the taxonomy
# is a tuple, not a convention). The names live in the shared registry
# (obs/taxonomy.py) next to the efficiency buckets — the taxonomy-drift
# lint rule pins every literal to it; re-exported here for the existing
# importers (blackbox, tests).
from cake_tpu.obs.taxonomy import PHASES  # noqa: E402


# Spans whose interval belongs to the engine's dispatch timeline; anything
# inside the request span not covered by an attribution lands in "host".
# The continuous scheduler's per-iteration ``step`` spans (and its
# ``segment`` root replacing the epoch span) are CONTAINERS, not dispatch
# time — the dispatches below nest inside them, so listing them here would
# double-count.
_ENGINE_SPANS = {
    "prefill", "join", "decode-chunk", "spec-round", "failover-migrate",
    "prefix-fork", "restore",
}


def _closed_spans(events: Iterable[dict]) -> list[dict]:
    """Flatten ring events into closed spans with [t0, t1) mono intervals."""
    out: list[dict] = []
    opens: dict[int, dict] = {}
    for e in events:
        ph = e.get("ph")
        if ph == "X":
            t0 = float(e.get("mono", 0.0))
            out.append({
                "name": e.get("name", ""), "rid": e.get("rid"),
                "t0": t0, "t1": t0 + float(e.get("dur", 0.0)),
                "args": e.get("args") or {}, "track": e.get("track"),
            })
        elif ph == "B" and "id" in e:
            opens[e["id"]] = e
        elif ph == "E" and e.get("id") in opens:
            b = opens.pop(e["id"])
            out.append({
                "name": b.get("name", ""), "rid": b.get("rid"),
                "t0": float(b.get("mono", 0.0)),
                "t1": float(e.get("mono", 0.0)),
                "args": {**(b.get("args") or {}), **(e.get("args") or {})},
                "track": b.get("track"),
            })
    return out


def _overlap(lo: float, hi: float, t0: float, t1: float) -> float:
    return max(0.0, min(hi, t1) - max(lo, t0))


def request_ids(events: Iterable[dict]) -> list[str]:
    """Request ids with a lane-track ``request`` span in the event list,
    oldest first (the ids ``explain`` can decompose)."""
    seen: dict[str, None] = {}
    for e in events:
        if (
            e.get("ph") in ("B", "X")
            and e.get("name") == "request"
            and e.get("rid")
        ):
            seen.setdefault(e["rid"], None)
    return list(seen)


def explain(events: list[dict], request_id: str) -> dict | None:
    """Decompose one request's end-to-end latency into PHASES.

    ``events`` is a timeline ring snapshot (or a loaded ``--trace-jsonl``
    stream); returns None when the request has no ``request`` span in it
    (evicted, shed before admission, or never existed). A request whose
    span is still open is explained up to the newest event and flagged
    ``in_flight``.
    """
    spans = _closed_spans(events)
    # A preempted request closes its lane span at the spill and opens a
    # fresh one at the restore, so one rid may own SEVERAL request spans.
    # They ALL belong to the explanation: the live intervals carry the
    # engine-span attribution, and the parked gaps between them (the lane
    # waiting for capacity again) are queue time — dropping the pre-spill
    # spans would hide exactly the latency preemption caused.
    req_spans = [
        s for s in spans
        if s["name"] == "request" and s["rid"] == request_id
    ]
    in_flight = False
    # Still-open span (B without E): a request mid-flight — possibly a
    # restored lane still decoding after an earlier closed pre-spill span.
    closed_ids = {e.get("id") for e in events if e.get("ph") == "E"}
    open_bs = [
        e for e in events
        if e.get("ph") == "B"
        and e.get("name") == "request"
        and e.get("rid") == request_id
        and e.get("id") not in closed_ids
    ]
    if open_bs:
        t_end = max(
            (float(ev.get("mono", 0.0)) for ev in events),
            default=float(open_bs[0].get("mono", 0.0)),
        )
        for e in open_bs:
            req_spans.append({
                "name": "request", "rid": request_id,
                "t0": float(e.get("mono", 0.0)), "t1": t_end,
                "args": e.get("args") or {}, "track": e.get("track"),
            })
        in_flight = True
    if not req_spans:
        return None
    req_spans.sort(key=lambda s: s["t0"])
    ivs = [(s["t0"], s["t1"]) for s in req_spans]
    b, e_ = ivs[0][0], ivs[-1][1]
    # The merged args: finish/completion from the FINAL span; the
    # queue/admission stamps from the FIRST (the original admission — a
    # restore's span re-stamps them relative to its own open).
    args: dict = {}
    for s in req_spans:
        args.update(s["args"])
    first_args = req_spans[0]["args"]
    # Live lane time vs parked time: span_s is what the engine-span walk
    # can cover (the host complement's denominator); the parked gaps are
    # queue-shaped waits.
    span_s = max(0.0, sum(t1 - t0 for t0, t1 in ivs))
    parked = max(0.0, (e_ - b) - span_s)

    def _live_ov(t0: float, t1: float) -> float:
        return sum(_overlap(a, z, t0, t1) for a, z in ivs)

    # The engine stamps t_submit AFTER submit()'s tokenize/quota/shed
    # work: queue_wait_s already excludes the admission slice, so
    # admission ADDS to the wall instead of carving into queue.
    queue_wait = float(first_args.get("queue_wait_s", 0.0) or 0.0)
    admit_s = float(first_args.get("admit_s", 0.0) or 0.0)
    prompt_tokens = int(args.get("prompt_tokens", 0) or 0)
    completion = int(args.get("completion_tokens", 0) or 0)
    is_join = "join_slot" in first_args

    phases = {p: 0.0 for p in PHASES}
    phases["queue"] = queue_wait + parked
    phases["admission"] = admit_s
    wire_nodes: dict[str, float] = {}

    # Stuck-epoch stalls: point instants carrying the abandoned wait; the
    # wait happened INSIDE the dispatch span it fired in, so that span's
    # effective duration shrinks by it before the own/convoy split.
    stall_marks = [
        (float(ev.get("mono", 0.0)), float(
            (ev.get("args") or {}).get("stall_s", 0.0) or 0.0
        ))
        for ev in events
        if ev.get("ph") == "i" and ev.get("name") == "epoch-stall"
        and any(a <= float(ev.get("mono", 0.0)) <= z for a, z in ivs)
    ]

    def stall_inside(t0: float, t1: float) -> float:
        return sum(s for (tm, s) in stall_marks if t0 <= tm <= t1)

    # Wire round trips (``wire.<node>`` — nested inside dispatch spans on
    # TCP backends): their own phase with a per-node breakdown, and pulled
    # back out of whatever dispatch span they nest in so nothing counts
    # twice. Clock alignment rides the PR 11 plane: merged cluster event
    # lists explain the same way.
    wire_spans = []
    for s in spans:
        if not s["name"].startswith("wire."):
            continue
        ov = _live_ov(s["t0"], s["t1"])
        if ov <= 0.0:
            continue
        wire_spans.append(s)
        phases["wire"] += ov
        node = s["name"][len("wire."):] or "?"
        wire_nodes[node] = wire_nodes.get(node, 0.0) + ov

    def wire_inside(t0: float, t1: float) -> float:
        return sum(
            _overlap(t0, t1, w["t0"], w["t1"]) for w in wire_spans
        )

    # Prefix-cache fork spans nest inside prefill ("lanes" in args — the
    # epoch-layout pass) or inside some request's join ("lane" in args).
    # They attribute RELATIVE to this request: the epoch fork is shared
    # epoch work (own share 1/lanes, rest convoy), this request's own
    # join fork is all its own, and ANOTHER request's join fork is just
    # part of that join's convoy — never this request's prefix_fork.
    fork_spans = [
        s for s in spans if s["name"] == "prefix-fork"
        and _live_ov(s["t0"], s["t1"]) > 0.0
    ]

    def fork_inside(t0: float, t1: float) -> float:
        return sum(
            _overlap(t0, t1, f["t0"], f["t1"]) for f in fork_spans
        )

    # Chronological walk of the engine spans the request was live for.
    work = sorted(
        (s for s in spans if s["name"] in _ENGINE_SPANS
         and s["name"] != "prefix-fork"
         and _live_ov(s["t0"], s["t1"]) > 0.0),
        key=lambda s: s["t0"],
    )
    # Tokens still owed after the prefill's first sample.
    rem = max(0, completion - 1)

    def _eff(s, ov, forks=0.0):
        """Dispatch-span time net of the stalls, wire hops, and fork
        passes inside it (each attributed to its own phase)."""
        st = min(stall_inside(s["t0"], s["t1"]), ov)
        phases["stall"] += st
        return max(0.0, ov - st - wire_inside(s["t0"], s["t1"]) - forks)

    for s in work:
        ov = _live_ov(s["t0"], s["t1"])
        name = s["name"]
        if name == "failover-migrate":
            phases["failover"] += max(
                0.0, ov - wire_inside(s["t0"], s["t1"])
            )
        elif name == "prefill":
            if is_join:
                continue  # an epoch prefill from before this join's lane
            fov = fork_inside(s["t0"], s["t1"])
            eff = _eff(s, ov, forks=fov)
            bucket = max(1, int((s["args"] or {}).get("bucket", 0) or 1))
            share = min(1.0, prompt_tokens / bucket) if prompt_tokens else 1.0
            phases["prefill"] += eff * share
            phases["convoy"] += eff * (1.0 - share)
            # The epoch-layout fork forks EVERY lane's chain: this
            # request's share is one lane's worth, the rest is convoy.
            lanes = max(1, int((s["args"] or {}).get("lanes", 1) or 1))
            phases["prefix_fork"] += fov / lanes
            phases["convoy"] += fov * (1.0 - 1.0 / lanes)
        elif name == "join":
            fov = fork_inside(s["t0"], s["t1"])
            if s["rid"] != request_id:
                # Another request joining the shared epoch: this lane sat
                # out its prefill — lockstep tax, fork included.
                phases["convoy"] += _eff(s, ov, forks=fov) + fov
                continue
            phases["prefill"] += _eff(s, ov, forks=fov)
            phases["prefix_fork"] += fov
        elif name == "restore":
            fov = fork_inside(s["t0"], s["t1"])
            if s["rid"] != request_id:
                # Another preempted lane re-attaching to the shared
                # segment: this lane rode along — convoy.
                phases["convoy"] += _eff(s, ov, forks=fov) + fov
                continue
            # This request's own re-attach prefill: the price its
            # preemption cost it, fork pass included.
            phases["restore"] += _eff(s, ov, forks=fov) + fov
        elif name == "decode-chunk":
            eff = _eff(s, ov)
            n = max(1, int((s["args"] or {}).get("n", 1) or 1))
            used = min(rem, n)
            rem -= used
            phases["decode"] += eff * (used / n)
            phases["convoy"] += eff * (1.0 - used / n)
        elif name == "spec-round":
            eff = _eff(s, ov)
            a = int((s["args"] or {}).get("accepted", 0) or 0)
            k = max(0, int((s["args"] or {}).get("k", 0) or 0))
            used = min(rem, a)
            rem -= used
            acc = eff * (used / (k + 1))
            phases["spec_accepted"] += acc
            phases["spec_wasted"] += eff - acc

    attributed = sum(
        phases[p] for p in PHASES if p not in ("queue", "admission", "host",
                                               "other")
    )
    phases["host"] = max(0.0, span_s - attributed)
    # Wall covers first-open to last-close: live lane time PLUS the parked
    # preemption gaps (already folded into the queue phase above).
    wall = admit_s + queue_wait + span_s + parked
    phases["other"] = max(0.0, wall - sum(
        phases[p] for p in PHASES if p != "other"
    ))
    phases = {p: round(v, 6) for p, v in phases.items()}
    named = sum(v for p, v in phases.items() if p not in ("host", "other"))
    out = {
        "request_id": request_id,
        "in_flight": in_flight,
        "wall_s": round(wall, 6),
        "span_s": round(span_s, 6),
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion,
        "finish_reason": args.get("finish_reason"),
        "phases": phases,
        "dominant": dominant(phases),
        "convoy_frac": round(phases["convoy"] / wall, 4) if wall > 0 else 0.0,
        # How much of the wall the NAMED phases (everything except the
        # host/other complements) explain — the >= 0.95 acceptance gate.
        "coverage": round(named / wall, 4) if wall > 0 else 0.0,
    }
    if wire_nodes:
        out["wire_nodes"] = {n: round(v, 6) for n, v in wire_nodes.items()}
    return out


def explain_all(events: list[dict]) -> list[dict]:
    """``explain`` for every request id in the event list (oldest first) —
    the offline ``cake-tpu explain --jsonl`` sweep."""
    out = []
    for rid in request_ids(events):
        res = explain(events, rid)
        if res is not None:
            out.append(res)
    return out


def dominant(phases: dict) -> str:
    """Largest phase by seconds (host/other lose ties to named phases)."""
    best, best_v = "host", -1.0
    for p in PHASES:
        v = float(phases.get(p, 0.0) or 0.0)
        bonus = 0 if p in ("host", "other") else 1e-12
        if v + bonus > best_v:
            best, best_v = p, v + bonus
    return best


def render(res: dict) -> str:
    """Terminal table for one explained request (``cake-tpu explain``)."""
    lines = [
        f"request {res['request_id']}"
        + ("  [in flight]" if res.get("in_flight") else ""),
        f"  wall {res['wall_s'] * 1e3:.2f} ms  "
        f"(prompt {res.get('prompt_tokens', 0)} tok, "
        f"completion {res.get('completion_tokens', 0)} tok, "
        f"finish {res.get('finish_reason') or '?'})",
        f"  dominant phase: {res['dominant']}   "
        f"convoy_frac {res['convoy_frac']:.3f}   "
        f"coverage {res['coverage']:.3f}",
        "",
        f"  {'phase':14} {'ms':>10} {'share':>7}",
    ]
    wall = res["wall_s"] or 1.0
    for p in PHASES:
        v = float(res["phases"].get(p, 0.0) or 0.0)
        if v <= 0.0:
            continue
        lines.append(f"  {p:14} {v * 1e3:>10.2f} {v / wall * 100:>6.1f}%")
    for node, v in sorted(res.get("wire_nodes", {}).items()):
        lines.append(f"    wire.{node:9} {v * 1e3:>10.2f}")
    return "\n".join(lines)
