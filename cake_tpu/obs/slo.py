"""Per-tenant SLO tracking: rolling multi-window SLIs and burn rates.

PR 10 gave every request a tenant and a deadline; nothing tracked whether
tenants actually MEET their objectives over time. This module closes the
loop the ROADMAP names ("SLO-aware epoch sizing that feeds the deadline
estimator back into admission") with the goodput-under-SLO framing of the
multi-core-NPU serving study (PAPERS.md):

  * ``SloObjectives`` — the server's declared objectives (``--slo-ttft-ms``
    with a target fraction, ``--slo-deadline-rate``). Objectives are
    server-wide; COMPLIANCE is tracked per tenant.
  * ``SloTracker`` — per-tenant rolling SLIs over a FAST and a SLOW window
    (classic multiwindow burn-rate alerting): TTFT p99, TTFT-objective hit
    fraction, deadline hit rate, error/shed rates, and goodput tok/s.
    The error-budget **burn rate** of an objective is
    ``observed_miss_fraction / allowed_miss_fraction`` — 1.0 consumes the
    budget exactly at the sustainable rate, >1 burns it. A tenant's
    headline burn is ``max`` over objectives of ``min(fast, slow)``: both
    windows must show the burn (a blip in the fast window alone does not
    trigger feedback; a long-past incident still visible in the slow
    window alone does not either).

SLI definitions (documented contract, pinned by tests/test_slo.py):

  * **TTFT**: over ACCEPTED requests. A request that produced a first
    token counts against ``ttft_ms``; a request that finished with ZERO
    tokens for ``deadline``/``error`` reasons is a miss by definition (it
    never produced a first token within any bound). 429/503 refusals are
    not TTFT samples (the request was never accepted) — they feed the
    shed-rate SLI instead.
  * **Deadline**: over accepted requests that CARRIED a deadline — hit
    when the stream finished ``stop``/``length``, miss when it finished
    ``deadline`` (queued expiry included). ``error`` and ``cancelled``
    outcomes are excluded from this SLI (errors feed the error-rate SLI;
    a cancel is the client's own action) — counting them as hits would
    hide a tenant whose deadline traffic all errored.
  * **Goodput**: completion tokens of ``stop``/``length`` finishes per
    window second.

Feedback to admission (``adjustments``): a tenant burning budget gets its
FairQueue quantum WEIGHTED up (runtime/admission.py — the per-tenant-
weights seam PR 10 left: more deficit per round-robin visit, so its queue
drains ahead of non-burning tenants) and its WaitEstimator shed estimate
SCALED up (deadline-doomed submissions from a tenant already missing SLOs
are refused earlier, protecting goodput instead of queueing work that will
miss). The engine applies both about once a second
(runtime/serving.BatchEngine._apply_slo_feedback).

Observability: ``cake_slo_*`` gauges (refreshed at scrape time), the
``GET /slo`` endpoint (snapshot), and ``slo-burn`` flight events on every
burning/recovered transition.

Stdlib-only, thread-safe, bounded (least-recently-active tenants evicted
past ``max_tenants`` — the same label-space discipline as TenantMeter).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque

from cake_tpu.utils import metrics

# Reservoir cap per bucket for TTFT percentile estimation: p99 over the
# window is computed from at most bucket_count * this many samples.
_SAMPLES_PER_BUCKET = 64

# Feedback caps: a burning tenant's quantum weight / shed-estimate scale
# grow with the burn but never past these (isolation must survive feedback).
_MAX_QUANTUM_WEIGHT = 4.0
_MAX_SHED_SCALE = 4.0


@dataclasses.dataclass(frozen=True)
class SloObjectives:
    """Declared service objectives (0 disables each)."""

    # TTFT objective: ``ttft_target`` of accepted requests must see their
    # first token within ``ttft_ms`` milliseconds.
    ttft_ms: float = 0.0
    ttft_target: float = 0.99
    # Deadline objective: this fraction of deadline-carrying requests must
    # finish before their deadline.
    deadline_rate: float = 0.0

    def __post_init__(self) -> None:
        if self.ttft_ms < 0 or not (0.0 < self.ttft_target < 1.0):
            raise ValueError(
                "slo_ttft_ms must be >= 0 and slo_ttft_target in (0, 1), "
                f"got {self.ttft_ms}/{self.ttft_target}"
            )
        if not (0.0 <= self.deadline_rate < 1.0):
            raise ValueError(
                f"slo_deadline_rate must be in [0, 1), got "
                f"{self.deadline_rate}"
            )

    def declared(self) -> bool:
        return self.ttft_ms > 0 or self.deadline_rate > 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Bucket:
    __slots__ = (
        "t0", "ttft_n", "ttft_miss", "ttft_samples", "dl_n", "dl_miss",
        "finished", "errors", "refusals", "quota_refusals", "good_tokens",
    )

    def __init__(self, t0: float):
        self.t0 = t0
        self.ttft_n = 0
        self.ttft_miss = 0
        self.ttft_samples: list[float] = []
        self.dl_n = 0
        self.dl_miss = 0
        self.finished = 0
        self.errors = 0
        self.refusals = 0         # all pre-acceptance refusals (shed+quota)
        self.quota_refusals = 0   # the 429 slice of the above
        self.good_tokens = 0


class _TenantSeries:
    """One tenant's rolling buckets (width = fast_window / 12, deque spans
    the slow window)."""

    __slots__ = ("buckets", "burning")

    def __init__(self) -> None:
        self.buckets: deque[_Bucket] = deque()
        self.burning = False  # transition state for slo-burn events


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    i = min(len(s) - 1, max(0, int(round((q / 100.0) * (len(s) - 1)))))
    return s[i]


class SloTracker:
    """Rolling per-tenant SLIs + burn rates against declared objectives."""

    def __init__(
        self,
        objectives: SloObjectives | None = None,
        *,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        max_tenants: int = 256,
        time_fn=time.monotonic,
    ):
        if fast_window_s <= 0 or slow_window_s < fast_window_s:
            raise ValueError(
                "slo windows need 0 < fast <= slow, got "
                f"{fast_window_s}/{slow_window_s}"
            )
        self.objectives = objectives or SloObjectives()
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.max_tenants = int(max_tenants)
        self._bucket_s = max(1.0, self.fast_window_s / 12.0)
        self._time = time_fn
        self._lock = threading.Lock()
        self._tenants: OrderedDict[str, _TenantSeries] = OrderedDict()
        # Tenants whose gauges the last refresh_metrics exported: an
        # LRU-evicted tenant's series must be zeroed on the next refresh,
        # or its last burn value would stand in /metrics forever (the
        # registry keeps every series) — a permanent false alert.
        self._exported: set[str] = set()

    # ------------------------------------------------------------ recording

    def _bucket(self, tenant: str) -> _Bucket:
        """Current bucket for ``tenant`` (caller holds the lock)."""
        now = self._time()
        series = self._tenants.get(tenant)
        if series is None:
            series = self._tenants[tenant] = _TenantSeries()
            while len(self._tenants) > self.max_tenants:
                self._tenants.popitem(last=False)  # least recently active
        else:
            self._tenants.move_to_end(tenant)
        horizon = now - self.slow_window_s - self._bucket_s
        while series.buckets and series.buckets[0].t0 < horizon:
            series.buckets.popleft()
        if not series.buckets or now - series.buckets[-1].t0 >= self._bucket_s:
            series.buckets.append(_Bucket(now))
        return series.buckets[-1]

    def observe_ttft(self, tenant: str, ttft_s: float) -> None:
        """A stream produced its first token ``ttft_s`` after submit."""
        with self._lock:
            b = self._bucket(tenant)
            b.ttft_n += 1
            if (
                self.objectives.ttft_ms > 0
                and ttft_s * 1e3 > self.objectives.ttft_ms
            ):
                b.ttft_miss += 1
            if len(b.ttft_samples) < _SAMPLES_PER_BUCKET:
                b.ttft_samples.append(ttft_s)

    def observe_finish(
        self,
        tenant: str,
        finish_reason: str,
        *,
        tokens: int = 0,
        had_deadline: bool = False,
        got_first_token: bool = True,
    ) -> None:
        """A stream ended (any reason; queued deadline expiry included)."""
        with self._lock:
            b = self._bucket(tenant)
            b.finished += 1
            if finish_reason in ("stop", "length"):
                b.good_tokens += int(tokens)
            elif finish_reason == "error":
                b.errors += 1
            if had_deadline and finish_reason in (
                "stop", "length", "deadline"
            ):
                # The deadline SLI is hit-on-clean-finish vs miss-on-
                # expiry. Other outcomes of deadline-carrying requests —
                # "error" (feeds the error-rate SLI) and "cancelled" (a
                # client action) — are excluded rather than silently
                # counted as hits, which would report a 100% hit rate for
                # a tenant whose deadline traffic all errored.
                b.dl_n += 1
                if finish_reason == "deadline":
                    b.dl_miss += 1
            if not got_first_token and finish_reason in ("deadline", "error"):
                # No first token within ANY bound: a TTFT miss by
                # definition (module docstring SLI contract).
                b.ttft_n += 1
                b.ttft_miss += 1

    def observe_refusal(self, tenant: str, kind: str) -> None:
        """A submission refused before acceptance. ``kind`` distinguishes
        server saturation (``"shed"`` — 503) from the tenant's own quota
        (``"quota"`` — 429): both feed the combined shed-rate SLI, and the
        quota slice surfaces separately in the window breakdown."""
        with self._lock:
            b = self._bucket(tenant)
            b.refusals += 1
            if kind == "quota":
                b.quota_refusals += 1

    # ------------------------------------------------------------- windows

    def _window(self, series: _TenantSeries, window_s: float) -> dict:
        """Aggregate SLIs over the trailing ``window_s`` (caller holds the
        lock)."""
        now = self._time()
        lo = now - window_s
        agg = _Bucket(lo)
        for b in series.buckets:
            if b.t0 + self._bucket_s <= lo:
                continue
            agg.ttft_n += b.ttft_n
            agg.ttft_miss += b.ttft_miss
            agg.ttft_samples.extend(b.ttft_samples)
            agg.dl_n += b.dl_n
            agg.dl_miss += b.dl_miss
            agg.finished += b.finished
            agg.errors += b.errors
            agg.refusals += b.refusals
            agg.quota_refusals += b.quota_refusals
            agg.good_tokens += b.good_tokens
        out = {
            "requests": agg.finished,
            "ttft_p99_s": round(_percentile(agg.ttft_samples, 99), 6),
            "deadline_hit_rate": (
                round(1.0 - agg.dl_miss / agg.dl_n, 4) if agg.dl_n else None
            ),
            "error_rate": (
                round(agg.errors / agg.finished, 4) if agg.finished else 0.0
            ),
            "shed_rate": (
                round(agg.refusals / (agg.finished + agg.refusals), 4)
                if (agg.finished + agg.refusals)
                else 0.0
            ),
            "refusals": {
                "shed": agg.refusals - agg.quota_refusals,
                "quota": agg.quota_refusals,
            },
            "goodput_tok_s": round(agg.good_tokens / window_s, 3),
        }
        burns = {}
        if self.objectives.ttft_ms > 0:
            allowed = 1.0 - self.objectives.ttft_target
            frac = agg.ttft_miss / agg.ttft_n if agg.ttft_n else 0.0
            burns["ttft"] = round(frac / allowed, 3)
        if self.objectives.deadline_rate > 0:
            allowed = 1.0 - self.objectives.deadline_rate
            frac = agg.dl_miss / agg.dl_n if agg.dl_n else 0.0
            burns["deadline"] = round(frac / allowed, 3)
        out["burn"] = burns
        return out

    def _burn_locked(self, series: _TenantSeries) -> float:
        fast = self._window(series, self.fast_window_s)["burn"]
        slow = self._window(series, self.slow_window_s)["burn"]
        worst = 0.0
        for obj in fast:
            worst = max(worst, min(fast[obj], slow.get(obj, 0.0)))
        return worst

    def burn(self, tenant: str) -> float:
        """Headline burn rate: max over objectives of min(fast, slow);
        0.0 = inside budget (or no objectives declared)."""
        with self._lock:
            series = self._tenants.get(tenant)
            if series is None:
                return 0.0
            return self._burn_locked(series)

    # ------------------------------------------------------------- outputs

    def snapshot(self) -> dict:
        """The ``GET /slo`` body: objectives, windows, per-tenant SLIs and
        burn rates."""
        with self._lock:
            tenants = {}
            for name, series in self._tenants.items():
                tenants[name] = {
                    "fast": self._window(series, self.fast_window_s),
                    "slow": self._window(series, self.slow_window_s),
                    "burn_rate": round(self._burn_locked(series), 3),
                }
        return {
            "objectives": self.objectives.to_dict(),
            "windows": {
                "fast_s": self.fast_window_s,
                "slow_s": self.slow_window_s,
            },
            "tenants": tenants,
        }

    def adjustments(self) -> dict[str, dict]:
        """Admission feedback per tracked tenant (module docstring):
        ``quantum_weight`` for the FairQueue and ``shed_scale`` for the
        WaitEstimator, both 1.0 when the tenant is inside budget. Also
        emits the burning/recovered transition events."""
        transitions: list[tuple[str, bool, float]] = []
        out: dict[str, dict] = {}
        with self._lock:
            for name, series in self._tenants.items():
                burn = self._burn_locked(series)
                burning = burn >= 1.0
                if burning != series.burning:
                    series.burning = burning
                    transitions.append((name, burning, burn))
                if burning:
                    w = min(_MAX_QUANTUM_WEIGHT, 1.0 + burn)
                    s = min(_MAX_SHED_SCALE, 1.0 + burn)
                else:
                    w = s = 1.0
                out[name] = {
                    "burn": round(burn, 3),
                    "quantum_weight": round(w, 3),
                    "shed_scale": round(s, 3),
                }
        for name, burning, burn in transitions:
            metrics.flight.record(
                "slo-burn", tenant=name,
                state="burning" if burning else "recovered",
                burn=round(burn, 3),
            )
            metrics.registry.counter(
                "cake_slo_burn_transitions_total",
                "Tenant error-budget burn transitions "
                "(state=burning|recovered).",
            ).inc(tenant=name,
                  state="burning" if burning else "recovered")
        return out

    def refresh_metrics(self) -> None:
        """Set the ``cake_slo_*`` gauges from the current windows — called
        at scrape time (GET /metrics), so the exported series always
        reflect the live windows without per-observation gauge churn."""
        snap = self.snapshot()
        p99 = metrics.registry.gauge(
            "cake_slo_ttft_p99_seconds",
            "Rolling TTFT p99 per tenant and window.",
        )
        hit = metrics.registry.gauge(
            "cake_slo_deadline_hit_rate",
            "Rolling deadline hit rate per tenant and window (-1 = no "
            "deadline-carrying traffic in the window).",
        )
        good = metrics.registry.gauge(
            "cake_slo_goodput_tokens_per_second",
            "Rolling goodput (completion tokens of clean finishes) per "
            "tenant and window.",
        )
        burn = metrics.registry.gauge(
            "cake_slo_burn_rate",
            "Error-budget burn rate per tenant, objective and window "
            "(1.0 = consuming budget exactly at the sustainable rate).",
        )
        head = metrics.registry.gauge(
            "cake_slo_tenant_burn",
            "Headline burn per tenant: max over objectives of "
            "min(fast, slow).",
        )
        for tenant, t in snap["tenants"].items():
            for window in ("fast", "slow"):
                w = t[window]
                p99.set(w["ttft_p99_s"], tenant=tenant, window=window)
                hit.set(
                    -1.0 if w["deadline_hit_rate"] is None
                    else w["deadline_hit_rate"],
                    tenant=tenant, window=window,
                )
                good.set(w["goodput_tok_s"], tenant=tenant, window=window)
                for obj, b in w["burn"].items():
                    burn.set(
                        b, tenant=tenant, objective=obj, window=window
                    )
            head.set(t["burn_rate"], tenant=tenant)
        # Tenants evicted since the last refresh: zero their series (the
        # registry keeps them) so a stale burn never stands as a false
        # alert after the tenant aged out of tracking.
        for tenant in self._exported - set(snap["tenants"]):
            head.set(0.0, tenant=tenant)
            for window in ("fast", "slow"):
                p99.set(0.0, tenant=tenant, window=window)
                hit.set(-1.0, tenant=tenant, window=window)
                good.set(0.0, tenant=tenant, window=window)
                for obj in ("ttft", "deadline"):
                    burn.set(
                        0.0, tenant=tenant, objective=obj, window=window
                    )
        self._exported = set(snap["tenants"])
