"""Cluster observability plane: federated telemetry + clock-aligned merges.

PRs 1 and 5 built the single-process observability stack (utils/metrics.py,
obs/timeline.py); in a tcp/pipeline cluster every WORKER keeps its own
counters, flight events, and timeline spans, and none of it reaches the
master's /metrics, /events, or /trace surfaces. This module is the master's
side of the federation:

  * ``ClockOffsetEstimator`` — per-worker wall-clock offset from PING round
    trips, NTP-style: the worker stamps its wall clock into the PING reply
    (runtime/proto.py), and ``offset = t_worker - (t_send + t_recv) / 2``
    assumes the reply clock was read at the round-trip midpoint. The error
    is bounded by the path asymmetry — at most RTT/2 — and EWMA smoothing
    rejects jitter. Exported as ``cake_clock_offset_seconds{node}``.
  * ``ClusterObserver`` — the per-node report store + merge logic. Reports
    arrive from the heartbeat monitor's STATS pulls (runtime/client.py —
    piggybacked on the PR 6 probe connections, so federation allocates no
    new sockets) or from an on-demand ``DistributedForwardStep.
    pull_cluster_stats`` (runtime/master.py). Merges:
      - ``merged_exposition`` — ONE Prometheus scrape with every node's
        series under a ``node`` label (utils/metrics.merged_exposition);
      - ``merged_events`` — cluster-wide flight events interleaved by
        clock-ALIGNED time;
      - ``merged_trace`` — ONE Chrome-trace export where each worker's
        timeline events are shifted by its estimated offset, so worker op
        spans visibly nest (in time) inside the master's ``wire.<node>``
        spans and the PR 5 flow arrows connect across process tracks.

  The pull model is snapshot-replacement: the latest report per node WINS
  (a worker restart resets that node's series to the worker's truth —
  counters stay monotonic per node lifetime, never double-counted). When a
  node reports, any LOCALLY recorded events/series carrying its node label
  are superseded by the report (impossible in a real multi-process
  deployment, exact in single-process test clusters).

Everything is stdlib-only and thread-safe, mirroring metrics.registry /
obs.timeline: one process-global ``cluster`` observer serves the runtime.
"""

from __future__ import annotations

import threading
import time

from cake_tpu.utils import metrics


class ClockOffsetEstimator:
    """NTP-style wall-clock offset of one remote node, EWMA-smoothed.

    ``observe(t_send, t_recv, t_worker)`` takes the master-side wall clocks
    around one PING round trip and the worker's reply stamp; the sample
    ``t_worker - (t_send + t_recv) / 2`` is exact when the path is
    symmetric and off by at most RTT/2 when it is not (the alignment
    contract README documents). Samples whose RTT blows up past the best
    observed RTT are discarded — congestion makes the midpoint assumption
    worthless exactly when RTT is inflated.
    """

    # Smoothing weight per accepted sample; ~10 samples to converge.
    ALPHA = 0.2
    # Accept a sample only within this multiple of the best RTT seen.
    RTT_GATE = 3.0

    def __init__(self) -> None:
        self.offset = 0.0          # smoothed seconds (worker - master)
        self.samples = 0
        self.rtt = 0.0             # last accepted RTT, seconds
        self.best_rtt = float("inf")

    def observe(self, t_send: float, t_recv: float, t_worker: float) -> float:
        rtt = max(0.0, t_recv - t_send)
        if self.samples and rtt > self.RTT_GATE * max(1e-6, self.best_rtt):
            # Congested round trip: the midpoint assumption is noise. But
            # AGE the gate on every rejection — a sustained RTT regime
            # shift (route change, loaded link) re-opens it within a few
            # probes instead of freezing the estimate forever on a stale
            # idle-link minimum.
            self.best_rtt *= 1.25
            return self.offset
        sample = t_worker - (t_send + t_recv) / 2.0
        self.samples += 1
        self.rtt = rtt
        self.best_rtt = min(self.best_rtt, rtt)
        if self.samples == 1:
            self.offset = sample
        else:
            self.offset += self.ALPHA * (sample - self.offset)
        return self.offset

    @property
    def error_bound_s(self) -> float:
        """Worst-case alignment error of the current estimate: half the
        best round trip (pure path asymmetry)."""
        return 0.0 if self.samples == 0 else self.best_rtt / 2.0


class _NodeView:
    __slots__ = ("clock", "report", "t_report")

    def __init__(self) -> None:
        self.clock = ClockOffsetEstimator()
        self.report: dict | None = None
        self.t_report = 0.0  # monotonic receive time (staleness)


class ClusterObserver:
    """Per-node telemetry store + the cluster-wide merge logic."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._nodes: dict[str, _NodeView] = {}

    # ------------------------------------------------------------- feeding

    def _view(self, node: str) -> _NodeView:
        """Get-or-create; every caller already holds ``self._lock`` (the
        observe_ping / update_report entry points take it)."""
        v = self._nodes.get(node)
        if v is None:
            # cake-lint: disable-next-line=unlocked-shared-mutation
            v = self._nodes[node] = _NodeView()
        return v

    def observe_ping(
        self,
        node: str,
        t_send: float,
        t_recv: float,
        t_worker: float | None,
    ) -> None:
        """One PING round trip's clocks. ``t_worker`` None (old worker,
        no reply stamp) still registers the node but estimates nothing."""
        with self._lock:
            clock = self._view(node).clock
            if t_worker is not None:
                off = clock.observe(t_send, t_recv, t_worker)
            else:
                off = None
        if off is not None:
            metrics.registry.gauge(
                "cake_clock_offset_seconds",
                "Estimated wall-clock offset of each worker vs this master "
                "(NTP-style from heartbeat RTT midpoints; error <= RTT/2).",
            ).set(round(off, 6), node=node)

    def update_report(self, node: str, report: dict) -> None:
        """Adopt one node's STATS snapshot (replaces the previous — the
        pull model's last-snapshot-wins contract)."""
        if not isinstance(report, dict):
            return
        with self._lock:
            v = self._view(node)
            v.report = report
            v.t_report = time.monotonic()

    def clear(self) -> None:
        with self._lock:
            self._nodes.clear()

    # ------------------------------------------------------------- queries

    def nodes(self) -> list[str]:
        """Nodes with a live report (a ping-only node has nothing to
        merge yet)."""
        with self._lock:
            return sorted(
                n for n, v in self._nodes.items() if v.report is not None
            )

    def offset(self, node: str) -> float:
        with self._lock:
            v = self._nodes.get(node)
            return v.clock.offset if v is not None else 0.0

    def report_age_s(self, node: str) -> float | None:
        with self._lock:
            v = self._nodes.get(node)
            if v is None or v.report is None:
                return None
            return time.monotonic() - v.t_report

    def _reports(self) -> list[tuple[str, float, dict]]:
        """(node, offset, report) for every reporting node, under one
        lock acquisition."""
        with self._lock:
            return [
                (n, v.clock.offset, v.report)
                for n, v in sorted(self._nodes.items())
                if v.report is not None
            ]

    # -------------------------------------------------------------- merges

    def merged_exposition(
        self, local_dump: dict, local_node: str = "master"
    ) -> str:
        """ONE Prometheus scrape for the whole cluster: the master's own
        registry dump plus every node's pulled dump, each series under a
        ``node`` label. A local series is dropped only when the exact same
        (family, label set) arrives in a report — the pulled report is
        authoritative for series the worker records about ITSELF (which is
        also what deduplicates single-process test clusters, where both
        ends share one registry); master-side series ABOUT a worker
        (``cake_hop_seconds{node=...}``, ``cake_clock_offset_seconds``)
        stay, they exist nowhere else."""
        remote = self._reports()
        reported: set[tuple] = set()
        for _, _, report in remote:
            for m in report.get("metrics", {}).get("metrics", []):
                for s in m.get("series", []):
                    reported.add(
                        (m["name"], tuple(sorted(s["labels"].items())))
                    )
        local = {
            "metrics": [
                {
                    **m,
                    "series": [
                        s for s in m["series"]
                        if (
                            m["name"],
                            tuple(sorted(s["labels"].items())),
                        ) not in reported
                    ],
                }
                for m in local_dump.get("metrics", [])
            ]
        }
        local["metrics"] = [m for m in local["metrics"] if m["series"]]
        dumps = [(local_node, local)]
        for node, _, report in remote:
            dumps.append((node, report.get("metrics", {})))
        return metrics.merged_exposition(dumps)

    def merged_events(
        self, local_events: list[dict], local_node: str = "master"
    ) -> list[dict]:
        """Cluster-wide flight events interleaved by ALIGNED wall time:
        each remote event's ``ts`` is shifted onto the master clock by the
        node's estimated offset, every event carries a ``node`` field, and
        the merge sorts by the aligned clock. A local event identical to a
        reported one is dropped (single-process test clusters share the
        ring); master-recorded events ABOUT a worker (``worker-reconnect``,
        ``hop-failed``) differ from anything the worker reports and stay."""
        import json as _json

        remote = self._reports()
        reported_ev = {
            _json.dumps(e, sort_keys=True, default=str)
            for _, _, report in remote
            for e in report.get("events", [])
        }
        out = [
            {**e, "node": e.get("node", local_node)}
            for e in local_events
            if _json.dumps(e, sort_keys=True, default=str) not in reported_ev
        ]
        for node, off, report in remote:
            for e in report.get("events", []):
                e2 = dict(e)
                if "ts" in e2:
                    e2["ts"] = round(float(e2["ts"]) - off, 6)
                e2.setdefault("node", node)
                out.append(e2)
        out.sort(key=lambda e: e.get("ts", 0.0))
        return out

    def remote_timeline_events(
        self, request_id: str | None = None
    ) -> list[dict]:
        """Every reporting node's timeline slice, shifted onto the master
        clock (``wall -= offset``) and node-stamped — ready to concatenate
        with the local ring for one merged export."""
        out: list[dict] = []
        for node, off, report in self._reports():
            events = report.get("timeline", [])
            if request_id is not None:
                keep = {
                    e.get("id") for e in events
                    if e.get("rid") == request_id and "id" in e
                }
                events = [
                    e for e in events
                    if e.get("rid") == request_id or e.get("id") in keep
                ]
            for e in events:
                e2 = dict(e)
                if "wall" in e2:
                    e2["wall"] = round(float(e2["wall"]) - off, 6)
                e2.setdefault("node", node)
                out.append(e2)
        return out

    def merged_trace(
        self,
        local_events: list[dict],
        default_node: str = "master",
        request_id: str | None = None,
    ) -> dict:
        """ONE Chrome-trace export for the cluster: local events plus every
        node's clock-shifted slice (``GET /trace?cluster=1``,
        ``cake-tpu trace --cluster``). After the shift, a worker op span's
        interval sits inside the master's ``wire.<node>`` span that caused
        it — the nesting the obs-smoke gate pins — and flow arrows land on
        slices in BOTH processes."""
        from cake_tpu.obs.timeline import export_events

        remote_nodes = set(self.nodes())
        local = [
            e for e in local_events if e.get("node") not in remote_nodes
        ]
        events = local + self.remote_timeline_events(request_id)
        # The exporter emits in input order per track; B/E pairing is by id
        # so ordering across sources is safe, but keep instants/counters
        # readable by sorting on the aligned clock.
        events.sort(key=lambda e: e.get("wall", 0.0))
        return export_events(events, default_node=default_node)

    # ------------------------------------------------------------ summaries

    def snapshot(self) -> dict:
        """Per-node summary for ``/stats`` and the ``cake-tpu stats``
        per-node table: clock estimate, report freshness, and headline op
        telemetry derived from the node's own dump."""
        out: dict[str, dict] = {}
        with self._lock:
            items = sorted(self._nodes.items())
        now = time.monotonic()
        for node, v in items:
            row: dict = {
                "offset_s": round(v.clock.offset, 6),
                "offset_error_bound_s": round(v.clock.error_bound_s, 6),
                "rtt_ms": round(v.clock.rtt * 1e3, 3),
                "report_age_s": (
                    round(now - v.t_report, 3)
                    if v.report is not None
                    else None
                ),
            }
            if v.report is not None:
                row.update(_report_headline(v.report))
            out[node] = row
        return out


def _report_headline(report: dict) -> dict:
    """Headline numbers from one node's metrics dump: served ops + mean op
    latency (cake_worker_op_seconds) and payload bytes by direction."""
    ops = 0
    op_sum = 0.0
    rx = tx = 0.0
    for m in report.get("metrics", {}).get("metrics", []):
        if m["name"] == "cake_worker_op_seconds":
            for s in m["series"]:
                ops += s.get("count", 0)
                op_sum += s.get("sum", 0.0)
        elif m["name"] == "cake_worker_bytes_total":
            for s in m["series"]:
                d = s["labels"].get("direction")
                if d == "rx":
                    rx += s.get("value", 0.0)
                elif d == "tx":
                    tx += s.get("value", 0.0)
    return {
        "ops": ops,
        "op_mean_ms": round(op_sum / ops * 1e3, 3) if ops else 0.0,
        "bytes_rx": int(rx),
        "bytes_tx": int(tx),
    }


# Process-global instance: one observer serves the whole runtime (tests may
# build private ones). Mirrors metrics.registry / obs.timeline.timeline.
cluster = ClusterObserver()
