"""Perf ledger: a durable bench trajectory + noise-aware regression diffs.

``bench.py`` emits one normalized JSON record per run; until now each run
overwrote the last and the trajectory lived only in git history of the
``BENCH_r*.json`` snapshots someone remembered to commit. This module

  * appends every top-level bench emit to ``BENCH_HISTORY.jsonl`` (one
    line per run, stamped with the git revision and a wall timestamp —
    ``append_history``), and
  * compares two bench records with noise-aware thresholds
    (``diff_records`` behind ``cake-tpu benchdiff old.json new.json``):
    a key regresses only when it moves BOTH more than the relative
    threshold AND more than the key class's absolute floor — a 3% wobble
    on a 150 tok/s headline is noise; a 20% drop is a gate failure.

Direction is inferred from the key name (the bench's own conventions):
throughput/utilization keys (``*tok_s*``, ``*mfu*``, ``*util*``,
``*hit_rate*``, ``*goodput*``, ``vs_baseline``) are higher-better; latency/compile keys
(``*_s``, ``*_ms``, ``*seconds*``, ``*compile*``, ``*retrace*``,
``*ttft*``) are lower-better; anything else is reported informationally
and never gates. Stdlib-only (bench.py imports this before jax exists).
"""

from __future__ import annotations

import json
import os
import subprocess
import time

HISTORY_NAME = "BENCH_HISTORY.jsonl"

# Absolute floors per key class: a change smaller than the floor never
# regresses regardless of its relative size (sub-noise keys like a 0.01s
# compile wobble would otherwise flap the gate).
DEFAULT_FLOORS = {
    "tok_s": 1.0,       # throughput keys (tok/s)
    "seconds": 0.02,    # latency / compile-time keys
    "count": 0.5,       # retrace / integer counters
    "ratio": 0.01,      # mfu / util / hit-rate fractions
    "default": 1e-9,
}

_HIGHER = ("tok_s", "tok/s", "mfu", "util", "hit_rate", "vs_baseline",
           "bandwidth", "gbps", "goodput")
_LOWER = ("_s", "_ms", "seconds", "compile", "retrace", "ttft", "latency")


def git_rev(repo_dir: str | None = None) -> str | None:
    """Short git revision of ``repo_dir`` (this file's repo by default);
    None when git or the repo is unavailable (the record still lands)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo_dir or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, timeout=10, text=True,
        )
        rev = out.stdout.strip()
        return rev or None
    except (OSError, subprocess.SubprocessError):
        return None


def flatten_numeric(rec: dict, prefix: str = "") -> dict[str, float]:
    """Dotted numeric leaves of a (possibly nested) bench record — the
    comparable key set. Bools and strings never gate."""
    out: dict[str, float] = {}
    for k, v in rec.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten_numeric(v, prefix=f"{key}."))
    return out


def append_history(
    rec: dict, path: str, *, repo_dir: str | None = None,
    ts: float | None = None,
) -> dict:
    """Append one normalized ledger line for a bench emit; returns the line
    that was written. Failures never propagate into the bench (the stdout
    record is still the result)."""
    line = {
        "ts": round(time.time() if ts is None else ts, 3),
        "git_rev": git_rev(repo_dir),
        "record": rec,
    }
    try:
        with open(path, "a") as f:
            f.write(json.dumps(line, separators=(",", ":"), default=str))
            f.write("\n")
    except OSError:
        pass
    return line


def load_record(path: str) -> dict:
    """A bench record from a bench JSON file (single-line or pretty-
    printed) OR a ledger JSONL, whatever the extension says: the whole
    text is tried as one JSON document first, and a multi-line parse
    failure falls back to the LAST line (the ledger contract — the
    newest run wins)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        rec = json.loads(text)
    except ValueError:
        rec = json.loads(text.splitlines()[-1])
    return rec.get("record", rec)


def _direction(key: str) -> str:
    low = key.lower()
    if any(t in low for t in _HIGHER):
        return "higher"
    if any(low.endswith(t) or t in low for t in _LOWER):
        return "lower"
    return "info"


def _floor(key: str, floors: dict) -> float:
    low = key.lower()
    if any(t in low for t in ("tok_s", "tok/s")):
        return floors.get("tok_s", DEFAULT_FLOORS["tok_s"])
    if any(t in low for t in ("mfu", "util", "hit_rate", "vs_baseline",
                              "goodput")):
        return floors.get("ratio", DEFAULT_FLOORS["ratio"])
    if any(t in low for t in ("retrace", "count")):
        return floors.get("count", DEFAULT_FLOORS["count"])
    if any(low.endswith(t) or t in low for t in ("_s", "_ms", "seconds",
                                                 "compile", "ttft")):
        return floors.get("seconds", DEFAULT_FLOORS["seconds"])
    return floors.get("default", DEFAULT_FLOORS["default"])


def diff_records(
    old: dict, new: dict, *, pct: float = 0.10, floors: dict | None = None,
) -> dict:
    """Compare two bench records key by key.

    Returns ``{regressions, improvements, unchanged, info, missing}`` —
    each entry ``{key, old, new, delta_pct, direction}``. A key regresses
    when it moves against its direction by more than ``pct`` relative AND
    more than its class's absolute floor.
    """
    floors = {**DEFAULT_FLOORS, **(floors or {})}
    a, b = flatten_numeric(old), flatten_numeric(new)
    out = {
        "regressions": [], "improvements": [], "unchanged": [],
        "info": [], "missing": [],
    }
    for key in sorted(set(a) | set(b)):
        if key not in a or key not in b:
            out["missing"].append({
                "key": key, "old": a.get(key), "new": b.get(key),
            })
            continue
        ov, nv = a[key], b[key]
        delta = nv - ov
        rel = abs(delta) / abs(ov) if ov else (0.0 if not delta else 1.0)
        direction = _direction(key)
        entry = {
            "key": key, "old": ov, "new": nv,
            "delta_pct": round(rel * 100.0 * (1 if delta >= 0 else -1), 2),
            "direction": direction,
        }
        if direction == "info":
            out["info"].append(entry)
            continue
        worse = delta < 0 if direction == "higher" else delta > 0
        significant = rel > pct and abs(delta) > _floor(key, floors)
        if not significant:
            out["unchanged"].append(entry)
        elif worse:
            out["regressions"].append(entry)
        else:
            out["improvements"].append(entry)
    return out


def render_diff(diff: dict, *, pct: float = 0.10) -> str:
    """Terminal rendering for ``cake-tpu benchdiff``."""
    lines = [
        f"benchdiff (threshold {pct * 100:.0f}% + per-class floors): "
        f"{len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s), "
        f"{len(diff['unchanged'])} within noise, "
        f"{len(diff['missing'])} key(s) only on one side"
    ]

    def block(title, entries, mark):
        if not entries:
            return
        lines.append("")
        lines.append(title)
        for e in entries:
            lines.append(
                f"  {mark} {e['key']:44} {e['old']:>12.3f} -> "
                f"{e['new']:>12.3f}  ({e['delta_pct']:+.1f}%)"
            )

    block("REGRESSIONS", diff["regressions"], "!")
    block("improvements", diff["improvements"], "+")
    if diff["missing"]:
        lines.append("")
        lines.append("only on one side:")
        for e in diff["missing"][:20]:
            lines.append(f"  ? {e['key']} (old={e['old']}, new={e['new']})")
    return "\n".join(lines)
