"""Canonical per-request completion record (the traffic observatory's log).

Every request the engine terminates — any stream finish, a queued
cancel/expire, a stranded joiner, and the two admission refusals (quota
429 / shed 503) — lands here as ONE flat record whose field schema is
pinned in ``obs/taxonomy.py`` (``REQUEST_LOG_FIELDS``): tenant, priority,
token counts, the arrival/queue/TTFT/TPOT timing ladder, finish reason,
SLO verdict, the critical-path phase digest, the scheduler's decision
causes, and the routed node. Three surfaces share the one record:

  * a bounded in-memory ring, served at ``GET /requests`` (filterable by
    tenant / finish / since-cursor) and rendered by ``cake-tpu requests``;
  * an optional JSONL sink (``--request-log PATH``) — the durable copy;
  * the replay trace: ``python -m cake_tpu.loadgen --replay log.jsonl``
    re-issues the recorded traffic preserving inter-arrival gaps,
    tenants, and lengths (cake_tpu/loadgen/replay.py).

Schema drift is refused twice: ``record()`` raises on a key outside the
registry, and the ``requestlog-field-drift`` lint rule flags the write
site statically (analysis/rules/obs.py). Stdlib only — the lint engine
and the loadgen client import this module with no jax present.
"""

from __future__ import annotations

import collections
import json
import threading
import time

from cake_tpu.obs.taxonomy import (
    REQUEST_LOG_FIELDS,
    REQUEST_OUTCOMES,
    REQUEST_SLO_VERDICTS,
)

_FIELD_SET = frozenset(REQUEST_LOG_FIELDS)
_CALLER_REQUIRED = ("request_id", "tenant", "finish_reason")


class RequestLog:
    """Bounded ring + optional JSONL sink of request completion records."""

    def __init__(self, keep: int = 2048, time_fn=time.time):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self._ring: collections.deque = collections.deque(maxlen=keep)
        self._lock = threading.Lock()
        self._time = time_fn
        self._seq = 0
        self._jsonl_path: str | None = None

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    @property
    def last_seq(self) -> int:
        """Highest sequence number ever stamped (0 = nothing recorded):
        the ``since`` cursor for tail/follow consumers."""
        with self._lock:
            return self._seq

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def attach_jsonl(self, path: str | None) -> None:
        """Stream every future record to ``path`` as one JSON line (append
        mode — restarts extend the trace). None detaches (tests)."""
        with self._lock:
            self._jsonl_path = path or None

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0

    def record(self, **fields) -> dict:
        """Append one completion record. Keys are validated against the
        ``REQUEST_LOG_FIELDS`` registry (obs/taxonomy.py) — an unknown
        field name raises, so the schema cannot drift silently; the
        ``requestlog-field-drift`` lint rule flags the same statically."""
        bad = set(fields) - _FIELD_SET
        if bad:
            raise ValueError(
                f"request-log field(s) {sorted(bad)} not in the "
                "obs/taxonomy.py REQUEST_LOG_FIELDS registry"
            )
        if "seq" in fields:
            raise ValueError("seq is stamped by the log, not callers")
        missing = [k for k in _CALLER_REQUIRED if not fields.get(k)]
        if missing:
            raise ValueError(f"request record missing {missing}")
        finish = fields["finish_reason"]
        if finish not in REQUEST_OUTCOMES:
            raise ValueError(
                f"finish_reason {finish!r} not in REQUEST_OUTCOMES"
            )
        verdict = fields.get("slo", "none")
        if verdict not in REQUEST_SLO_VERDICTS:
            raise ValueError(f"slo verdict {verdict!r} not in registry")
        rec = dict(fields)
        rec.setdefault("slo", "none")
        rec.setdefault("t_wall", round(self._time(), 3))
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            path = self._jsonl_path
        if path is not None:
            # Outside the lock (the FlightRecorder idiom): a slow disk must
            # not serialize finishing streams, and single-line O_APPEND
            # writes from multiple threads interleave whole lines on POSIX
            # so the trace stays parseable.
            try:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            except OSError:
                # A full disk must never take a finishing stream down; the
                # in-memory ring stays authoritative.
                with self._lock:
                    self._jsonl_path = None
        return rec

    def snapshot(
        self,
        tenant: str | None = None,
        finish: str | None = None,
        since: int | None = None,
        limit: int = 0,
    ) -> list[dict]:
        """Chronological copy of the ring, optionally filtered by tenant,
        finish_reason, and ``seq > since``; ``limit`` keeps the NEWEST N
        matches (0 = all)."""
        with self._lock:
            recs = list(self._ring)
        if tenant is not None:
            recs = [r for r in recs if r.get("tenant") == tenant]
        if finish is not None:
            recs = [r for r in recs if r.get("finish_reason") == finish]
        if since is not None:
            recs = [r for r in recs if r.get("seq", 0) > since]
        if limit > 0:
            recs = recs[-limit:]
        return recs

    def stats(self) -> dict:
        with self._lock:
            return {
                "count": len(self._ring),
                "capacity": self.capacity,
                "last_seq": self._seq,
                "jsonl": self._jsonl_path,
            }


def load_trace(path: str) -> list[dict]:
    """Read a ``--request-log`` JSONL capture back as records, oldest
    first by wall time — the loadgen replay input. Malformed lines are
    skipped (a crash mid-write leaves at most one), records missing the
    replay-critical fields are dropped."""
    records: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if not rec.get("request_id") or "t_wall" not in rec:
                continue
            records.append(rec)
    records.sort(key=lambda r: (r.get("t_wall", 0.0), r.get("seq", 0)))
    return records
