"""Shared observability name registries (the ONE source of truth).

Three subsystems classify engine time and tokens — the critical-path
explainer (obs/critpath.py), the goodput/efficiency ledger
(obs/efficiency.py), and the scheduler decision audit — and all of their
names live HERE, as plain tuples, so a bucket renamed in one place cannot
silently diverge from the dashboards, tests, and lint that iterate the
taxonomy elsewhere. The ``taxonomy-drift`` lint rule
(analysis/rules/obs.py) enforces it: a string-literal bucket/phase name
anywhere in the tree must be a member of the registry below.

Pure constants, stdlib only: the lint engine imports this module from a
linter process with no jax, and the smoke drivers import it before any
backend exists — keep it dependency-free.
"""

from __future__ import annotations

# Per-request latency phases (obs/critpath.py; pinned by
# tests/test_critpath.py). Canonical rendering order.
PHASES = (
    "queue", "admission", "prefix_fork", "prefill", "decode",
    "spec_accepted", "spec_wasted", "convoy", "stall", "failover",
    "restore", "wire", "host", "other",
)

# Device-time buckets (obs/efficiency.py): every second between the
# engine's first and last backend dispatch lands in exactly one bucket,
# so the buckets always sum to the measured device wall.
#
#   * ``prefill``         — positions computed for a live lane's own
#     prompt (epoch-start, suffix, or join prefill).
#   * ``decode``          — decode-chunk positions a live stream consumed.
#   * ``spec_accepted``   — verify-round positions accepted into a stream.
#   * ``spec_wasted``     — verify-round positions computed but rejected
#     (drafts past the acceptance point, co-batched shape).
#   * ``pad``             — positions computed for prompt padding or
#     dead/dummy lanes (the lockstep width tax).
#   * ``convoy``          — decode positions computed for a live lane past
#     its own need (unconsumed chunk tails: EOS/budget mid-chunk).
#   * ``stall``           — dispatch wall abandoned by the stuck-epoch
#     watchdog (bounded by ``epoch_stall_s`` per stall).
#   * ``failover``        — live-stream migration re-prefills (redone work
#     a worker death cost the device).
#   * ``restore_prefill`` — a preempted lane's re-attach prefill (redone
#     work its spill cost; the price of continuous-mode preemption).
#   * ``host_gap``        — wall time between consecutive dispatches when
#     the device sat idle (scheduler bookkeeping, admission-window sleeps,
#     sampling readback glue).
BUCKETS = (
    "prefill", "decode", "spec_accepted", "spec_wasted", "pad", "convoy",
    "stall", "failover", "restore_prefill", "host_gap",
)

# The buckets that count as USEFUL device time: positions whose output a
# stream actually kept. goodput_frac = sum(GOODPUT_BUCKETS) / wall.
GOODPUT_BUCKETS = ("prefill", "decode", "spec_accepted")

# Generated-token classes (obs/efficiency.py): every emitted token,
# classed at stream finish. ``completed`` (stop/length finishes) is
# goodput; the rest is work the device did for output nobody kept.
TOKEN_CLASSES = ("completed", "cancelled", "deadline", "error")

# Scheduler decision-audit actions (what the scheduler did to a request).
DECISION_ACTIONS = (
    "admit", "join", "defer", "preempt", "spill", "restore", "shed",
    "expire", "budget",
)

# Structured causes for those actions (WHY): the bounded vocabulary
# ``cake-tpu explain`` renders, and the label set of
# cake_sched_decisions_total.
DECISION_CAUSES = (
    "fair_order",        # taken in fair-queue (DRR) order
    "step_budget",       # over this step's prefill grant
    "page_pressure",     # pool could not fit the pages needed
    "knob_incompatible", # sampling knobs differ from the running group
    "cache_group",       # cache-aware ordering deferred (radix group)
    "fairness_skip",     # per-tenant FIFO / epoch-bounding stop
    "capacity",          # segment too short / prompt too tall to attach
    "queue_depth",       # shed: queue-depth gate
    "deadline_doomed",   # shed: estimated wait already exceeds deadline
    "deadline_expired",  # request passed its deadline (queued or running)
    "slo_feedback",      # step-budget grant scaled by SLO burn / slack
    "priority",          # preemption victim choice (lowest class spills)
)
