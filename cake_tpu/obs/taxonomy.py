"""Shared observability name registries (the ONE source of truth).

Three subsystems classify engine time and tokens — the critical-path
explainer (obs/critpath.py), the goodput/efficiency ledger
(obs/efficiency.py), and the scheduler decision audit — and all of their
names live HERE, as plain tuples, so a bucket renamed in one place cannot
silently diverge from the dashboards, tests, and lint that iterate the
taxonomy elsewhere. The ``taxonomy-drift`` lint rule
(analysis/rules/obs.py) enforces it: a string-literal bucket/phase name
anywhere in the tree must be a member of the registry below.

Pure constants, stdlib only: the lint engine imports this module from a
linter process with no jax, and the smoke drivers import it before any
backend exists — keep it dependency-free.
"""

from __future__ import annotations

# Per-request latency phases (obs/critpath.py; pinned by
# tests/test_critpath.py). Canonical rendering order.
PHASES = (
    "queue", "admission", "prefix_fork", "prefill", "decode",
    "spec_accepted", "spec_wasted", "convoy", "stall", "failover",
    "restore", "wire", "host", "other",
)

# Device-time buckets (obs/efficiency.py): every second between the
# engine's first and last backend dispatch lands in exactly one bucket,
# so the buckets always sum to the measured device wall.
#
#   * ``prefill``         — positions computed for a live lane's own
#     prompt (epoch-start, suffix, or join prefill).
#   * ``decode``          — decode-chunk positions a live stream consumed.
#   * ``spec_accepted``   — verify-round positions accepted into a stream.
#   * ``spec_wasted``     — verify-round positions computed but rejected
#     (drafts past the acceptance point, co-batched shape).
#   * ``pad``             — positions computed for prompt padding or
#     dead/dummy lanes (the lockstep width tax).
#   * ``convoy``          — decode positions computed for a live lane past
#     its own need (unconsumed chunk tails: EOS/budget mid-chunk).
#   * ``stall``           — dispatch wall abandoned by the stuck-epoch
#     watchdog (bounded by ``epoch_stall_s`` per stall).
#   * ``failover``        — live-stream migration re-prefills (redone work
#     a worker death cost the device).
#   * ``restore_prefill`` — a preempted lane's re-attach prefill (redone
#     work its spill cost; the price of continuous-mode preemption).
#   * ``host_gap``        — wall time between consecutive dispatches when
#     the device sat idle (scheduler bookkeeping, admission-window sleeps,
#     sampling readback glue).
BUCKETS = (
    "prefill", "decode", "spec_accepted", "spec_wasted", "pad", "convoy",
    "stall", "failover", "restore_prefill", "host_gap",
)

# The buckets that count as USEFUL device time: positions whose output a
# stream actually kept. goodput_frac = sum(GOODPUT_BUCKETS) / wall.
GOODPUT_BUCKETS = ("prefill", "decode", "spec_accepted")

# Generated-token classes (obs/efficiency.py): every emitted token,
# classed at stream finish. ``completed`` (stop/length finishes) is
# goodput; the rest is work the device did for output nobody kept.
TOKEN_CLASSES = ("completed", "cancelled", "deadline", "error")

# Scheduler decision-audit actions (what the scheduler did to a request).
DECISION_ACTIONS = (
    "admit", "join", "defer", "preempt", "spill", "restore", "shed",
    "expire", "budget",
)

# Structured causes for those actions (WHY): the bounded vocabulary
# ``cake-tpu explain`` renders, and the label set of
# cake_sched_decisions_total.
DECISION_CAUSES = (
    "fair_order",        # taken in fair-queue (DRR) order
    "step_budget",       # over this step's prefill grant
    "page_pressure",     # pool could not fit the pages needed
    "knob_incompatible", # sampling knobs differ from the running group
    "cache_group",       # cache-aware ordering deferred (radix group)
    "fairness_skip",     # per-tenant FIFO / epoch-bounding stop
    "capacity",          # segment too short / prompt too tall to attach
    "queue_depth",       # shed: queue-depth gate
    "deadline_doomed",   # shed: estimated wait already exceeds deadline
    "deadline_expired",  # request passed its deadline (queued or running)
    "slo_feedback",      # step-budget grant scaled by SLO burn / slack
    "priority",          # preemption victim choice (lowest class spills)
)

# Canonical per-request completion record (obs/requestlog.py): the ONE
# field schema of the request log — the bounded ring behind GET /requests,
# the --request-log JSONL sink, and the loadgen replay trace format are
# all this tuple. A record written with any other key raises at runtime
# (RequestLog.record) and is flagged statically by the
# ``requestlog-field-drift`` lint rule (analysis/rules/obs.py) — the same
# accounting-invariant class as ``taxonomy-drift``. ``seq`` is stamped by
# the log itself (the /requests?since= cursor), never by callers.
REQUEST_LOG_FIELDS = (
    "seq",                # monotone record number (stamped by RequestLog)
    "t_wall",             # arrival wall-clock, unix seconds (replay gaps)
    "request_id",
    "tenant",
    "priority",           # 0 low / 1 normal / 2 high
    "prompt_tokens",
    "max_tokens",
    "completion_tokens",
    "queue_s",            # submit -> admission (0 for refusals)
    "admit_s",            # tokenize + quota + shed gate wall
    "ttft_s",             # submit -> first token (None: none emitted)
    "tpot_s",             # mean inter-token gap (None under 2 tokens)
    "wall_s",             # admission slice + submit -> close
    "finish_reason",      # REQUEST_OUTCOMES member
    "slo",                # REQUEST_SLO_VERDICTS member
    "phases",             # critpath digest: nonzero PHASES -> seconds
    "decisions",          # scheduler audit, compact "action:cause" list
    "node",               # routed backend node(s) serving the request
    "deadline_s",         # requested end-to-end deadline (None = none)
)

# Terminal outcomes a request record may carry: the stream finish taxonomy
# (runtime/serving.py StreamHandle.finish_reason) plus the two admission
# refusals — ``quota`` (HTTP 429, the caller's budget) and ``shed``
# (HTTP 503, server saturation) — so refused traffic is part of the
# replayable trace, not a hole in it.
REQUEST_OUTCOMES = (
    "stop", "length", "error", "cancelled", "deadline", "quota", "shed",
)

# Per-record SLO verdict (obs/requestlog.py derives it at finish from the
# declared objectives): ``none`` = nothing declared and no deadline to
# judge against; ``refused`` = never admitted (quota/shed).
REQUEST_SLO_VERDICTS = (
    "ok", "ttft_miss", "deadline_miss", "refused", "none",
)
